"""Training-job CRD types: TPUJob plus TFJob/PyTorchJob/MPIJob compatibility.

The north star (BASELINE.json) is that the training-job reconcilers gain a
``TPU`` replica type: a replica spec that names a slice topology instead of a
pod count, is gang-scheduled all-or-nothing, and gets the jax.distributed
topology contract injected instead of TF_CONFIG / MASTER_ADDR / hostfiles.

We therefore model ONE job shape with four API kinds:

- ``TPUJob``     (tpu.kubeflow.org/v1alpha1) — the native kind.
- ``TFJob``      (kubeflow.org/v1beta2)      — reference CRD
                 (kubeflow/tf-training/tf-job-operator.libsonnet:52-95), with
                 replica types Chief/Master/Worker/PS/Evaluator + TPU.
- ``PyTorchJob`` (kubeflow.org/v1beta2)      — Master/Worker + TPU
                 (kubeflow/pytorch-job/prototypes/pytorch-job.jsonnet:16-85).
- ``MPIJob``     (kubeflow.org/v1alpha1)     — oneOf{gpus, replicas} becomes
                 oneOf{tpuTopology, replicas}
                 (kubeflow/mpi-job/mpi-operator.libsonnet:27-77; SURVEY §2.6).

All four are reconciled by the same operator (controllers/tpujob.py); the only
kind-specific behavior is replica-type vocabulary and legacy env rendering
(TF_CONFIG for TFJob CPU replicas, MASTER_ADDR for PyTorchJob), so Katib and
kubebench templates written against the reference kinds run unmodified.
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields
from typing import Any, Optional

from . import k8s
from .topology import SliceTopology, parse_topology

TPU_API_VERSION = "tpu.kubeflow.org/v1alpha1"
KF_API_VERSION_V1BETA2 = "kubeflow.org/v1beta2"
KF_API_VERSION_V1ALPHA1 = "kubeflow.org/v1alpha1"

JOB_KINDS = ("TPUJob", "TFJob", "PyTorchJob", "MPIJob",
             "ChainerJob", "MXJob", "PaddleJob")

# How the worker lays the optimizer update out across data-parallel
# replicas (spec.weightUpdate → KFTPU_WEIGHT_UPDATE → TrainStepBuilder;
# runtime/recipe.py re-exports this vocabulary for the step engine):
# "replicated" = every chip reads/writes the full optimizer state after a
# gradient all-reduce; "sharded" = ZeRO-2 (reduce-scatter gradients, each
# replica updates a 1/N shard of the state, all-gather the new params).
# Same losses/params, ~1/N the optimizer HBM traffic per chip (PERF.md).
# Defined HERE, not in runtime/: admission-time validation must stay
# importable without pulling jax/optax into the operator layer.
WEIGHT_UPDATE_MODES = ("replicated", "sharded")


def validate_weight_update(mode: str) -> str:
    if mode not in WEIGHT_UPDATE_MODES:
        raise ValueError(
            f"weight_update {mode!r} not one of {WEIGHT_UPDATE_MODES}")
    return mode


# Kernel-tier vocabularies (spec.kernels → KFTPU_KERNEL_* → the recipe
# fingerprint and the AOT step key). Each names an optimized execution
# path for one segment of the compute: which attention implementation
# transformer workloads run, whether the (shard-local) optimizer update
# runs as the fused Pallas kernel or the stock optax chain, and whether
# a served model is int8-quantized behind the parity gate. Defined HERE,
# jax-free, like WEIGHT_UPDATE_MODES: admission must not import the
# runtime. docs/training.md "Kernel tier".
ATTENTION_KERNELS = ("einsum", "flash", "ring")
OPTIMIZER_KERNELS = ("stock", "fused_adam")
SERVING_KERNELS = ("stock", "int8")


@dataclass
class InputSpec:
    """Input-pipeline knobs (``spec.input``): how the worker feeds the
    chips. Each field is plumbed the full operator path — parsed here at
    admission, rendered by controllers/tpujob.py as the env named in its
    metadata, consumed by runtime/worker.py via the CLI flag named there
    (tests/test_lint.py enforces every layer). ``None`` = unset, worker
    default. Defined HERE, jax-free, like WEIGHT_UPDATE_MODES: admission
    must not import the runtime."""

    # decode+augment worker processes feeding the shared-memory input
    # ring (data/mp_augment.py); 0 = the in-process prefetch thread
    workers: Optional[int] = field(default=None, metadata={
        "spec_field": "workers", "env": "KFTPU_INPUT_WORKERS",
        "cli": "--input-workers"})
    # device batches staged ahead of the step by async device_put
    # (data/device_prefetch.py); 0 = place on the critical path
    device_prefetch: Optional[int] = field(default=None, metadata={
        "spec_field": "devicePrefetch", "env": "KFTPU_DEVICE_PREFETCH",
        "cli": "--device-prefetch"})

    def validate(self) -> None:
        for f in fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise ValueError(
                    f"input.{f.metadata['spec_field']} must be a "
                    f"non-negative integer, got {v!r}")

    def to_dict(self) -> dict:
        return {f.metadata["spec_field"]: getattr(self, f.name)
                for f in fields(self) if getattr(self, f.name) is not None}

    def to_env(self) -> dict[str, str]:
        """The controller-rendered worker env for every SET knob."""
        return {f.metadata["env"]: str(getattr(self, f.name))
                for f in fields(self) if getattr(self, f.name) is not None}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "InputSpec":
        if d is not None and not isinstance(d, dict):
            # a YAML list/scalar typo must get the same clean
            # admission-time rejection as a bad knob value
            raise ValueError(
                f"spec.input must be a mapping of input-pipeline knobs, "
                f"got {type(d).__name__}: {d!r}")
        d = dict(d or {})
        by_spec = {f.metadata["spec_field"]: f.name for f in fields(cls)}
        unknown = set(d) - set(by_spec)
        if unknown:
            raise ValueError(
                f"unknown input-pipeline knobs {sorted(unknown)}; "
                f"valid: {sorted(by_spec)}")
        spec = cls(**{by_spec[k]: v for k, v in d.items()})
        spec.validate()
        return spec

@dataclass
class ObsSpec:
    """Observability knobs (``spec.observability``): where this job's
    workers stream trace spans and whether they expose their own
    ``/metrics``. Plumbed the full operator path like InputSpec — parsed
    here at admission, rendered by controllers/tpujob.py as the env
    named in each field's metadata, consumed by runtime/worker.py via
    the CLI flag named there (tests/test_lint.py enforces every layer).
    The job's ``trace_id`` is NOT a spec field: it is minted by the
    control plane (observability.kubeflow.org/trace-id annotation) and
    rendered as KFTPU_TRACE_ID alongside these. ``None`` = unset, obs
    off. Defined HERE, jax-free: admission must not import the
    runtime."""

    # JSONL sink for trace spans (obs/trace.py SpanWriter): the worker
    # appends window/checkpoint/profile spans the control plane's
    # queued/bound/running events stitch into one timeline
    span_path: Optional[str] = field(default=None, metadata={
        "spec_field": "spanPath", "env": "KFTPU_SPAN_PATH",
        "cli": "--span-path"})
    # port for the worker's own /metrics exposition (obs/http.py);
    # 0/unset = no worker scrape surface
    metrics_port: Optional[int] = field(default=None, metadata={
        "spec_field": "metricsPort", "env": "KFTPU_OBS_METRICS_PORT",
        "cli": "--obs-metrics-port"})

    def validate(self) -> None:
        if self.span_path is not None and \
                not isinstance(self.span_path, str):
            raise ValueError(
                f"observability.spanPath must be a string, got "
                f"{self.span_path!r}")
        p = self.metrics_port
        if p is not None and (not isinstance(p, int) or
                              isinstance(p, bool) or
                              p < 0 or p > 65535):
            raise ValueError(
                f"observability.metricsPort must be a port number, got "
                f"{p!r}")

    def to_dict(self) -> dict:
        return {f.metadata["spec_field"]: getattr(self, f.name)
                for f in fields(self) if getattr(self, f.name) is not None}

    def to_env(self) -> dict[str, str]:
        """The controller-rendered worker env for every SET knob."""
        return {f.metadata["env"]: str(getattr(self, f.name))
                for f in fields(self) if getattr(self, f.name) is not None}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ObsSpec":
        if d is not None and not isinstance(d, dict):
            raise ValueError(
                f"spec.observability must be a mapping of observability "
                f"knobs, got {type(d).__name__}: {d!r}")
        d = dict(d or {})
        by_spec = {f.metadata["spec_field"]: f.name for f in fields(cls)}
        unknown = set(d) - set(by_spec)
        if unknown:
            raise ValueError(
                f"unknown observability knobs {sorted(unknown)}; "
                f"valid: {sorted(by_spec)}")
        spec = cls(**{by_spec[k]: v for k, v in d.items()})
        spec.validate()
        return spec


@dataclass
class WarmStartSpec:
    """Warm-start knobs (``spec.warmStart``): how this job's workers cut
    the startup→first-step cost on every (re)start. Plumbed the full
    operator path like InputSpec — parsed here at admission, rendered by
    controllers/tpujob.py as the env named in each field's metadata,
    consumed by runtime/worker.py via the CLI flag named there
    (tests/test_lint.py enforces every layer). ``None`` = unset, worker
    default. Defined HERE, jax-free: admission must not import the
    runtime. The persistent compile cache is NOT a knob here — it is
    always on when a cache volume exists (spec.compileCacheDir /
    checkpointDir); warmStart adds the AOT executable rung above it
    (docs/operations.md "Warm starts and the compile cache")."""

    # AOT executable export/load (runtime/aot.py): the worker loads a
    # keyed serialized step executable on rebind/resize — no trace, no
    # lower, no XLA — and exports it at first bind; falls back to the
    # compile cache, then a fresh compile
    aot: Optional[bool] = field(default=None, metadata={
        "spec_field": "aot", "env": "KFTPU_AOT", "cli": "--aot"})
    # where the serialized executables live; defaults to
    # <checkpointDir>/.jax-aot-executables (the volume the gang mounts)
    aot_dir: Optional[str] = field(default=None, metadata={
        "spec_field": "aotDir", "env": "KFTPU_AOT_DIR",
        "cli": "--aot-dir"})

    def validate(self) -> None:
        if self.aot is not None and not isinstance(self.aot, bool):
            raise ValueError(
                f"warmStart.aot must be a boolean, got {self.aot!r}")
        if self.aot_dir is not None and \
                not isinstance(self.aot_dir, str):
            raise ValueError(
                f"warmStart.aotDir must be a string, got "
                f"{self.aot_dir!r}")

    def to_dict(self) -> dict:
        return {f.metadata["spec_field"]: getattr(self, f.name)
                for f in fields(self) if getattr(self, f.name) is not None}

    def to_env(self) -> dict[str, str]:
        """The controller-rendered worker env for every SET knob
        (booleans render "1"/"0" — the worker's _env_int contract)."""
        out = {}
        for f in fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            out[f.metadata["env"]] = ("1" if v else "0") \
                if isinstance(v, bool) else str(v)
        return out

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "WarmStartSpec":
        if d is not None and not isinstance(d, dict):
            raise ValueError(
                f"spec.warmStart must be a mapping of warm-start knobs, "
                f"got {type(d).__name__}: {d!r}")
        d = dict(d or {})
        by_spec = {f.metadata["spec_field"]: f.name for f in fields(cls)}
        unknown = set(d) - set(by_spec)
        if unknown:
            raise ValueError(
                f"unknown warm-start knobs {sorted(unknown)}; "
                f"valid: {sorted(by_spec)}")
        spec = cls(**{by_spec[k]: v for k, v in d.items()})
        spec.validate()
        return spec


@dataclass
class MultisliceSpec:
    """Multi-slice execution knobs (``spec.multislice``): how a job
    spanning ``numSlices > 1`` runs across the DCN boundary. Plumbed the
    full operator path like InputSpec — parsed here at admission,
    rendered by controllers/tpujob.py as the env named in each field's
    metadata, consumed by runtime/worker.py via the CLI flag named there
    (tests/test_lint.py enforces every layer). ``None`` = unset, worker
    default (the single-program GSPMD path with DCN-aware sharding
    rules). Defined HERE, jax-free: admission must not import the
    runtime. docs/training.md "Multi-slice training"."""

    # MPMD pipeline-over-DCN (parallel/multislice.py): one program PER
    # SLICE — pipeline stages with explicit activation/grad send-recv
    # over DCN and a microbatched 1F1B-style schedule — instead of one
    # SPMD program resharding across the slow link
    pipeline: Optional[bool] = field(default=None, metadata={
        "spec_field": "pipeline", "env": "KFTPU_MULTISLICE_PIPELINE",
        "cli": "--multislice-pipeline"})
    # microbatches per step for the MPMD schedule; the pipeline bubble
    # fraction is (S-1)/(M+S-1), so M >= 4*S keeps it under 20%
    microbatches: Optional[int] = field(default=None, metadata={
        "spec_field": "microbatches",
        "env": "KFTPU_MULTISLICE_MICROBATCHES",
        "cli": "--multislice-microbatches"})

    @property
    def pipeline_enabled(self) -> bool:
        return bool(self.pipeline)

    def validate(self) -> None:
        if self.pipeline is not None and \
                not isinstance(self.pipeline, bool):
            raise ValueError(
                f"multislice.pipeline must be a boolean, got "
                f"{self.pipeline!r}")
        m = self.microbatches
        if m is not None and (not isinstance(m, int) or
                              isinstance(m, bool) or m < 1):
            raise ValueError(
                f"multislice.microbatches must be a positive integer, "
                f"got {m!r}")
        if m is not None and not self.pipeline:
            # only the MPMD schedule consumes the knob — accepting it
            # without the pipeline would be a silent no-op the user
            # mistakes for a pinned schedule (the fused_routing-
            # without-fused_blocks rule)
            raise ValueError(
                "multislice.microbatches requires multislice.pipeline: "
                "true (only the MPMD schedule consumes it)")

    def to_dict(self) -> dict:
        return {f.metadata["spec_field"]: getattr(self, f.name)
                for f in fields(self) if getattr(self, f.name) is not None}

    def to_env(self) -> dict[str, str]:
        """The controller-rendered worker env for every SET knob
        (booleans render "1"/"0" — the worker's _env_int contract)."""
        out = {}
        for f in fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            out[f.metadata["env"]] = ("1" if v else "0") \
                if isinstance(v, bool) else str(v)
        return out

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "MultisliceSpec":
        if d is not None and not isinstance(d, dict):
            raise ValueError(
                f"spec.multislice must be a mapping of multi-slice "
                f"knobs, got {type(d).__name__}: {d!r}")
        d = dict(d or {})
        by_spec = {f.metadata["spec_field"]: f.name for f in fields(cls)}
        unknown = set(d) - set(by_spec)
        if unknown:
            raise ValueError(
                f"unknown multislice knobs {sorted(unknown)}; "
                f"valid: {sorted(by_spec)}")
        spec = cls(**{by_spec[k]: v for k, v in d.items()})
        spec.validate()
        return spec


@dataclass
class KernelSpec:
    """Kernel-tier knobs (``spec.kernels``): which optimized execution
    path each compute segment runs (ISSUE 16 "Raw-speed kernel tier").
    Plumbed the full operator path like InputSpec — parsed here at
    admission, rendered by controllers/tpujob.py as the env named in
    each field's metadata, consumed by runtime/worker.py via the CLI
    flag named there (tests/test_lint.py enforces every layer).
    ``None`` = unset, worker default (stock/einsum — the tier is opt-in).
    Every set knob is baked into ``recipe_fingerprint`` and the AOT
    ``step_key`` so a tier flip can never alias a cached executable.
    Defined HERE, jax-free: admission must not import the runtime."""

    # attention implementation for transformer workloads: "einsum"
    # (stock XLA), "flash" (ops/flash_attention.py Pallas kernel — falls
    # back to einsum on unaligned shapes, visibly:
    # kftpu_kernel_fallback_total), or "ring" (sequence-parallel)
    attention: Optional[str] = field(default=None, metadata={
        "spec_field": "attention", "env": "KFTPU_KERNEL_ATTENTION",
        "cli": "--kernel-attention"})
    # optimizer update: "stock" (optax chain) or "fused_adam"
    # (ops/fused_adam.py — one Pallas kernel for decay+moments+step over
    # the shard-local slab; requires --optimizer adam)
    optimizer: Optional[str] = field(default=None, metadata={
        "spec_field": "optimizer", "env": "KFTPU_KERNEL_OPTIMIZER",
        "cli": "--kernel-optimizer"})
    # serving path: "stock" (f32 weights) or "int8" (per-channel absmax
    # quantized matmul weights behind the accuracy parity gate —
    # serving/servable.py)
    serving: Optional[str] = field(default=None, metadata={
        "spec_field": "serving", "env": "KFTPU_KERNEL_SERVING",
        "cli": "--kernel-serving"})

    def validate(self) -> None:
        for name, value, vocab in (
                ("attention", self.attention, ATTENTION_KERNELS),
                ("optimizer", self.optimizer, OPTIMIZER_KERNELS),
                ("serving", self.serving, SERVING_KERNELS)):
            if value is not None and value not in vocab:
                raise ValueError(
                    f"kernels.{name} {value!r} not one of {vocab}")

    def to_dict(self) -> dict:
        return {f.metadata["spec_field"]: getattr(self, f.name)
                for f in fields(self) if getattr(self, f.name) is not None}

    def to_env(self) -> dict[str, str]:
        """The controller-rendered worker env for every SET knob."""
        return {f.metadata["env"]: str(getattr(self, f.name))
                for f in fields(self)
                if getattr(self, f.name) is not None}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "KernelSpec":
        if d is not None and not isinstance(d, dict):
            raise ValueError(
                f"spec.kernels must be a mapping of kernel-tier knobs, "
                f"got {type(d).__name__}: {d!r}")
        d = dict(d or {})
        by_spec = {f.metadata["spec_field"]: f.name for f in fields(cls)}
        unknown = set(d) - set(by_spec)
        if unknown:
            raise ValueError(
                f"unknown kernel-tier knobs {sorted(unknown)}; "
                f"valid: {sorted(by_spec)}")
        spec = cls(**{by_spec[k]: v for k, v in d.items()})
        spec.validate()
        return spec


@dataclass
class IntegritySpec:
    """Numeric-integrity sentinel knobs (``spec.integrity``): in-step
    NaN/Inf and loss-spike detection with last-known-good rollback
    (ISSUE 17, runtime/sentinel.py). Plumbed the full operator path like
    InputSpec — parsed here at admission, rendered by
    controllers/tpujob.py as the env named in each field's metadata,
    consumed by runtime/worker.py via the CLI flag named there
    (tests/test_lint.py enforces every layer). ``None`` = unset, worker
    default (sentinel OFF). Deliberately EXCLUDED from the recipe
    fingerprint and the AOT step key: the sentinel observes the metrics
    the worker already fetches and changes no math, so flipping it must
    never invalidate a cached executable. Defined HERE, jax-free:
    admission must not import the runtime. docs/operations.md "Numeric
    integrity"."""

    # master switch: NaN/Inf checks on loss / global grad norm, the
    # rolling z-score spike detector, and the cross-replica agreement
    # check (ZeRO-2 path) ride the worker's window drain
    enabled: Optional[bool] = field(default=None, metadata={
        "spec_field": "enabled", "env": "KFTPU_INTEGRITY",
        "cli": "--integrity"})
    # one-sided z-score threshold for the loss-spike detector (EWMA
    # mean/variance); default 8 — the false-positive budget is zero
    spike_z: Optional[float] = field(default=None, metadata={
        "spec_field": "spikeZ", "env": "KFTPU_INTEGRITY_SPIKE_Z",
        "cli": "--integrity-spike-z"})
    # EWMA window (steps) the spike baseline averages over; the detector
    # arms only after the window has filled
    window_steps: Optional[int] = field(default=None, metadata={
        "spec_field": "windowSteps", "env": "KFTPU_INTEGRITY_WINDOW",
        "cli": "--integrity-window"})
    # detection cadence: the worker closes a metrics window at least
    # every this many steps so a trip is caught within the bound
    check_every_steps: Optional[int] = field(default=None, metadata={
        "spec_field": "checkEverySteps",
        "env": "KFTPU_INTEGRITY_CHECK_EVERY",
        "cli": "--integrity-check-every"})

    @property
    def is_enabled(self) -> bool:
        return bool(self.enabled)

    def validate(self) -> None:
        if self.enabled is not None and \
                not isinstance(self.enabled, bool):
            raise ValueError(
                f"integrity.enabled must be a boolean, got "
                f"{self.enabled!r}")
        z = self.spike_z
        if z is not None and (isinstance(z, bool) or
                              not isinstance(z, (int, float)) or z <= 0):
            raise ValueError(
                f"integrity.spikeZ must be a positive number, got {z!r}")
        for name, v, lo in (("windowSteps", self.window_steps, 2),
                            ("checkEverySteps",
                             self.check_every_steps, 1)):
            if v is not None and (not isinstance(v, int) or
                                  isinstance(v, bool) or v < lo):
                raise ValueError(
                    f"integrity.{name} must be an integer >= {lo}, "
                    f"got {v!r}")
        if not self.enabled and (z is not None or
                                 self.window_steps is not None or
                                 self.check_every_steps is not None):
            # only the sentinel consumes the tuning knobs — accepting
            # them without enabled: true would be a silent no-op the
            # user mistakes for armed detection (the
            # multislice.microbatches-without-pipeline rule)
            raise ValueError(
                "integrity.spikeZ/windowSteps/checkEverySteps require "
                "integrity.enabled: true (only the sentinel consumes "
                "them)")

    def to_dict(self) -> dict:
        return {f.metadata["spec_field"]: getattr(self, f.name)
                for f in fields(self) if getattr(self, f.name) is not None}

    def to_env(self) -> dict[str, str]:
        """The controller-rendered worker env for every SET knob
        (booleans render "1"/"0" — the worker's _env_int contract)."""
        out = {}
        for f in fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            out[f.metadata["env"]] = ("1" if v else "0") \
                if isinstance(v, bool) else str(v)
        return out

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "IntegritySpec":
        if d is not None and not isinstance(d, dict):
            raise ValueError(
                f"spec.integrity must be a mapping of integrity-sentinel "
                f"knobs, got {type(d).__name__}: {d!r}")
        d = dict(d or {})
        by_spec = {f.metadata["spec_field"]: f.name for f in fields(cls)}
        unknown = set(d) - set(by_spec)
        if unknown:
            raise ValueError(
                f"unknown integrity knobs {sorted(unknown)}; "
                f"valid: {sorted(by_spec)}")
        spec = cls(**{by_spec[k]: v for k, v in d.items()})
        spec.validate()
        return spec


@dataclass
class SchedulingPolicy:
    """Gang-scheduling knobs (``spec.schedulingPolicy``): how the slice
    scheduler (kubeflow_tpu/scheduler/) queues, places, and — when
    ``preemptible`` — reclaims this job's slices. A job that carries the
    block is SCHEDULER-MANAGED: the operator creates no pods until the
    scheduler writes the slice binding annotation (the job sits in a
    visible ``Queued`` condition instead of half-creating a gang). A job
    without the block keeps the legacy admission==placement path.
    Defined HERE, jax-free, like InputSpec: admission and the scheduler
    process must not import the runtime."""

    # scheduler queue this job submits to ("" = the default queue);
    # quotas are enforced per (queue, namespace) — scheduler/queue.py
    queue: str = ""
    # higher binds first; ties break by submission order (FIFO)
    priority: int = 0
    # a preemptible gang may be reclaimed for a higher-priority job via
    # the graceful path (SIGTERM → forced checkpoint → exit 75) and is
    # RE-QUEUED by the scheduler, not failed
    preemptible: bool = False
    # Elastic gang bounds (minChips/maxChips): either set makes the job
    # ELASTIC — the scheduler may resize the gang's binding at checkpoint
    # boundaries anywhere in [minChips, maxChips] total chips (shrink to
    # survive a lost host or admit a blocked head, grow into idle chips,
    # migrate to defragment). Global batch size stays FIXED across
    # resizes: only the data-parallel replica degree changes, and the
    # checkpoint restore reshapes optimizer state across degrees
    # (runtime/checkpoint.py). None = that bound pins to the nominal
    # spec shape; both None = fixed-shape (the pre-elastic contract).
    min_chips: Optional[int] = None
    max_chips: Optional[int] = None

    ENV_QUEUE = "KFTPU_SCHED_QUEUE"
    ENV_PRIORITY = "KFTPU_SCHED_PRIORITY"
    ENV_PREEMPTIBLE = "KFTPU_SCHED_PREEMPTIBLE"
    ENV_MIN_CHIPS = "KFTPU_SCHED_MIN_CHIPS"
    ENV_MAX_CHIPS = "KFTPU_SCHED_MAX_CHIPS"

    @property
    def elastic(self) -> bool:
        """Whether the scheduler may resize this gang's binding."""
        return self.min_chips is not None or self.max_chips is not None

    def chip_bounds(self, nominal: int) -> tuple[int, int]:
        """The [min, max] total-chip envelope around the spec's nominal
        gang size (an unset bound pins to nominal — the spec shape is
        always inside its own envelope)."""
        return (self.min_chips if self.min_chips is not None else nominal,
                self.max_chips if self.max_chips is not None else nominal)

    def validate(self) -> None:
        if not isinstance(self.queue, str):
            raise ValueError(
                f"schedulingPolicy.queue must be a string, got "
                f"{self.queue!r}")
        if not isinstance(self.priority, int) or \
                isinstance(self.priority, bool):
            raise ValueError(
                f"schedulingPolicy.priority must be an integer, got "
                f"{self.priority!r}")
        if not isinstance(self.preemptible, bool):
            raise ValueError(
                f"schedulingPolicy.preemptible must be a boolean, got "
                f"{self.preemptible!r}")
        for label, v in (("minChips", self.min_chips),
                         ("maxChips", self.max_chips)):
            if v is None:
                continue
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(
                    f"schedulingPolicy.{label} must be a positive "
                    f"integer, got {v!r}")
        if self.min_chips is not None and self.max_chips is not None \
                and self.min_chips > self.max_chips:
            raise ValueError(
                f"schedulingPolicy.minChips ({self.min_chips}) must not "
                f"exceed maxChips ({self.max_chips})")

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"priority": self.priority,
                             "preemptible": self.preemptible}
        if self.queue:
            d["queue"] = self.queue
        if self.min_chips is not None:
            d["minChips"] = self.min_chips
        if self.max_chips is not None:
            d["maxChips"] = self.max_chips
        return d

    def to_env(self) -> dict[str, str]:
        """Rendered into every worker pod: informational for the queue
        name/priority and the elastic bounds, behavioral for preemptible
        (the worker's SIGTERM handler knows a reclaim is a requeue, not
        a failure)."""
        env = {
            self.ENV_QUEUE: self.queue or DEFAULT_QUEUE,
            self.ENV_PRIORITY: str(self.priority),
            self.ENV_PREEMPTIBLE: "1" if self.preemptible else "0",
        }
        if self.min_chips is not None:
            env[self.ENV_MIN_CHIPS] = str(self.min_chips)
        if self.max_chips is not None:
            env[self.ENV_MAX_CHIPS] = str(self.max_chips)
        return env

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["SchedulingPolicy"]:
        """None (absent block) = NOT scheduler-managed — the distinction
        the operator gates pod creation on, so it must survive the
        parse/serialize round trip exactly."""
        if d is None:
            return None
        if not isinstance(d, dict):
            raise ValueError(
                f"spec.schedulingPolicy must be a mapping, got "
                f"{type(d).__name__}: {d!r}")
        known = {"queue", "priority", "preemptible", "minChips", "maxChips"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown schedulingPolicy fields {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        policy = cls(queue=d.get("queue", "") or "",
                     priority=d.get("priority", 0),
                     preemptible=d.get("preemptible", False),
                     min_chips=d.get("minChips"),
                     max_chips=d.get("maxChips"))
        policy.validate()
        return policy


# the queue a schedulingPolicy without an explicit queue submits to
DEFAULT_QUEUE = "default"

# Slice-binding contract between the gang scheduler and the operator
# (scheduler/core.py writes, controllers/tpujob.py consumes): the binding
# annotation carries the JSON placement (per-slice pool + ICI-grid rect,
# scheduler/inventory.py Placement wire format). A scheduler-managed job
# WITHOUT the annotation is queued — the operator creates no pods for it.
BINDING_ANNOTATION = "scheduling.kubeflow.org/binding"
# scheduler-visible state for dashboards/kubectl: queued | bound | preempted
SCHED_STATE_ANNOTATION = "scheduling.kubeflow.org/state"
# human-readable reason a job is still queued (quota, capacity, ...)
SCHED_REASON_ANNOTATION = "scheduling.kubeflow.org/reason"
# times this job's gang was preempted (reclaimed, not failed)
PREEMPTED_COUNT_ANNOTATION = "scheduling.kubeflow.org/preempted-count"
# Elastic-resize event history (scheduler/core.py writes, dashboard
# reads): a JSON list of {"time", "fromChips", "toChips", "reason"}
# records, newest last, capped — the audit trail of every shrink / grow
# / defrag migration the scheduler applied to this gang's binding.
RESIZE_HISTORY_ANNOTATION = "scheduling.kubeflow.org/resize-history"

# Node-health contract between the operator (evidence writer) and the
# scheduler (policy actor) — scheduler/health.py owns the parse/fold
# helpers, BOTH sides consume them (the binding_of pattern: one wire
# contract, no string drift; tests/test_lint.py enforces single
# definition). All three ride on annotations so the two processes
# coordinate through the apiserver only:
#
# - HEALTH_ANNOTATION (on Nodes): exponential-decay failure score, JSON
#   {"score": s, "time": unix, "events": n, "last": kind}. The operator
#   folds runtime failure evidence in (pod crash attributed to the host
#   it ran on, stalled worker, step-time skew); the scheduler decays and
#   reads it each pass.
# - QUARANTINE_ANNOTATION (on Nodes): set by the scheduler when a
#   host's score crosses the threshold (or by a human, reason
#   "manual"), JSON {"reason": r, "score": s, "since": unix, "until":
#   unix|null}. Quarantined hosts are carved out of placeable
#   rectangles (scheduler/inventory.py); expiry + score decay below the
#   release threshold auto-releases (probation), manual quarantines
#   never auto-release.
# - SUSPECT_ANNOTATION (on TPUJobs): the host the operator attributes a
#   gang teardown to (crash loop on one pod, stalled single worker).
#   The scheduler replans the job's binding EXCLUDING the suspect's
#   cells — the gang migrates instead of crash-looping in place — and
#   clears the annotation on the rebind.
HEALTH_ANNOTATION = "kubeflow.org/health"
QUARANTINE_ANNOTATION = "kubeflow.org/quarantine"
SUSPECT_ANNOTATION = "scheduling.kubeflow.org/suspect-host"

# apiVersion per kind (reference CRD groups/versions)
API_VERSIONS = {
    "TPUJob": TPU_API_VERSION,
    "TFJob": KF_API_VERSION_V1BETA2,
    "PyTorchJob": KF_API_VERSION_V1BETA2,
    "MPIJob": KF_API_VERSION_V1ALPHA1,
    "ChainerJob": KF_API_VERSION_V1ALPHA1,
    "MXJob": KF_API_VERSION_V1ALPHA1,
    "PaddleJob": KF_API_VERSION_V1ALPHA1,
}

# replica-spec key inside .spec, per kind (reference CRD field names)
_SPECS_KEY = {
    "TFJob": "tfReplicaSpecs",
    "PyTorchJob": "pytorchReplicaSpecs",
    "TPUJob": "replicaSpecs",
    "MPIJob": "replicaSpecs",
    "ChainerJob": "chainerReplicaSpecs",
    "MXJob": "mxReplicaSpecs",
    "PaddleJob": "paddleReplicaSpecs",
}

# Replica-type vocabulary per kind. "TPU" is valid in every kind — that is the
# whole point of the build. Validation constraints mirror the reference CRD
# schemas (Chief/Master max 1: tf-job-operator.libsonnet:14-46).
REPLICA_TYPES: dict[str, tuple[str, ...]] = {
    "TPUJob": ("TPU", "Coordinator", "Evaluator"),
    "TFJob": ("TPU", "Chief", "Master", "Worker", "PS", "Evaluator"),
    "PyTorchJob": ("TPU", "Master", "Worker"),
    "MPIJob": ("TPU", "Launcher", "Worker"),
    # reference operators: kubeflow/chainer-job/chainer-operator.libsonnet,
    # kubeflow/mxnet-job/mxnet-operator.libsonnet,
    # kubeflow/paddle-job/*.libsonnet
    "ChainerJob": ("TPU", "Master", "Worker"),
    "MXJob": ("TPU", "Scheduler", "Server", "Worker"),
    "PaddleJob": ("TPU", "Pserver", "Trainer"),
}
_MAX_ONE = {"Chief", "Master", "Coordinator", "Launcher", "Scheduler"}

# Condition types, mirroring tf-operator's JobCondition vocabulary.
# Queued is the TPU-native addition: a scheduler-managed job admitted but
# not yet bound to slices (visible in kubectl/dashboard instead of a
# half-created gang).
COND_QUEUED = "Queued"
COND_CREATED = "Created"
COND_RUNNING = "Running"
COND_RESTARTING = "Restarting"
COND_SUCCEEDED = "Succeeded"
COND_FAILED = "Failed"

# Pod phases we consume (fake or real apiserver).
POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"

CLEAN_POD_ALL = "All"
CLEAN_POD_RUNNING = "Running"
CLEAN_POD_NONE = "None"

RESTART_POLICY_NEVER = "Never"
RESTART_POLICY_ON_FAILURE = "OnFailure"
# Gang restart: any worker failure restarts the whole slice (SURVEY §5
# "failure detection": a dead worker kills the gang).
RESTART_POLICY_GANG = "GangOnFailure"

# Worker liveness contract (the stall watchdog, SURVEY §5 hung-not-dead):
# workers annotate their own pod with a JSON {"step": N, "time": unix}
# heartbeat (runtime/metrics.py HeartbeatReporter); the controller restarts
# a gang whose CHIEF heartbeat is staler than runPolicy.stallTimeoutSeconds.
# Defined here, not in runtime/: the controller layer must stay importable
# without pulling jax into the operator process.
# The heartbeat payload MAY also carry "lastLoss"/"lastGradNorm" (the
# last drained window's host floats, stringified so NaN/Inf survive
# strict-JSON consumers): the operator flags a NaN-emitting worker even
# when that worker's own sentinel is disabled — after the same
# freshness clamp the stall watchdog applies (a future-stamped beat
# must not be trusted).
HEARTBEAT_ANNOTATION = "kubeflow.org/worker-heartbeat"

# Numeric-integrity anomaly contract (ISSUE 17; runtime/sentinel.py is
# the worker side, controllers/tpujob.py the operator side):
#
# - ANOMALY_ANNOTATION (on Pods): a worker whose sentinel trips patches
#   its own pod with the AnomalyEvidence JSON {"kind", "step", "value",
#   "lkg", "detail"} BEFORE exiting ANOMALY_EXIT_CODE. The operator's
#   failed-pod branch reads it to route the gang failure down the
#   rollback path instead of the plain restart path.
# - ANOMALY_COUNT_ANNOTATION (on TPUJobs): rollbacks consumed so far;
#   compared against runPolicy.maxAnomalyRollbacks — exhausted → the
#   job Fails with the evidence in the condition.
# - ANOMALY_ROLLBACK_ANNOTATION (on TPUJobs): the ACTIVE rollback, JSON
#   {"lkgStep", "tripStep", "kind", "count", "replay"?}. The controller
#   renders it into the recreated gang as KFTPU_RESUME_STEP (restore
#   the newest intact step <= LKG, not newest overall) and — on the
#   second trip at the same LKG, when "replay" is set — as
#   KFTPU_REPLAY_RANGE (replay bisection over the suspect steps with
#   the suspect host evacuated). Cleared once the chief's heartbeat
#   advances past the trip step.
ANOMALY_ANNOTATION = "kubeflow.org/numeric-anomaly"
ANOMALY_COUNT_ANNOTATION = "kubeflow.org/anomaly-rollback-count"
ANOMALY_ROLLBACK_ANNOTATION = "kubeflow.org/anomaly-rollback"


@dataclass
class ReplicaSpec:
    """One replica group. Either a pod-count replica (CPU roles) or a
    topology replica (the TPU gang)."""

    replica_type: str
    replicas: int = 1
    topology: Optional[SliceTopology] = None   # set iff replica_type == "TPU"
    num_slices: int = 1
    template: dict = field(default_factory=dict)  # corev1.PodTemplateSpec
    restart_policy: str = RESTART_POLICY_GANG

    @property
    def is_tpu(self) -> bool:
        return self.replica_type == "TPU"

    @property
    def pod_count(self) -> int:
        """Pods this replica group schedules (TPU: one pod per host per slice)."""
        if self.is_tpu and self.topology is not None:
            return self.topology.num_hosts * self.num_slices
        return self.replicas

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"replicas": self.replicas,
                             "restartPolicy": self.restart_policy,
                             "template": self.template}
        if self.is_tpu and self.topology is not None:
            d["tpuTopology"] = self.topology.name
            d["numSlices"] = self.num_slices
            d.pop("replicas")
        return d


@dataclass
class RunPolicy:
    """Job-level execution policy (tf-operator RunPolicy analog)."""

    clean_pod_policy: str = CLEAN_POD_RUNNING
    backoff_limit: int = 3                      # gang restarts before Failed
    active_deadline_seconds: Optional[int] = None
    gang_scheduling: bool = True                # mandatory for TPU replicas
    ttl_seconds_after_finished: Optional[int] = None
    # Restart-storm protection: delay between gang restarts grows
    # base * 2^restarts (capped at max), with deterministic jitter, and the
    # next-eligible time is persisted as a job annotation so a controller
    # restart cannot shortcut the wait. 0 = restart immediately (the
    # pre-backoff behavior, and the default).
    restart_backoff_seconds: float = 0.0
    restart_backoff_max_seconds: float = 300.0
    # Stall watchdog: restart a gang whose chief heartbeat annotation
    # (HEARTBEAT_ANNOTATION) is staler than this — hung-but-not-dead
    # workers (wedged collective, dead TPU runtime with a live pod) never
    # produce a Failed phase on their own. None = watchdog off.
    stall_timeout_seconds: Optional[int] = None
    # Anomaly budget: last-known-good rollbacks (a worker exiting
    # ANOMALY_EXIT_CODE with evidence in ANOMALY_ANNOTATION) before the
    # job Fails with the evidence in the condition. Separate from
    # backoffLimit — a rollback is a recovery, not a crash — and
    # tracked in ANOMALY_COUNT_ANNOTATION. docs/operations.md "Numeric
    # integrity".
    max_anomaly_rollbacks: int = 2

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "cleanPodPolicy": self.clean_pod_policy,
            "backoffLimit": self.backoff_limit,
            "gangScheduling": self.gang_scheduling,
        }
        if self.active_deadline_seconds is not None:
            d["activeDeadlineSeconds"] = self.active_deadline_seconds
        if self.ttl_seconds_after_finished is not None:
            d["ttlSecondsAfterFinished"] = self.ttl_seconds_after_finished
        if self.restart_backoff_seconds:
            d["restartBackoffSeconds"] = self.restart_backoff_seconds
            d["restartBackoffMaxSeconds"] = self.restart_backoff_max_seconds
        if self.stall_timeout_seconds is not None:
            d["stallTimeoutSeconds"] = self.stall_timeout_seconds
        if self.max_anomaly_rollbacks != 2:
            d["maxAnomalyRollbacks"] = self.max_anomaly_rollbacks
        return d


@dataclass
class ShardingSpec:
    """Parallelism as job-spec data (SURVEY §2.5 row 5 — absent in the
    reference; first-class here). Axis sizes multiply to the global chip count;
    -1 means "fill with remaining chips" (at most one axis).

    Lowered by the runtime to a jax.sharding.Mesh with axes
    ("data", "fsdp", "expert", "pipeline", "sequence", "tensor") — DCN-major
    ordering so data parallelism rides DCN and tensor parallelism rides the
    innermost ICI dimension.
    """

    data: int = -1        # pure data parallel (DCN-friendly)
    fsdp: int = 1         # data parallel with sharded params (ZeRO-3 analog)
    tensor: int = 1       # megatron-style op sharding (innermost ICI)
    pipeline: int = 1     # pipeline stages
    sequence: int = 1     # sequence/context parallelism (ring attention)
    expert: int = 1       # MoE expert parallelism

    AXES = ("data", "fsdp", "expert", "pipeline", "sequence", "tensor")

    def axis_sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in self.AXES}

    def resolve(self, num_chips: int) -> dict[str, int]:
        sizes = self.axis_sizes()
        wildcards = [a for a, s in sizes.items() if s == -1]
        if len(wildcards) > 1:
            raise ValueError(f"at most one sharding axis may be -1, got {wildcards}")
        fixed = 1
        for a, s in sizes.items():
            if s != -1:
                if s < 1:
                    raise ValueError(f"sharding axis {a} must be >=1 or -1, got {s}")
                fixed *= s
        if wildcards:
            if num_chips % fixed:
                raise ValueError(
                    f"fixed sharding axes product {fixed} does not divide {num_chips} chips"
                )
            sizes[wildcards[0]] = num_chips // fixed
        elif fixed != num_chips:
            raise ValueError(
                f"sharding axes product {fixed} != total chip count {num_chips} "
                "(slice chips x numSlices)"
            )
        return sizes

    def to_dict(self) -> dict:
        return self.axis_sizes()

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ShardingSpec":
        d = d or {}
        unknown = set(d) - set(cls.AXES)
        if unknown:
            raise ValueError(
                f"unknown sharding axes {sorted(unknown)}; valid: {list(cls.AXES)}"
            )
        return cls(**{a: int(d.get(a, -1 if a == "data" else 1)) for a in cls.AXES})


# Mesh axes a multi-slice layout may legally place across the DCN
# boundary: data/fsdp collectives are once-per-step gradient traffic
# (latency-tolerant), pipeline's send/recv is deliberate stage transfer.
# tensor/sequence are PER-LAYER collectives — a layout that puts them
# across slices pays the slow link inside every matmul, and the GSPMD
# partitioner's fallback for the resulting layout conflicts is the
# "involuntary full rematerialization" reshard (MULTICHIP_r05).
DCN_LEGAL_AXES = ("data", "fsdp", "expert", "pipeline")


def dcn_crossing_axes(sizes: dict, num_slices: int,
                      axes: tuple = ShardingSpec.AXES) -> tuple:
    """Mesh axes whose coordinate change crosses a slice boundary.

    DCN-major device order (parallel/mesh.py): flat participant position
    = row-major index over ``axes``; slice id = position // chips_per_
    slice. An axis crosses DCN iff two positions differing only in that
    axis's coordinate land in different slices. Pure arithmetic, jax-free
    — admission (validate() below) rejects layouts the partitioner would
    only fail at compile time, deep inside the gang."""
    if num_slices <= 1:
        return ()
    total = 1
    for a in axes:
        total *= int(sizes.get(a, 1))
    if total % num_slices:
        raise ValueError(
            f"sharding axes product {total} not divisible by "
            f"{num_slices} slices")
    cps = total // num_slices
    # strides of the row-major enumeration (innermost axis stride 1)
    strides = {}
    inner = 1
    for a in reversed(axes):
        strides[a] = inner
        inner *= int(sizes.get(a, 1))
    crossing = []
    for a in axes:
        size = int(sizes.get(a, 1))
        if size <= 1:
            continue
        stride = strides[a]
        # exact: two positions differing only in this axis's coordinate
        # land in different slices. The sweep from any base covers
        # base + c*stride, c in [0, size); bases are every position
        # with this coordinate zero.
        found = False
        for base in range(total):
            if (base // stride) % size:
                continue   # not a coordinate-zero base for this axis
            s0 = base // cps
            if any((base + c * stride) // cps != s0
                   for c in range(1, size)):
                found = True
                break
        if found:
            crossing.append(a)
    return tuple(crossing)


@dataclass
class TrainingJob:
    """Typed view over a training-job manifest (any of the four kinds)."""

    kind: str
    name: str
    namespace: str
    replica_specs: dict[str, ReplicaSpec]
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    sharding: ShardingSpec = field(default_factory=ShardingSpec)
    # checkpoint/resume contract (SURVEY §5: "checkpoint-resume makes
    # slice-level failure domains cheap"): checkpointDir is where workers
    # write (rendered as KFTPU_CHECKPOINT_DIR); resumeFrom is where they
    # restore before the loop (KFTPU_RESUME_FROM) — set by the user for
    # warm starts, or by the operator on gang restart so a restarted gang
    # continues from the last step
    checkpoint_dir: str = ""
    resume_from: str = ""
    # dataset shard dir (rendered as KFTPU_DATA_DIR; the launcher.py
    # --data_dir analog) — workers train on real records when set
    data_dir: str = ""
    # held-out shard dir for the eval pass (KFTPU_EVAL_DATA_DIR)
    eval_data_dir: str = ""
    # TensorBoard event dir (KFTPU_TB_DIR) — the tensorboard component's
    # --logdir; process 0 streams scalar events there
    tensorboard_dir: str = ""
    # persistent XLA compilation cache dir (KFTPU_COMPILE_CACHE_DIR) —
    # warm restarts skip the multi-ten-second first-step compile
    # (BASELINE.md north-star #2). Defaults to a subdir of checkpointDir
    # when that is set (same volume the gang already mounts).
    compile_cache_dir: str = ""
    # input-pipeline knobs (spec.input → KFTPU_INPUT_WORKERS /
    # KFTPU_DEVICE_PREFETCH): augment worker processes and device
    # prefetch depth — the overlapped input pipeline (docs/training.md
    # "Input pipeline")
    input_spec: InputSpec = field(default_factory=InputSpec)
    # observability knobs (spec.observability → KFTPU_SPAN_PATH /
    # KFTPU_OBS_METRICS_PORT): trace-span sink and the worker's own
    # /metrics port (docs/operations.md "Observability")
    obs_spec: ObsSpec = field(default_factory=ObsSpec)
    # warm-start knobs (spec.warmStart → KFTPU_AOT / KFTPU_AOT_DIR):
    # the AOT serialized-executable rung of the warm-start ladder
    # (docs/operations.md "Warm starts and the compile cache")
    warm_start: WarmStartSpec = field(default_factory=WarmStartSpec)
    # multi-slice execution knobs (spec.multislice → KFTPU_MULTISLICE_*):
    # the MPMD pipeline-over-DCN path and its microbatch schedule
    # (docs/training.md "Multi-slice training")
    multislice: MultisliceSpec = field(default_factory=MultisliceSpec)
    # kernel-tier knobs (spec.kernels → KFTPU_KERNEL_*): which optimized
    # execution path each compute segment runs — attention / optimizer /
    # serving (docs/training.md "Kernel tier"); every set knob is baked
    # into the recipe fingerprint and AOT step key
    kernels: KernelSpec = field(default_factory=KernelSpec)
    # numeric-integrity sentinel knobs (spec.integrity →
    # KFTPU_INTEGRITY_*): in-step anomaly detectors + LKG rollback
    # (docs/operations.md "Numeric integrity"); deliberately EXCLUDED
    # from the recipe fingerprint — the sentinel changes no math
    integrity: IntegritySpec = field(default_factory=IntegritySpec)
    # gang-scheduling knobs (spec.schedulingPolicy → the slice
    # scheduler's queue/priority/preemptible; None = not
    # scheduler-managed, the legacy immediate-create path)
    scheduling_policy: Optional[SchedulingPolicy] = None
    # optimizer-update layout across data-parallel replicas (rendered as
    # KFTPU_WEIGHT_UPDATE; WEIGHT_UPDATE_MODES above):
    # "sharded" = ZeRO-2 cross-replica sharded weight update — reduce-
    # scatter grads, 1/N optimizer state per replica, all-gather params
    # (Xu et al.; PERF.md "Weight-update sharding"). "" = worker default
    # (replicated).
    weight_update: str = ""
    raw: dict = field(default_factory=dict)

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_manifest(cls, obj: dict) -> "TrainingJob":
        kind = obj.get("kind", "")
        if kind not in JOB_KINDS:
            raise ValueError(f"not a training-job kind: {kind!r}")
        spec = obj.get("spec", {}) or {}
        # TFJob v1beta2 uses tfReplicaSpecs, PyTorchJob pytorchReplicaSpecs,
        # MPIJob replicas/gpus shorthand, TPUJob replicaSpecs.
        specs_key = _SPECS_KEY[kind]
        raw_specs = spec.get(specs_key) or {}
        if kind == "MPIJob" and not raw_specs:
            raw_specs = cls._mpi_shorthand(spec)
        replica_specs: dict[str, ReplicaSpec] = {}
        for rtype, rs in raw_specs.items():
            rs = rs or {}
            topo_name = rs.get("tpuTopology")
            topo = parse_topology(topo_name) if topo_name else None
            if rtype == "TPU" and topo is None:
                raise ValueError("TPU replica spec requires tpuTopology (e.g. v5e-32)")
            replica_specs[rtype] = ReplicaSpec(
                replica_type=rtype,
                replicas=int(rs.get("replicas", 1)),
                topology=topo,
                num_slices=int(rs.get("numSlices", 1)),
                template=rs.get("template") or {},
                restart_policy=rs.get(
                    "restartPolicy",
                    RESTART_POLICY_GANG if rtype == "TPU" else RESTART_POLICY_ON_FAILURE,
                ),
            )
        rp = spec.get("runPolicy", {}) or {}
        job = cls(
            kind=kind,
            name=k8s.name_of(obj),
            namespace=k8s.namespace_of(obj, "default"),
            replica_specs=replica_specs,
            run_policy=RunPolicy(
                clean_pod_policy=rp.get("cleanPodPolicy", CLEAN_POD_RUNNING),
                backoff_limit=int(rp.get("backoffLimit", 3)),
                active_deadline_seconds=rp.get("activeDeadlineSeconds"),
                gang_scheduling=bool(rp.get("gangScheduling", True)),
                ttl_seconds_after_finished=rp.get("ttlSecondsAfterFinished"),
                restart_backoff_seconds=float(
                    rp.get("restartBackoffSeconds", 0.0)),
                restart_backoff_max_seconds=float(
                    rp.get("restartBackoffMaxSeconds", 300.0)),
                stall_timeout_seconds=rp.get("stallTimeoutSeconds"),
                max_anomaly_rollbacks=int(rp.get("maxAnomalyRollbacks", 2)),
            ),
            sharding=ShardingSpec.from_dict(spec.get("sharding")),
            checkpoint_dir=spec.get("checkpointDir", "") or "",
            resume_from=spec.get("resumeFrom", "") or "",
            data_dir=spec.get("dataDir", "") or "",
            eval_data_dir=spec.get("evalDataDir", "") or "",
            tensorboard_dir=spec.get("tensorboardDir", "") or "",
            compile_cache_dir=spec.get("compileCacheDir", "") or "",
            input_spec=InputSpec.from_dict(spec.get("input")),
            obs_spec=ObsSpec.from_dict(spec.get("observability")),
            warm_start=WarmStartSpec.from_dict(spec.get("warmStart")),
            multislice=MultisliceSpec.from_dict(spec.get("multislice")),
            kernels=KernelSpec.from_dict(spec.get("kernels")),
            integrity=IntegritySpec.from_dict(spec.get("integrity")),
            scheduling_policy=SchedulingPolicy.from_dict(
                spec.get("schedulingPolicy")),
            weight_update=spec.get("weightUpdate", "") or "",
            raw=obj,
        )
        job.validate()
        return job

    @staticmethod
    def _mpi_shorthand(spec: dict) -> dict:
        """MPIJob `oneOf{tpuTopology, replicas}` shorthand → replica specs.

        Reference API shape: mpi-operator.libsonnet:27-77 (`oneOf{gpus,
        replicas}`); here `tpuTopology: v5e-32` names the whole gang.
        """
        if "tpuTopology" in spec:
            return {"TPU": {"tpuTopology": spec["tpuTopology"],
                            "numSlices": spec.get("numSlices", 1),
                            "template": spec.get("template", {})}}
        if "replicas" in spec:
            return {"Launcher": {"replicas": 1, "template": spec.get("template", {})},
                    "Worker": {"replicas": int(spec["replicas"]),
                               "template": spec.get("template", {})}}
        raise ValueError("MPIJob spec requires one of tpuTopology or replicas")

    # -- validation ---------------------------------------------------------

    # Derived names ("<name>-worker-<slice>-<host>" pod hostnames,
    # "<name>-workers" service) must each fit a 63-char DNS label; reserve
    # headroom for the longest suffix the operator generates.
    MAX_NAME_LEN = 45

    def validate(self) -> None:
        k8s.validate_name(self.name, max_len=self.MAX_NAME_LEN)
        if self.weight_update:
            # admission-time rejection: a typo'd mode must fail at apply,
            # not at worker startup deep inside the gang
            validate_weight_update(self.weight_update)
        self.input_spec.validate()
        self.obs_spec.validate()
        self.warm_start.validate()
        self.multislice.validate()
        self.kernels.validate()
        self.integrity.validate()
        if self.scheduling_policy is not None:
            self.scheduling_policy.validate()
        vocab = REPLICA_TYPES[self.kind]
        if not self.replica_specs:
            raise ValueError(f"{self.kind} {self.name}: no replica specs")
        for rtype, rs in self.replica_specs.items():
            if rtype not in vocab:
                raise ValueError(
                    f"{self.kind} {self.name}: invalid replica type {rtype!r}; "
                    f"valid: {vocab}"
                )
            if rtype in _MAX_ONE and rs.replicas > 1:
                raise ValueError(f"{self.kind} {self.name}: at most one {rtype} replica")
            if rs.is_tpu:
                if rs.topology is None:
                    raise ValueError(
                        f"{self.kind} {self.name}: TPU replica spec requires "
                        "tpuTopology (e.g. v5e-32)")
                # Resolving the sharding spec against the slice validates the
                # axis product here, at admission time, not at runtime.
                sizes = self.sharding.resolve(
                    rs.topology.num_chips * rs.num_slices)
                if rs.num_slices > 1:
                    # DCN-aware layout rejection: a tensor/sequence axis
                    # crossing the slice boundary puts PER-LAYER
                    # collectives on the slow link and forces the SPMD
                    # partitioner's involuntary-full-rematerialization
                    # fallback (MULTICHIP_r05) — reject at apply, not at
                    # compile deep inside the gang.
                    bad = tuple(a for a in dcn_crossing_axes(
                        sizes, rs.num_slices)
                        if a not in DCN_LEGAL_AXES)
                    if bad:
                        raise ValueError(
                            f"{self.kind} {self.name}: sharding axes "
                            f"{list(bad)} would cross the DCN slice "
                            f"boundary ({rs.num_slices} slices x "
                            f"{rs.topology.num_chips} chips); only "
                            f"{list(DCN_LEGAL_AXES)} may span slices — "
                            f"move the parallelism intra-slice or use "
                            f"spec.multislice.pipeline")
                if self.multislice.pipeline_enabled and rs.num_slices < 2:
                    raise ValueError(
                        f"{self.kind} {self.name}: "
                        f"multislice.pipeline requires numSlices >= 2 "
                        f"(one program per slice needs slices to "
                        f"program)")
                policy = self.scheduling_policy
                if policy is not None and policy.elastic:
                    # Elastic admission contract: the nominal shape must
                    # sit inside its own [min, max] envelope, and the
                    # sharding must leave a data-parallel axis as the -1
                    # wildcard — a resized gang re-resolves the mesh
                    # against its new chip count, which a fully pinned
                    # axis product cannot do. Rejected at apply, not at
                    # the first resize deep inside the scheduler.
                    nominal = rs.topology.num_chips * rs.num_slices
                    lo, hi = policy.chip_bounds(nominal)
                    if not lo <= nominal <= hi:
                        raise ValueError(
                            f"{self.kind} {self.name}: nominal gang size "
                            f"{nominal} chips outside schedulingPolicy "
                            f"minChips/maxChips [{lo}, {hi}]")
                    sizes = self.sharding.axis_sizes()
                    if sizes.get("data") != -1 and sizes.get("fsdp") != -1:
                        raise ValueError(
                            f"{self.kind} {self.name}: elastic resizing "
                            "(minChips/maxChips) requires a -1 wildcard "
                            "on the data or fsdp sharding axis — a "
                            "pinned axis product cannot follow the "
                            "resized chip count")
                    # ...and EVERY shape inside the envelope must
                    # resolve: the scheduler may legally bind any
                    # supported slice size in [min, max], and a fixed
                    # axis product (e.g. tensor=4) that does not divide
                    # one of them would crash-loop the gang at the
                    # scheduler-chosen shape — reject at apply, not at
                    # the first resize
                    for c in rs.topology.generation.supported_chip_counts:
                        total = c * rs.num_slices
                        if not lo <= total <= hi:
                            continue
                        try:
                            self.sharding.resolve(total)
                        except ValueError as e:
                            raise ValueError(
                                f"{self.kind} {self.name}: elastic "
                                f"envelope admits a {total}-chip gang "
                                f"the sharding spec cannot resolve "
                                f"({e}); tighten minChips/maxChips or "
                                f"relax the pinned axes") from None
        if "TPU" in self.replica_specs and not self.run_policy.gang_scheduling:
            raise ValueError(
                f"{self.kind} {self.name}: TPU replicas require gangScheduling "
                "(the slice is the atomic unit)"
            )

    # -- helpers ------------------------------------------------------------

    @property
    def tpu_spec(self) -> Optional[ReplicaSpec]:
        return self.replica_specs.get("TPU")

    def total_pods(self) -> int:
        return sum(rs.pod_count for rs in self.replica_specs.values())

    def selector(self) -> dict[str, str]:
        return {"kubeflow.org/job-name": self.name,
                "kubeflow.org/job-kind": self.kind.lower()}

    def to_manifest(self) -> dict:
        """Serialize from the typed fields (always — a job parsed from a
        manifest and then mutated must serialize its mutations). Metadata
        extras from the source manifest (labels, uid, ...) are preserved."""
        api_version = API_VERSIONS[self.kind]
        specs_key = _SPECS_KEY[self.kind]
        out = k8s.make(
            api_version, self.kind, self.name, self.namespace,
            spec={
                specs_key: {t: rs.to_dict() for t, rs in self.replica_specs.items()},
                "runPolicy": self.run_policy.to_dict(),
                "sharding": self.sharding.to_dict(),
            },
        )
        if self.checkpoint_dir:
            out["spec"]["checkpointDir"] = self.checkpoint_dir
        if self.resume_from:
            out["spec"]["resumeFrom"] = self.resume_from
        if self.data_dir:
            out["spec"]["dataDir"] = self.data_dir
        if self.eval_data_dir:
            out["spec"]["evalDataDir"] = self.eval_data_dir
        if self.tensorboard_dir:
            out["spec"]["tensorboardDir"] = self.tensorboard_dir
        if self.compile_cache_dir:
            out["spec"]["compileCacheDir"] = self.compile_cache_dir
        if self.input_spec.to_dict():
            out["spec"]["input"] = self.input_spec.to_dict()
        if self.obs_spec.to_dict():
            out["spec"]["observability"] = self.obs_spec.to_dict()
        if self.warm_start.to_dict():
            out["spec"]["warmStart"] = self.warm_start.to_dict()
        if self.multislice.to_dict():
            out["spec"]["multislice"] = self.multislice.to_dict()
        if self.kernels.to_dict():
            out["spec"]["kernels"] = self.kernels.to_dict()
        if self.integrity.to_dict():
            out["spec"]["integrity"] = self.integrity.to_dict()
        if self.scheduling_policy is not None:
            out["spec"]["schedulingPolicy"] = self.scheduling_policy.to_dict()
        if self.weight_update:
            out["spec"]["weightUpdate"] = self.weight_update
        if self.raw:
            out["apiVersion"] = self.raw.get("apiVersion", out["apiVersion"])
            meta = dict(self.raw.get("metadata", {}))
            meta.update(out["metadata"])
            out["metadata"] = meta
            if "status" in self.raw:
                out["status"] = self.raw["status"]
        return out
