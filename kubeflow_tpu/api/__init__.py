"""Typed API surface: platform config (KfDef), CRD types, k8s object model."""
