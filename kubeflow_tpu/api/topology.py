"""TPU slice topology model.

The reference's cluster-topology contract is TF_CONFIG / MPI hostfiles /
MASTER_ADDR rendered by the training operators (SURVEY.md §2.5, §3.2). On TPU
the contract is two-level:

- **ICI** (intra-slice): the physical chip mesh of one slice, over which XLA
  compiles collectives. Described by a named topology ("v5e-32" = 4x8 chips).
- **DCN** (inter-slice): data-parallel replication across slices, coordinated
  by `jax.distributed` (coordinator address + process ids), the analog of the
  TF_CONFIG cluster dict.

This module is the single source of truth for what a topology name means:
chip count, per-host chip count, the physical mesh, and how hosts map onto it.
The TPUJob reconciler uses it to size the gang (hosts = pods) and to render
the topology contract into worker env; the runtime uses it to build the
jax.sharding.Mesh.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Optional

# Accelerator generations we model. chips_per_host is the gang-sizing constant:
# one K8s pod per TPU VM host.
@dataclass(frozen=True)
class TpuGeneration:
    name: str                  # "v5e"
    chips_per_host: int        # chips on one TPU VM (one pod in the gang)
    cores_per_chip: int
    hbm_gib_per_chip: int
    supported_chip_counts: tuple[int, ...]  # valid slice sizes
    default_2d: dict[int, tuple[int, int]] = field(default_factory=dict)


GENERATIONS: dict[str, TpuGeneration] = {
    "v4": TpuGeneration(
        name="v4", chips_per_host=4, cores_per_chip=2, hbm_gib_per_chip=32,
        supported_chip_counts=(8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096),
    ),
    "v5e": TpuGeneration(
        name="v5e", chips_per_host=4, cores_per_chip=1, hbm_gib_per_chip=16,
        supported_chip_counts=(1, 4, 8, 16, 32, 64, 128, 256),
        default_2d={1: (1, 1), 4: (2, 2), 8: (2, 4), 16: (4, 4), 32: (4, 8),
                    64: (8, 8), 128: (8, 16), 256: (16, 16)},
    ),
    "v5p": TpuGeneration(
        name="v5p", chips_per_host=4, cores_per_chip=2, hbm_gib_per_chip=95,
        supported_chip_counts=tuple(2 ** i for i in range(2, 14)),
    ),
    "v6e": TpuGeneration(
        name="v6e", chips_per_host=4, cores_per_chip=1, hbm_gib_per_chip=32,
        supported_chip_counts=(1, 4, 8, 16, 32, 64, 128, 256),
        default_2d={1: (1, 1), 4: (2, 2), 8: (2, 4), 16: (4, 4), 32: (4, 8),
                    64: (8, 8), 128: (8, 16), 256: (16, 16)},
    ),
}

_TOPOLOGY_RE = re.compile(r"^(v\d+[a-z]*)-(\d+)$")


@dataclass(frozen=True)
class SliceTopology:
    """A named, validated TPU slice: the atomic scheduling unit (the gang)."""

    name: str                      # "v5e-32"
    generation: TpuGeneration
    num_chips: int
    ici_mesh: tuple[int, ...]      # physical chip mesh, e.g. (4, 8)

    @property
    def num_hosts(self) -> int:
        return max(1, self.num_chips // self.generation.chips_per_host)

    @property
    def chips_per_host(self) -> int:
        return min(self.num_chips, self.generation.chips_per_host)

    @property
    def hbm_gib(self) -> int:
        return self.num_chips * self.generation.hbm_gib_per_chip

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "generation": self.generation.name,
            "numChips": self.num_chips,
            "numHosts": self.num_hosts,
            "chipsPerHost": self.chips_per_host,
            "iciMesh": list(self.ici_mesh),
        }


def _near_square(n: int) -> tuple[int, int]:
    a = int(math.isqrt(n))
    while n % a:
        a -= 1
    return (a, n // a)


def parse_topology(name: str) -> SliceTopology:
    """Parse "v5e-32"-style topology names (the `oneOf{tpuTopology, replicas}`
    API surface, SURVEY.md §2.6 — the TPU analog of MPIJob's `gpus`)."""
    m = _TOPOLOGY_RE.match(name.strip().lower())
    if not m:
        raise ValueError(
            f"invalid TPU topology {name!r}; expected <generation>-<chips>, e.g. v5e-32"
        )
    gen_name, chips_s = m.groups()
    gen = GENERATIONS.get(gen_name)
    if gen is None:
        raise ValueError(
            f"unknown TPU generation {gen_name!r}; known: {sorted(GENERATIONS)}"
        )
    chips = int(chips_s)
    if chips not in gen.supported_chip_counts:
        raise ValueError(
            f"{gen_name} does not come in {chips}-chip slices; "
            f"valid sizes: {gen.supported_chip_counts}"
        )
    ici = gen.default_2d.get(chips) or _near_square(chips)
    return SliceTopology(name=f"{gen_name}-{chips}", generation=gen,
                         num_chips=chips, ici_mesh=ici)


@dataclass(frozen=True)
class TopologyContract:
    """What the operator renders into each worker pod — the TF_CONFIG analog.

    Reference: tf-operator injects TF_CONFIG={"cluster":{...},"task":{...}}
    (SURVEY.md §3.2); here the contract is the jax.distributed bootstrap tuple
    plus the two-level mesh description.
    """

    coordinator_address: str       # "<job>-worker-0.<svc>.<ns>:8476"
    num_processes: int             # hosts * num_slices
    process_id: int
    slice_topology: SliceTopology
    num_slices: int = 1            # DCN-level data parallel replicas
    slice_id: int = 0

    ENV_COORDINATOR = "KFTPU_COORDINATOR_ADDRESS"
    ENV_NUM_PROCESSES = "KFTPU_NUM_PROCESSES"
    ENV_PROCESS_ID = "KFTPU_PROCESS_ID"
    ENV_TOPOLOGY = "KFTPU_TOPOLOGY"
    ENV_NUM_SLICES = "KFTPU_NUM_SLICES"
    ENV_SLICE_ID = "KFTPU_SLICE_ID"

    def to_env(self) -> dict[str, str]:
        return {
            self.ENV_COORDINATOR: self.coordinator_address,
            self.ENV_NUM_PROCESSES: str(self.num_processes),
            self.ENV_PROCESS_ID: str(self.process_id),
            self.ENV_TOPOLOGY: self.slice_topology.name,
            self.ENV_NUM_SLICES: str(self.num_slices),
            self.ENV_SLICE_ID: str(self.slice_id),
        }

    @classmethod
    def from_env(cls, env: dict[str, str]) -> "TopologyContract":
        topo = parse_topology(env[cls.ENV_TOPOLOGY])
        return cls(
            coordinator_address=env[cls.ENV_COORDINATOR],
            num_processes=int(env[cls.ENV_NUM_PROCESSES]),
            process_id=int(env[cls.ENV_PROCESS_ID]),
            slice_topology=topo,
            num_slices=int(env.get(cls.ENV_NUM_SLICES, "1")),
            slice_id=int(env.get(cls.ENV_SLICE_ID, "0")),
        )


def render_contracts(
    job_name: str,
    namespace: str,
    topology: SliceTopology,
    num_slices: int = 1,
    port: int = 8476,
    headless_service: Optional[str] = None,
) -> list[TopologyContract]:
    """One contract per worker pod, coordinator = slice 0 / host 0.

    Pod DNS follows the headless-service convention the reference's operators
    use for replica discovery (tf-operator creates one headless service per
    replica; we use one per job with stable pod hostnames).
    """
    svc = headless_service or f"{job_name}-workers"
    coord = f"{job_name}-worker-0-0.{svc}.{namespace}:{port}"
    contracts = []
    for s in range(num_slices):
        for h in range(topology.num_hosts):
            contracts.append(
                TopologyContract(
                    coordinator_address=coord,
                    num_processes=num_slices * topology.num_hosts,
                    process_id=s * topology.num_hosts + h,
                    slice_topology=topology,
                    num_slices=num_slices,
                    slice_id=s,
                )
            )
    return contracts
