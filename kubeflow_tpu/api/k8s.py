"""Lightweight Kubernetes object model.

The platform manipulates Kubernetes manifests as plain dicts (the way the
reference's ksonnet layer and kubectl do), with typed helpers layered on top.
This module is the single place that knows manifest structure: GVK access,
metadata, labels/selectors, owner references, and conditions.

Reference parity: the reference uses k8s.io/apimachinery unstructured +
typed Go structs (e.g. bootstrap/pkg/apis/apps/kfdef/v1alpha1/
application_types.go). We keep manifests unstructured and put typing in
dataclass views (see kfdef.py / tpujob.py), which is the idiomatic Python
equivalent and what the manifest-builder layer emits.
"""

from __future__ import annotations

import copy
import re
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

GROUP = "kubeflow.org"
TPU_GROUP = "tpu.kubeflow.org"

# Kinds that are not namespaced (shared by every KubeClient implementation).
CLUSTER_SCOPED_KINDS = {
    "Namespace", "Node", "CustomResourceDefinition", "ClusterRole",
    "ClusterRoleBinding", "MutatingWebhookConfiguration",
    "ValidatingWebhookConfiguration", "PersistentVolume", "Profile",
}

# ---------------------------------------------------------------------------
# GVK / naming helpers
# ---------------------------------------------------------------------------


def snapshot(obj) -> str:
    """Stable serialization for write-on-change guards (apply no-ops,
    status-update no-ops). One definition so the two layers never diverge."""
    import json
    return json.dumps(obj, sort_keys=True, default=str)


_QUANTITY_SUFFIXES = {
    "Ki": 2 ** 10, "Mi": 2 ** 20, "Gi": 2 ** 30, "Ti": 2 ** 40,
    "Pi": 2 ** 50, "k": 10 ** 3, "M": 10 ** 6, "G": 10 ** 9, "T": 10 ** 12,
    "m": 1e-3,  # millicores
}


def parse_quantity(value) -> float:
    """Kubernetes resource quantity → float ("8Gi", "500m", 4, "2")."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    for suffix in sorted(_QUANTITY_SUFFIXES, key=len, reverse=True):
        if s.endswith(suffix):
            return float(s[:-len(suffix)]) * _QUANTITY_SUFFIXES[suffix]
    return float(s)


def gvk(obj: dict) -> tuple[str, str]:
    """(apiVersion, kind) of a manifest."""
    return obj.get("apiVersion", ""), obj.get("kind", "")


def name_of(obj: dict) -> str:
    return obj.get("metadata", {}).get("name", "")


def namespace_of(obj: dict, default: str = "") -> str:
    return obj.get("metadata", {}).get("namespace", default)


def key_of(obj: dict) -> tuple[str, str, str, str]:
    """Unique store key: (apiVersion, kind, namespace, name)."""
    av, kind = gvk(obj)
    return av, kind, namespace_of(obj), name_of(obj)


def set_namespace(obj: dict, namespace: str) -> dict:
    obj.setdefault("metadata", {})["namespace"] = namespace
    return obj


def labels_of(obj: dict) -> dict[str, str]:
    return obj.get("metadata", {}).get("labels", {}) or {}


def annotations_of(obj: dict) -> dict[str, str]:
    return obj.get("metadata", {}).get("annotations", {}) or {}


_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


def validate_name(name: str, max_len: int = 63) -> None:
    """RFC-1123 DNS label check (63 chars — pod hostnames and service DNS
    labels derived from this name must each fit a DNS label)."""
    if not name or len(name) > max_len or not _DNS1123.match(name):
        raise ValueError(f"invalid kubernetes object name: {name!r}")


# ---------------------------------------------------------------------------
# Label selection (the subset controllers use: matchLabels + set-based equality)
# ---------------------------------------------------------------------------


def matches_selector(obj: dict, selector: dict[str, str]) -> bool:
    """True iff every selector k=v appears in the object's labels."""
    lbl = labels_of(obj)
    return all(lbl.get(k) == v for k, v in selector.items())


def selector_from(spec_selector: Optional[dict]) -> dict[str, str]:
    """Normalize a LabelSelector ({matchLabels: ...} or flat map) to a flat map."""
    if not spec_selector:
        return {}
    if "matchLabels" in spec_selector:
        return dict(spec_selector.get("matchLabels") or {})
    return dict(spec_selector)


# ---------------------------------------------------------------------------
# Owner references (controllers set these; the fake apiserver GCs on them)
# ---------------------------------------------------------------------------


def owner_reference(owner: dict, *, controller: bool = True) -> dict:
    av, kind = gvk(owner)
    return {
        "apiVersion": av,
        "kind": kind,
        "name": name_of(owner),
        "uid": owner.get("metadata", {}).get("uid", ""),
        "controller": controller,
        "blockOwnerDeletion": True,
    }


def set_owner(obj: dict, owner: dict) -> dict:
    refs = obj.setdefault("metadata", {}).setdefault("ownerReferences", [])
    ref = owner_reference(owner)
    if not any(r.get("uid") == ref["uid"] and r.get("name") == ref["name"] for r in refs):
        refs.append(ref)
    return obj


def is_owned_by(obj: dict, owner: dict) -> bool:
    ouid = owner.get("metadata", {}).get("uid")
    for ref in obj.get("metadata", {}).get("ownerReferences", []) or []:
        if ref.get("uid") == ouid and ref.get("name") == name_of(owner):
            return True
    return False


# ---------------------------------------------------------------------------
# Conditions (the status idiom every reconciler uses, reference:
# notebook_types.go conditions, application_types.go:142-157 KfDefCondition)
# ---------------------------------------------------------------------------


@dataclass
class Condition:
    type: str
    status: str  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "status": self.status,
            "reason": self.reason,
            "message": self.message,
            "lastTransitionTime": self.last_transition_time,
        }


def set_condition(obj: dict, cond: Condition) -> None:
    """Upsert a condition by type; preserves transition time if status unchanged.
    Does not mutate the passed Condition."""
    conds = obj.setdefault("status", {}).setdefault("conditions", [])
    d = cond.to_dict()
    for existing in conds:
        if existing.get("type") == cond.type:
            if existing.get("status") == cond.status:
                d["lastTransitionTime"] = existing.get(
                    "lastTransitionTime", d["lastTransitionTime"])
            existing.update(d)
            return
    conds.append(d)


def get_condition(obj: dict, ctype: str) -> Optional[dict]:
    for c in obj.get("status", {}).get("conditions", []) or []:
        if c.get("type") == ctype:
            return c
    return None


def condition_true(obj: dict, ctype: str) -> bool:
    c = get_condition(obj, ctype)
    return bool(c and c.get("status") == "True")


# ---------------------------------------------------------------------------
# Manifest constructors used across the manifest registry
# ---------------------------------------------------------------------------


def make(api_version: str, kind: str, name: str, namespace: str = "",
         labels: Optional[dict] = None, spec: Optional[dict] = None) -> dict:
    meta: dict[str, Any] = {"name": name}
    if namespace:
        meta["namespace"] = namespace
    if labels:
        meta["labels"] = dict(labels)
    obj: dict[str, Any] = {"apiVersion": api_version, "kind": kind, "metadata": meta}
    if spec is not None:
        obj["spec"] = spec
    return obj


def deep_merge(base: dict, overlay: dict) -> dict:
    """Strategic-merge-lite: dicts merge recursively, everything else replaces.

    The analog of the reference's kustomize overlay merge
    (bootstrap/v2/pkg/kfapp/kustomize/kustomize.go MergeKustomization).
    """
    out = copy.deepcopy(base)
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out


def walk_strings(obj: Any, fn: Callable[[str], str]) -> Any:
    """Apply fn to every string leaf (param substitution in manifests)."""
    if isinstance(obj, str):
        return fn(obj)
    if isinstance(obj, dict):
        return {k: walk_strings(v, fn) for k, v in obj.items()}
    if isinstance(obj, list):
        return [walk_strings(v, fn) for v in obj]
    return obj


def substitute_params(obj: Any, params: dict[str, Any]) -> Any:
    """Replace ``$(name)`` placeholders with param values, preserving type when
    a string is exactly one placeholder (so replica counts stay ints)."""
    def sub(s: str) -> Any:
        m = re.fullmatch(r"\$\(([\w.-]+)\)", s)
        if m and m.group(1) in params:
            return params[m.group(1)]
        return re.sub(
            r"\$\(([\w.-]+)\)",
            lambda mm: str(params.get(mm.group(1), mm.group(0))),
            s,
        )
    return walk_strings(obj, sub)


def sort_for_apply(objs: Iterable[dict]) -> list[dict]:
    """Dependency-ordered apply: namespaces and CRDs first, webhooks last.

    Mirrors the reference's apply ordering concerns (ksonnet.go applies
    namespace before components; kustomize.go deployResources).
    """
    order = {
        "Namespace": 0,
        "CustomResourceDefinition": 1,
        "ServiceAccount": 2,
        "ClusterRole": 3,
        "Role": 3,
        "ClusterRoleBinding": 4,
        "RoleBinding": 4,
        "ConfigMap": 5,
        "Secret": 5,
        "Service": 6,
        "PersistentVolume": 6,
        "PersistentVolumeClaim": 7,
        "Deployment": 8,
        "StatefulSet": 8,
        "DaemonSet": 8,
        "Job": 9,
        "CronJob": 9,
        "MutatingWebhookConfiguration": 20,
        "ValidatingWebhookConfiguration": 20,
    }
    return sorted(objs, key=lambda o: (order.get(o.get("kind", ""), 10), name_of(o)))
