"""Experiment CRD types: massively-multi-trial hyperparameter search.

The reference ships Katib as a core platform component (PAPER.md §Katib;
kubeflow/katib/studyjobcontroller.libsonnet); its StudyJob v1alpha1 shape
survives here only as a compat parser (katib/studyjob.py
``studyjob_to_experiment``). The native object is ``Experiment``
(kubeflow.org/v1alpha1): a search space over TPUJob template parameters,
an objective (metric + direction + optional goal), a trial budget
(maxTrials bounded by ``parallelism`` in flight), an algorithm
(random | grid | pbt), and a median-stopping early-termination policy.

The reconciler (controllers/experiment.py) fans trials through the slice
scheduler as ordinary TPUJobs — every trial is a gang-scheduled slice,
subject to queue quota and FIFO like any other job — and reads
per-window objective values from the trace-span sink (runtime/worker.py
emits one ``SPAN_OBJECTIVE`` event per drained metrics window).

Trials differing only in tuned scalars share one compiled executable:
the trial env sets ``KFTPU_RUNTIME_SCHEDULE=1`` so the worker feeds
lr/warmup/total-steps to the optimizer as runtime state and keys the
AOT/compile cache on ``compile_shape_fingerprint``
(runtime/recipe.py) — every trial after the first starts warm.

Jax-free like the rest of the api layer: admission and the controller
must not import the runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from .trainingjob import JOB_KINDS, KF_API_VERSION_V1ALPHA1, TrainingJob

EXPERIMENT_API_VERSION = KF_API_VERSION_V1ALPHA1
EXPERIMENT_KIND = "Experiment"
# trial names append "-t<index>"; the base name + longest suffix must
# still fit the TrainingJob name budget (its derived pod hostnames are
# the binding constraint)
MAX_NAME_LEN = TrainingJob.MAX_NAME_LEN - 6
EXPERIMENT_LABEL = "katib.kubeflow.org/experiment"
TRIAL_LABEL = "katib.kubeflow.org/trial"

#: objective metric assumed when spec.objective.metric is unset — the
#: name the worker's metrics stream (and its per-window objective span)
#: reports training loss under. Defined ONCE, here: the worker span
#: emitter, the reconciler's median-stopping read, the dashboard trial
#: table, and the manifests schema all import it (tests/test_lint.py).
DEFAULT_OBJECTIVE_METRIC = "loss"

#: point-event name the worker emits per drained metrics window
#: (runtime/worker.py) carrying that window's scalar metrics; the
#: reconciler's early-stopping policy reads these from the span sink.
SPAN_OBJECTIVE = "objective"

#: trial-job annotation carrying a final ``{metric: value}`` JSON map —
#: the out-of-band reporting fallback when no span sink is mounted
#: (the same contract StudyJob v1alpha1 used).
OBSERVATION_ANNOTATION = "kubeflow.org/observation"

ALGORITHMS = ("random", "grid", "pbt")
OBJECTIVE_TYPES = ("minimize", "maximize")
EARLY_STOPPING_POLICIES = ("none", "median")

# trial states recorded in Experiment status. "Stopped" = terminated
# early by policy: counts as DONE (its best-so-far objective stands as
# the trial's result) and its remaining chip-hours are ledgered as
# saved, not spent.
T_PENDING = "Pending"
T_RUNNING = "Running"
T_SUCCEEDED = "Succeeded"
T_FAILED = "Failed"
T_STOPPED = "Stopped"

_PARAM_TYPES = ("double", "int", "discrete", "categorical")


@dataclass
class ParameterRange:
    """One axis of the search space (``spec.parameters[]``): a feasible
    range or value list for a named template parameter. The name is both
    the ``$(param.<name>)`` placeholder key and (unless
    injectParameters=false) the ``--<name>=<value>`` flag appended to
    the trial container."""

    name: str
    type: str = "double"
    min: Optional[float] = None
    max: Optional[float] = None
    values: Optional[list] = None

    _KEYS = ("name", "type", "min", "max", "values")

    @classmethod
    def from_dict(cls, d: dict) -> "ParameterRange":
        if not isinstance(d, dict):
            raise ValueError(
                f"spec.parameters entries must be mappings, got {d!r}")
        unknown = set(d) - set(cls._KEYS)
        if unknown:
            raise ValueError(
                f"unknown parameter field(s) {sorted(unknown)}; "
                f"supported: {list(cls._KEYS)}")
        if not d.get("name"):
            raise ValueError("spec.parameters entries need a name")
        return cls(name=str(d["name"]), type=str(d.get("type", "double")),
                   min=d.get("min"), max=d.get("max"),
                   values=d.get("values"))

    def validate(self) -> None:
        if self.type not in _PARAM_TYPES:
            raise ValueError(
                f"parameter {self.name}: type {self.type!r} not one of "
                f"{_PARAM_TYPES}")
        if self.type in ("double", "int"):
            if self.min is None or self.max is None or \
                    float(self.min) > float(self.max):
                raise ValueError(
                    f"parameter {self.name}: {self.type} needs "
                    f"min <= max, got [{self.min}, {self.max}]")
        elif not self.values:
            raise ValueError(
                f"parameter {self.name}: {self.type} needs a non-empty "
                f"values list")

    def to_dict(self) -> dict:
        out: dict[str, Any] = {"name": self.name, "type": self.type}
        if self.min is not None:
            out["min"] = self.min
        if self.max is not None:
            out["max"] = self.max
        if self.values is not None:
            out["values"] = self.values
        return out

    def to_parameter_config(self) -> dict:
        """The katib/suggestion.py ``parameterconfigs`` shape the
        suggestion engines parse (min/max/list under ``feasible``)."""
        feasible: dict[str, Any] = {}
        if self.min is not None:
            feasible["min"] = self.min
        if self.max is not None:
            feasible["max"] = self.max
        if self.values is not None:
            feasible["list"] = self.values
        return {"name": self.name, "parametertype": self.type,
                "feasible": feasible}


@dataclass
class EarlyStoppingSpec:
    """``spec.earlyStopping``: median-stopping rule (Google Vizier §3.2,
    the katib medianstop service). A running trial is stopped when its
    best objective so far is worse than the median of all other trials'
    objectives at the same window index — judged only after
    ``minTrials`` trials have reported and the trial has produced at
    least ``startWindow`` objective windows."""

    policy: str = "median"
    min_trials: int = 3
    start_window: int = 2

    _KEYS = ("policy", "minTrials", "startWindow")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["EarlyStoppingSpec"]:
        if d is None:
            return None
        if not isinstance(d, dict):
            raise ValueError(
                f"spec.earlyStopping must be a mapping, got {d!r}")
        unknown = set(d) - set(cls._KEYS)
        if unknown:
            raise ValueError(
                f"unknown earlyStopping field(s) {sorted(unknown)}; "
                f"supported: {list(cls._KEYS)}")
        return cls(policy=str(d.get("policy", "median")),
                   min_trials=int(d.get("minTrials", 3)),
                   start_window=int(d.get("startWindow", 2)))

    def validate(self) -> None:
        if self.policy not in EARLY_STOPPING_POLICIES:
            raise ValueError(
                f"earlyStopping.policy {self.policy!r} not one of "
                f"{EARLY_STOPPING_POLICIES}")
        if self.min_trials < 1 or self.start_window < 1:
            raise ValueError(
                "earlyStopping.minTrials and startWindow must be >= 1")

    def to_dict(self) -> dict:
        return {"policy": self.policy, "minTrials": self.min_trials,
                "startWindow": self.start_window}


@dataclass
class PBTSpec:
    """``spec.pbt`` (algorithm: pbt only): population-based training.
    Trials run in generations of ``spec.parallelism``; when a generation
    completes, the bottom ``truncation`` fraction is replaced by clones
    of top performers — exploit = resume from the winner's checkpoint
    (the elastic-restore machinery reshapes it onto the clone's slice),
    explore = each numeric parameter multiplied by a factor drawn from
    ``perturbFactors``."""

    truncation: float = 0.25
    perturb_factors: tuple = (0.8, 1.25)

    _KEYS = ("truncation", "perturbFactors")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["PBTSpec"]:
        if d is None:
            return None
        if not isinstance(d, dict):
            raise ValueError(f"spec.pbt must be a mapping, got {d!r}")
        unknown = set(d) - set(cls._KEYS)
        if unknown:
            raise ValueError(
                f"unknown pbt field(s) {sorted(unknown)}; "
                f"supported: {list(cls._KEYS)}")
        factors = d.get("perturbFactors", (0.8, 1.25))
        return cls(truncation=float(d.get("truncation", 0.25)),
                   perturb_factors=tuple(float(f) for f in factors))

    def validate(self) -> None:
        if not 0.0 < self.truncation < 1.0:
            raise ValueError(
                f"pbt.truncation must be in (0, 1), got {self.truncation}")
        if not self.perturb_factors or \
                any(f <= 0 for f in self.perturb_factors):
            raise ValueError("pbt.perturbFactors must be positive factors")

    def to_dict(self) -> dict:
        return {"truncation": self.truncation,
                "perturbFactors": list(self.perturb_factors)}


_SPEC_KEYS = ("objective", "algorithm", "parameters", "maxTrials",
              "parallelism", "maxFailedTrials", "earlyStopping", "pbt",
              "trialTemplate", "injectParameters")
_OBJECTIVE_KEYS = ("type", "metric", "goal")
_ALGORITHM_KEYS = ("name", "settings")


@dataclass
class Experiment:
    """Typed view of an Experiment manifest. ``from_manifest`` is the
    admission gate (unknown keys and bad values raise ValueError with
    the field path); ``to_manifest`` round-trips."""

    name: str
    namespace: str = "default"
    objective_type: str = "minimize"
    objective_metric: str = DEFAULT_OBJECTIVE_METRIC
    objective_goal: Optional[float] = None
    algorithm: str = "random"
    algorithm_settings: dict = field(default_factory=dict)
    parameters: list = field(default_factory=list)
    max_trials: int = 10
    parallelism: int = 2
    max_failed_trials: Optional[int] = None
    early_stopping: Optional[EarlyStoppingSpec] = None
    pbt: Optional[PBTSpec] = None
    trial_template: dict = field(default_factory=dict)
    inject_parameters: bool = True
    metadata: dict = field(default_factory=dict)

    @classmethod
    def from_manifest(cls, manifest: dict) -> "Experiment":
        if manifest.get("kind", EXPERIMENT_KIND) != EXPERIMENT_KIND:
            raise ValueError(
                f"kind {manifest.get('kind')!r} is not {EXPERIMENT_KIND}")
        meta = manifest.get("metadata", {}) or {}
        spec = manifest.get("spec", {}) or {}
        if not isinstance(spec, dict):
            raise ValueError(f"spec must be a mapping, got {spec!r}")
        unknown = set(spec) - set(_SPEC_KEYS)
        if unknown:
            raise ValueError(
                f"unknown spec field(s) {sorted(unknown)}; "
                f"supported: {list(_SPEC_KEYS)}")

        objective = spec.get("objective", {}) or {}
        if not isinstance(objective, dict):
            raise ValueError(
                f"spec.objective must be a mapping, got {objective!r}")
        bad = set(objective) - set(_OBJECTIVE_KEYS)
        if bad:
            raise ValueError(
                f"unknown objective field(s) {sorted(bad)}; "
                f"supported: {list(_OBJECTIVE_KEYS)}")
        algo = spec.get("algorithm", {}) or {}
        if isinstance(algo, str):  # shorthand: algorithm: random
            algo = {"name": algo}
        if not isinstance(algo, dict):
            raise ValueError(
                f"spec.algorithm must be a mapping or name, got {algo!r}")
        bad = set(algo) - set(_ALGORITHM_KEYS)
        if bad:
            raise ValueError(
                f"unknown algorithm field(s) {sorted(bad)}; "
                f"supported: {list(_ALGORITHM_KEYS)}")

        goal = objective.get("goal")
        exp = cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            objective_type=str(objective.get("type", "minimize")),
            objective_metric=str(objective.get("metric",
                                               DEFAULT_OBJECTIVE_METRIC)),
            objective_goal=float(goal) if goal is not None else None,
            algorithm=str(algo.get("name", "random")),
            algorithm_settings=dict(algo.get("settings", {}) or {}),
            parameters=[ParameterRange.from_dict(p)
                        for p in spec.get("parameters", []) or []],
            max_trials=int(spec.get("maxTrials", 10)),
            parallelism=int(spec.get("parallelism", 2)),
            max_failed_trials=(
                int(spec["maxFailedTrials"])
                if spec.get("maxFailedTrials") is not None else None),
            early_stopping=EarlyStoppingSpec.from_dict(
                spec.get("earlyStopping")),
            pbt=PBTSpec.from_dict(spec.get("pbt")),
            trial_template=spec.get("trialTemplate") or {},
            inject_parameters=bool(spec.get("injectParameters", True)),
            metadata=dict(meta),
        )
        exp.validate()
        return exp

    def validate(self) -> None:
        if not self.name:
            raise ValueError("metadata.name is required")
        if self.objective_type not in OBJECTIVE_TYPES:
            raise ValueError(
                f"objective.type {self.objective_type!r} not one of "
                f"{OBJECTIVE_TYPES}")
        if not self.objective_metric:
            raise ValueError("objective.metric must be non-empty")
        if self.algorithm not in ALGORITHMS:
            raise ValueError(
                f"algorithm.name {self.algorithm!r} not one of "
                f"{ALGORITHMS}")
        if not self.parameters:
            raise ValueError("spec.parameters must name at least one "
                             "search dimension")
        for p in self.parameters:
            p.validate()
        if self.max_trials < 1:
            raise ValueError(f"maxTrials must be >= 1, got "
                             f"{self.max_trials}")
        if self.parallelism < 1:
            raise ValueError(f"parallelism must be >= 1, got "
                             f"{self.parallelism}")
        if self.max_failed_trials is not None and \
                self.max_failed_trials < 0:
            raise ValueError("maxFailedTrials must be >= 0")
        if self.early_stopping is not None:
            self.early_stopping.validate()
        if self.pbt is not None:
            if self.algorithm != "pbt":
                raise ValueError(
                    "spec.pbt requires algorithm: pbt")
            self.pbt.validate()
        if self.algorithm == "pbt":
            if self.early_stopping is not None:
                # PBT's truncation IS its stopping rule; layering the
                # median policy on top would stop population members the
                # exploit step needs as clone donors
                raise ValueError(
                    "algorithm pbt and earlyStopping are mutually "
                    "exclusive (truncation replaces median stopping)")
            numeric = [p for p in self.parameters
                       if p.type in ("double", "int")]
            if not numeric:
                raise ValueError(
                    "algorithm pbt needs at least one numeric parameter "
                    "to perturb")
        if not self.trial_template:
            raise ValueError("spec.trialTemplate is required")
        kind = self.trial_template.get("kind", "TPUJob")
        if kind not in JOB_KINDS:
            raise ValueError(
                f"trialTemplate kind {kind!r} not one of {JOB_KINDS}")
        # trial names append "-t<index>" (and the k8s name rules cap the
        # whole thing); reject at admission, not at trial 100
        if len(self.name) > MAX_NAME_LEN:
            raise ValueError(
                f"metadata.name {self.name!r} too long for trial "
                f"suffixes (max {MAX_NAME_LEN})")

    def to_manifest(self) -> dict:
        spec: dict[str, Any] = {
            "objective": {"type": self.objective_type,
                          "metric": self.objective_metric},
            "algorithm": {"name": self.algorithm},
            "parameters": [p.to_dict() for p in self.parameters],
            "maxTrials": self.max_trials,
            "parallelism": self.parallelism,
            "trialTemplate": self.trial_template,
        }
        if self.objective_goal is not None:
            spec["objective"]["goal"] = self.objective_goal
        if self.algorithm_settings:
            spec["algorithm"]["settings"] = dict(self.algorithm_settings)
        if self.max_failed_trials is not None:
            spec["maxFailedTrials"] = self.max_failed_trials
        if self.early_stopping is not None:
            spec["earlyStopping"] = self.early_stopping.to_dict()
        if self.pbt is not None:
            spec["pbt"] = self.pbt.to_dict()
        if not self.inject_parameters:
            spec["injectParameters"] = False
        meta = dict(self.metadata)
        meta["name"] = self.name
        meta["namespace"] = self.namespace
        return {"apiVersion": EXPERIMENT_API_VERSION,
                "kind": EXPERIMENT_KIND, "metadata": meta, "spec": spec}

    # -- engine plumbing -----------------------------------------------------

    @property
    def sign(self) -> float:
        """Multiplier that makes HIGHER always better (the suggestion
        engines' observe() contract)."""
        return -1.0 if self.objective_type == "minimize" else 1.0

    def parameter_configs(self) -> list:
        """The search space in katib/suggestion.py's ParameterConfig
        form (lazy import: api stays importable without numpy)."""
        from ..katib.suggestion import parse_parameter_configs
        return parse_parameter_configs(
            [p.to_parameter_config() for p in self.parameters])

    def make_engine(self, seed: int = 0):
        """Suggestion engine for this spec. PBT samples its population
        with the random engine (explore/exploit happens in the
        reconciler's generation step, not here)."""
        from ..katib.suggestion import make_suggestion
        algo = "random" if self.algorithm == "pbt" else self.algorithm
        return make_suggestion(algo, self.parameter_configs(),
                               seed=seed, settings=self.algorithm_settings)

    def goal_reached(self, objective: Optional[float]) -> bool:
        if objective is None or self.objective_goal is None:
            return False
        if self.objective_type == "minimize":
            return objective <= self.objective_goal
        return objective >= self.objective_goal

    def better(self, a: Optional[float], b: Optional[float]) -> bool:
        """True when objective ``a`` beats ``b`` (handles None)."""
        if a is None:
            return False
        if b is None:
            return True
        return self.sign * a > self.sign * b
