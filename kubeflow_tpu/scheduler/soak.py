"""Scheduler soaks: preemption, elastic resize, node health — and the
control-plane fault-tolerance soak (ControlPlaneSoak + the split-brain
drill), which kills the CONTROLLERS themselves.

The chaos-soak pattern (cluster/chaos.py) applied to the scheduler: a
preemptible low-priority job trains on the only pool, a high-priority job
arrives and reclaims its slices mid-run, the victim re-queues, re-binds
once the winner finishes, resumes from its own checkpoints, and
completes. The acceptance bar is numeric: the victim's final params must
match an UNCONTENDED run of the same seed to float tolerance — the
scheduler's preemption path must cost progress, never correctness.

Control plane is real (FakeCluster + SliceScheduler + the TPUJob
reconciler); the data plane is real too — each time a gang is fully
Running, a real training segment (runtime/worker.train, tiny transformer
on the CPU mesh) runs in-process with the env the operator rendered into
the chief pod. Used by ``bench.py --mode sched`` and the slow scheduler
tests.

jax-free at import time (worker.train imports lazily inside run()).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from ..api import k8s
from ..api.trainingjob import (BINDING_ANNOTATION, COND_QUEUED,
                               PREEMPTED_COUNT_ANNOTATION)

POOL_TOPOLOGY = "v5e-8"


@dataclass
class PreemptionSoak:
    """Two jobs contending for one v5e-8 pool; the scripted outcome is
    victim-preempted → winner-runs → victim-resumes, all through the
    real scheduler/operator loop."""

    workdir: str
    total_steps: int = 8
    checkpoint_every: int = 2
    preempt_at: int = 4          # victim's progress when the winner lands
    seed: int = 0
    global_batch: int = 8
    wall_budget_s: float = 300.0
    namespace: str = "kubeflow"

    def _manifest(self, name: str, ckpt_dir: str, priority: int,
                  preemptible: bool) -> dict:
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": self.namespace},
            "spec": {
                "checkpointDir": ckpt_dir,
                "schedulingPolicy": {"queue": "research",
                                     "priority": priority,
                                     "preemptible": preemptible},
                "replicaSpecs": {"TPU": {
                    "tpuTopology": POOL_TOPOLOGY,
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {"backoffLimit": 3},
            },
        }

    def _chief_env(self, cluster, chief: str) -> dict:
        pod = cluster.get("v1", "Pod", self.namespace, chief)
        return {e["name"]: e.get("value", "")
                for e in pod["spec"]["containers"][0].get("env", [])}

    def _run_segment(self, env_map: dict, target: int):
        from ..obs.trace import adopt_trace_env
        from ..runtime.worker import train  # lazy: pulls in jax
        # adopt the operator-rendered trace contract for the segment:
        # the in-process "worker" must read the SAME env a real pod
        # would, so its window spans stitch onto the job's trace id
        # (bench.py --mode obs asserts the end-to-end timeline; the
        # goodput ledger accounts the soak from the same stream)
        with adopt_trace_env(env_map):
            return train(
                workload="transformer", steps=target,
                global_batch=self.global_batch, sync_every=1,
                checkpoint_dir=env_map.get("KFTPU_CHECKPOINT_DIR"),
                checkpoint_every=self.checkpoint_every,
                resume_from=env_map.get("KFTPU_RESUME_FROM"),
                seed=self.seed, handle_sigterm=False, workload_kwargs={})

    def _gang_running(self, cluster, name: str) -> bool:
        pods = cluster.list("v1", "Pod", self.namespace,
                            selector={"kubeflow.org/job-name": name})
        running = [p for p in pods
                   if p.get("status", {}).get("phase") == "Running"]
        return len(running) == 2   # v5e-8 = 2 hosts

    def run(self) -> dict:
        from ..cluster.fake import FakeCluster
        from ..controllers.runtime import Manager
        from ..controllers.tpujob import TrainingJobReconciler
        from .core import SliceScheduler

        # preempt_at on a checkpoint boundary mirrors the real reclaim:
        # SIGTERM forces a save before exit 75, so the victim's on-disk
        # state is exactly its progress at preemption
        assert self.preempt_at % self.checkpoint_every == 0, \
            "preempt_at must land on a checkpoint boundary"
        ckpt_victim = os.path.join(self.workdir, "victim")
        ckpt_winner = os.path.join(self.workdir, "winner")
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes(POOL_TOPOLOGY)
        mgr = Manager(cluster)
        mgr.add(SliceScheduler())
        mgr.add(TrainingJobReconciler("TPUJob"))
        report: dict = {"events": [], "outcome": "timeout",
                        "checkpoint_dir": ckpt_victim}

        def pump(ticks: int = 3) -> None:
            for _ in range(ticks):
                mgr.run_pending()
                cluster.tick()
            mgr.run_pending()

        def job(name: str) -> dict:
            return cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                               self.namespace, name)

        cluster.create(self._manifest("victim", ckpt_victim,
                                      priority=0, preemptible=True))
        deadline = time.monotonic() + self.wall_budget_s
        pump()
        if not self._gang_running(cluster, "victim"):
            report["outcome"] = "victim-never-bound"
            return self._finish(report, mgr)

        # victim trains to the preemption point
        seg = self._run_segment(
            self._chief_env(cluster, "victim-worker-0-0"),
            self.preempt_at)
        # executed-step ledger: the ground truth bench.py --mode goodput
        # checks the span-derived restart-recompute number against
        report["victim_executed_steps"] = int(seg.steps)
        report["events"].append(f"victim reached step {self.preempt_at}")

        # the winner lands: higher priority, same (full-pool) shape
        cluster.create(self._manifest("winner", ckpt_winner,
                                      priority=10, preemptible=False))
        while time.monotonic() < deadline:
            pump()
            v = job("victim")
            if not k8s.annotations_of(v).get(BINDING_ANNOTATION) and \
                    k8s.condition_true(v, COND_QUEUED) and \
                    self._gang_running(cluster, "winner"):
                break
        else:
            report["outcome"] = "preemption-never-happened"
            return self._finish(report, mgr)
        v = job("victim")
        report["victim_preempted_count"] = int(k8s.annotations_of(v).get(
            PREEMPTED_COUNT_ANNOTATION, "0"))
        report["victim_resume_from"] = v["spec"].get("resumeFrom", "")
        report["events"].append("victim preempted, winner running")

        # winner trains to completion and succeeds
        self._run_segment(self._chief_env(cluster, "winner-worker-0-0"),
                          self.total_steps)
        cluster.set_pod_phase(self.namespace, "winner-worker-0-0",
                              "Succeeded")
        # winner done -> its binding releases -> victim re-binds
        while time.monotonic() < deadline:
            pump()
            if k8s.condition_true(job("winner"), "Succeeded") and \
                    self._gang_running(cluster, "victim"):
                break
        else:
            report["outcome"] = "victim-never-rebound"
            return self._finish(report, mgr)
        report["events"].append("winner succeeded, victim re-bound")

        # victim resumes from its own checkpoints and completes; the
        # resume step is whatever survived on disk — it must be the
        # forced save at preemption, not step 0 (a silent replay would
        # still pass the parity check while wasting the whole first run)
        env_map = self._chief_env(cluster, "victim-worker-0-0")
        report["victim_rebind_resume_env"] = env_map.get(
            "KFTPU_RESUME_FROM", "")
        report["victim_resume_step"] = self._latest_step(ckpt_victim)
        seg = self._run_segment(env_map, self.total_steps)
        report["victim_executed_steps"] += int(seg.steps)
        cluster.set_pod_phase(self.namespace, "victim-worker-0-0",
                              "Succeeded")
        while time.monotonic() < deadline:
            pump()
            if k8s.condition_true(job("victim"), "Succeeded"):
                report["outcome"] = "succeeded"
                break
        # the victim's final manifest rides along so callers can read
        # its annotations (trace id — bench.py --mode obs reconstructs
        # the victim's end-to-end timeline from the span sink)
        report["victim_manifest"] = job("victim")
        return self._finish(report, mgr)

    @staticmethod
    def _latest_step(ckpt_dir: str):
        from ..runtime.checkpoint import CheckpointManager  # lazy: jax
        mgr = CheckpointManager(ckpt_dir)
        try:
            return mgr.latest_step()
        finally:
            mgr.close()

    def _finish(self, report: dict, mgr) -> dict:
        for c in mgr.controllers:
            c.stop()
        return report

    def uncontended_params(self):
        """The parity reference: the victim's workload run start-to-finish
        with the same seed and no contention."""
        env_map = {"KFTPU_CHECKPOINT_DIR":
                   os.path.join(self.workdir, "clean")}
        self._run_segment(env_map, self.total_steps)
        from ..cluster.chaos import final_params
        return final_params(env_map["KFTPU_CHECKPOINT_DIR"])


@dataclass
class ElasticSoak:
    """Shrink-to-survive → grow-to-fill, end to end on the real loop.

    One ELASTIC TPUJob (``schedulingPolicy.minChips=4, maxChips=8``,
    ``weightUpdate=sharded`` so the optimizer state is genuinely
    distributed over the replica axes) trains on a single two-host
    v5e-8 pool. Mid-run a host VANISHES (cluster/chaos.py CapacityLoss
    deletes the node object): no same-size rectangle exists anywhere,
    so the pre-elastic scheduler could only strand the job in Queued —
    here the replan binds it DEGRADED at v5e-4 on the surviving host,
    the operator restarts the gang at the smaller shape with
    ``resumeFrom``, and the worker's restore reshapes the sharded
    optimizer state from replica degree 8 to 4 (runtime/checkpoint.py).
    Later the host returns; the grow-to-fill pass resizes the binding
    back to v5e-8 and the job finishes at full width.

    Acceptance is numeric: the job ends Succeeded; the final checkpoint
    restores IDENTICALLY (≤1e-5, in practice 0.0) into replica-degree-8
    and replica-degree-4 templates — the cross-degree round trip is
    lossless; and final params track an undisturbed same-seed run to a
    reported tolerance (cross-degree float drift is reduction-order
    only, ~1e-4 — reported, not hidden)."""

    workdir: str
    total_steps: int = 8
    checkpoint_every: int = 2
    lose_at: int = 3             # host vanishes after this many steps
    restore_at: int = 5          # ...and returns once the job reaches this
    # False = shrink-to-survive only: the host never comes back and the
    # job must still finish Succeeded at the degraded width (the
    # ``bench.py --mode chaos`` capacity-loss scenario; the full
    # shrink→grow arc runs under --mode sched)
    grow_phase: bool = True
    seed: int = 0
    global_batch: int = 8
    wall_budget_s: float = 300.0
    namespace: str = "kubeflow"
    job_name: str = "elastic-soak"

    POOL = "pool-a"

    def _manifest(self, ckpt_dir: str) -> dict:
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": self.job_name,
                         "namespace": self.namespace},
            "spec": {
                "checkpointDir": ckpt_dir,
                "weightUpdate": "sharded",
                "schedulingPolicy": {"queue": "research", "priority": 0,
                                     "minChips": 4, "maxChips": 8},
                "replicaSpecs": {"TPU": {
                    "tpuTopology": POOL_TOPOLOGY,
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {
                    "backoffLimit": 6,
                    "restartBackoffSeconds": 0.05,
                    "restartBackoffMaxSeconds": 0.2,
                },
            },
        }

    _chief_env = PreemptionSoak._chief_env
    _latest_step = staticmethod(PreemptionSoak._latest_step)

    def _ctx(self, devices: int):
        """A WorkerContext over the first ``devices`` CPU devices — the
        in-process stand-in for the resized gang's smaller mesh."""
        import jax

        from ..api.trainingjob import ShardingSpec
        from ..parallel.mesh import build_mesh
        from ..runtime.bootstrap import WorkerContext
        mesh = build_mesh(ShardingSpec(),
                          list(jax.devices())[:devices])
        return WorkerContext(contract=None, sharding=ShardingSpec(),
                             mesh=mesh, process_id=0, num_processes=1)

    def _run_segment(self, env_map: dict, target: int):
        """One real training segment at the CURRENTLY BOUND size: the
        chief env's topology contract names the resized shape, and the
        segment's mesh uses exactly that many devices — so restores
        genuinely cross replica degrees."""
        import jax

        from ..api.topology import TopologyContract, parse_topology
        from ..runtime.worker import train  # lazy: pulls in jax
        topo_name = env_map.get(TopologyContract.ENV_TOPOLOGY,
                                POOL_TOPOLOGY)
        chips = min(parse_topology(topo_name).num_chips,
                    len(jax.devices()))
        return train(
            workload="transformer", steps=target,
            global_batch=self.global_batch, sync_every=1,
            checkpoint_dir=env_map.get("KFTPU_CHECKPOINT_DIR"),
            checkpoint_every=self.checkpoint_every,
            resume_from=env_map.get("KFTPU_RESUME_FROM"),
            weight_update=env_map.get("KFTPU_WEIGHT_UPDATE"),
            seed=self.seed, handle_sigterm=False,
            ctx=self._ctx(chips), workload_kwargs={})

    def _state_template(self, degree: int):
        """An abstract TrainState template at the given replica degree,
        built exactly the way train() builds its state (same workload,
        optimizer, weight-update mode) — the restore target the
        cross-degree round-trip check reshapes into."""
        import jax

        from ..runtime.recipe import make_optimizer
        from ..runtime.trainstep import TrainStepBuilder
        from ..runtime.worker import WORKLOADS
        ctx = self._ctx(degree)
        spec = WORKLOADS["transformer"]()
        opt, _ = make_optimizer("momentum", 0.1, schedule="constant",
                                total_steps=self.total_steps)
        builder = TrainStepBuilder(mesh=ctx.mesh, loss_fn=spec.loss_fn,
                                   optimizer=opt, rules=spec.rules,
                                   param_logical_axes=spec.param_logical_axes,
                                   weight_update="sharded")
        return builder.init(spec.init_fn, jax.random.PRNGKey(self.seed))

    def roundtrip_delta(self, ckpt_dir: str,
                        degrees: tuple = (8, 4)) -> float:
        """Restore the newest checkpoint into templates at BOTH replica
        degrees and compare every leaf (params, sharded optimizer
        moments, rng, step): the cross-degree reshape must be lossless.
        Returns the max abs delta across all leaves."""
        import jax
        import numpy as np

        from ..runtime.checkpoint import CheckpointManager
        states = []
        for d in degrees:
            mgr = CheckpointManager(ckpt_dir)
            try:
                states.append(mgr.restore(self._state_template(d)))
            finally:
                mgr.close()
        deltas = jax.tree.map(
            lambda a, b: float(np.max(np.abs(
                np.asarray(a, dtype=np.float64)
                - np.asarray(b, dtype=np.float64)))) if hasattr(
                    a, "dtype") else 0.0,
            states[0], states[1])
        return max(jax.tree.leaves(deltas), default=0.0)

    def _gang_running(self, cluster, want: int) -> bool:
        pods = cluster.list("v1", "Pod", self.namespace,
                            selector={"kubeflow.org/job-name":
                                      self.job_name})
        running = [p for p in pods
                   if p.get("status", {}).get("phase") == "Running"]
        return len(running) == want

    def run(self) -> dict:
        from ..api.trainingjob import RESIZE_HISTORY_ANNOTATION
        from ..cluster.chaos import CapacityLoss
        from ..cluster.fake import FakeCluster
        from ..controllers.runtime import Manager
        from ..controllers.tpujob import TrainingJobReconciler
        from .core import SliceScheduler
        from .queue import SchedulerConfig, binding_of

        ckpt_dir = os.path.join(self.workdir, "job")
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes(POOL_TOPOLOGY, pool=self.POOL)
        lost_node = f"{self.POOL}-{POOL_TOPOLOGY}-1"
        fault = CapacityLoss(node=lost_node)
        mgr = Manager(cluster)
        # no grow cooldown: the soak compresses hours into seconds
        mgr.add(SliceScheduler(SchedulerConfig(grow_cooldown_s=0.0)))
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(self._manifest(ckpt_dir))

        chief = f"{self.job_name}-worker-0-0"
        report: dict = {"outcome": "timeout", "events": [],
                        "chips_seen": [], "checkpoint_dir": ckpt_dir}
        deadline = time.monotonic() + self.wall_budget_s

        def pump(ticks: int = 3) -> None:
            for _ in range(ticks):
                mgr.run_pending()
                cluster.tick()
            mgr.run_pending()

        def job() -> dict:
            return cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                               self.namespace, self.job_name)

        def note_chips() -> int:
            placement = binding_of(job())
            chips = placement.chips if placement else 0
            if chips and (not report["chips_seen"]
                          or report["chips_seen"][-1] != chips):
                report["chips_seen"].append(chips)
            return chips

        def wait_for(pods: int, chips: int, tag: str) -> bool:
            while time.monotonic() < deadline:
                pump()
                if note_chips() == chips and \
                        self._gang_running(cluster, pods):
                    return True
                time.sleep(0.02)
            report["outcome"] = f"timeout: {tag}"
            return False

        # phase 1: bind + train at nominal width until the host dies
        if not wait_for(2, 8, "never bound at nominal"):
            return self._finish(report, mgr)
        report["events"].append("bound at 8 chips (2 hosts)")
        self._run_segment(self._chief_env(cluster, chief), self.lose_at)
        report["events"].append(f"trained to step {self.lose_at} @8")

        # phase 2: the host vanishes -> shrink-to-survive at v5e-4
        fault.fire(cluster)
        if not wait_for(1, 4, "never shrank after capacity loss"):
            return self._finish(report, mgr)
        report["events"].append("host lost; re-bound DEGRADED at 4 chips")
        report["shrink_resume_step"] = self._latest_step(ckpt_dir)
        # cross-degree round trip at the shrink point: the state saved
        # at degree 8 must restore losslessly into the degree-4 layout
        report["roundtrip_delta_at_shrink"] = self.roundtrip_delta(
            ckpt_dir, degrees=(8, 4))
        if self.grow_phase:
            self._run_segment(self._chief_env(cluster, chief),
                              self.restore_at)
            report["events"].append(
                f"trained degraded to step {self.restore_at} @4")

            # phase 3: capacity returns -> grow-to-fill back to v5e-8
            fault.restore(cluster)
            if not wait_for(2, 8, "never grew after capacity returned"):
                return self._finish(report, mgr)
            report["events"].append("capacity back; grown to 8 chips")
            report["grow_resume_step"] = self._latest_step(ckpt_dir)
        self._run_segment(self._chief_env(cluster, chief),
                          self.total_steps)
        cluster.set_pod_phase(self.namespace, chief, "Succeeded")
        while time.monotonic() < deadline:
            pump()
            if k8s.condition_true(job(), "Succeeded"):
                report["outcome"] = "succeeded"
                break
        report["resize_history"] = k8s.annotations_of(job()).get(
            RESIZE_HISTORY_ANNOTATION, "")
        report["roundtrip_delta_final"] = self.roundtrip_delta(
            ckpt_dir, degrees=(8, 4))
        return self._finish(report, mgr)

    def clean_params(self):
        """The parity reference: same seed/steps/batch, full width the
        whole way (no capacity loss). Final params differ from the
        shrink→grow run only by cross-degree reduction order (~1e-4) —
        the report carries the measured delta."""
        env_map = {"KFTPU_CHECKPOINT_DIR":
                   os.path.join(self.workdir, "clean"),
                   "KFTPU_WEIGHT_UPDATE": "sharded"}
        self._run_segment(env_map, self.total_steps)
        from ..cluster.chaos import final_params
        return final_params(env_map["KFTPU_CHECKPOINT_DIR"])

    def _finish(self, report: dict, mgr) -> dict:
        for c in mgr.controllers:
            c.stop()
        return report


@dataclass
class HealthSoak:
    """Flaky-host migration drill: quarantine on vs off, end to end.

    One scheduler-managed TPUJob trains on a two-pool cluster whose
    first pool carries a FLAKY HOST (cluster/chaos.py HostFault): every
    time a gang pod lands on it, the pod dies — the recurring
    host-pinned failure the node-health subsystem exists for. With
    ``quarantine=True`` (health enabled in the scheduler config) the
    first crash records the suspect, the scheduler evacuates the
    binding off the host's cells within ONE rebind, and the gang
    finishes on the clean pool; with ``quarantine=False`` recovery is
    placement-blind — the gang crash-loops on the flaky host until the
    fault's trips budget runs out (the host "recovers"), burning a
    restart per trip. Both arms must finish Succeeded with final params
    IDENTICAL to a clean run (``bench.py --mode health`` asserts
    parity 0.0): the health path changes WHERE the gang runs, never
    what it computes.

    The fault fires on a step schedule (after steps 3, 4, 5 — off
    checkpoint boundaries) so the off arm pays real replay, making the
    useful-work fraction an honest A/B, not just a restart count."""

    workdir: str
    quarantine: bool = True
    total_steps: int = 6
    checkpoint_every: int = 2
    flaky_trips: int = 3
    seed: int = 0
    global_batch: int = 8
    wall_budget_s: float = 300.0
    namespace: str = "kubeflow"
    job_name: str = "health-soak"

    FLAKY_POOL = "pool-a"
    CLEAN_POOL = "pool-b"

    def _manifest(self, ckpt_dir: str) -> dict:
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": self.job_name,
                         "namespace": self.namespace},
            "spec": {
                "checkpointDir": ckpt_dir,
                "schedulingPolicy": {"queue": "research", "priority": 0,
                                     "preemptible": False},
                "replicaSpecs": {"TPU": {
                    "tpuTopology": POOL_TOPOLOGY,
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {
                    "backoffLimit": self.flaky_trips + 3,
                    # a small backoff so the scheduler's evacuation pass
                    # wins the race against the operator's recreate
                    "restartBackoffSeconds": 0.05,
                    "restartBackoffMaxSeconds": 0.2,
                },
            },
        }

    # the segment/env plumbing is PreemptionSoak's, verbatim — one
    # implementation of "run the real worker with the operator-rendered
    # env" shared by every scheduler soak
    _chief_env = PreemptionSoak._chief_env
    _run_segment = PreemptionSoak._run_segment
    _latest_step = staticmethod(PreemptionSoak._latest_step)

    def _gang_running(self, cluster) -> list[dict]:
        pods = cluster.list("v1", "Pod", self.namespace,
                            selector={"kubeflow.org/job-name":
                                      self.job_name})
        running = [p for p in pods
                   if p.get("status", {}).get("phase") == "Running"]
        return running if len(running) == 2 else []   # v5e-8 = 2 hosts

    def run(self) -> dict:
        from ..cluster.chaos import HostFault
        from ..cluster.fake import FakeCluster
        from ..controllers.runtime import Manager
        from ..controllers.tpujob import (RESTART_COUNT_ANNOTATION,
                                          TrainingJobReconciler)
        from .core import SliceScheduler
        from .health import HealthConfig, is_quarantined
        from .queue import SchedulerConfig, binding_of

        ckpt_dir = os.path.join(self.workdir, "job")
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes(POOL_TOPOLOGY, pool=self.FLAKY_POOL)
        cluster.add_tpu_slice_nodes(POOL_TOPOLOGY, pool=self.CLEAN_POOL)
        flaky_node = f"{self.FLAKY_POOL}-{POOL_TOPOLOGY}-1"
        fault = HostFault(node=flaky_node, mode="crash",
                          trips=self.flaky_trips)
        config = SchedulerConfig(health=HealthConfig(
            enabled=self.quarantine,
            # one crash (weight 1.0) is enough evidence in the drill;
            # 0.9 leaves room for the decay between fold and pass
            quarantine_threshold=0.9, release_threshold=0.5,
            quarantine_s=300.0))
        mgr = Manager(cluster)
        mgr.add(SliceScheduler(config))
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(self._manifest(ckpt_dir))

        # fault schedule: fire once the job has banked these steps (off
        # the checkpoint_every=2 boundaries -> real replay in the off
        # arm); trips beyond the schedule fire immediately on recreate
        fault_steps = [3, 4, 5][:self.flaky_trips]
        chief = f"{self.job_name}-worker-0-0"
        report: dict = {"outcome": "timeout", "restarts": 0,
                        "fires": 0, "rebinds": 0, "pools": [],
                        "executed_steps": 0, "checkpoint_dir": ckpt_dir,
                        "quarantine": self.quarantine}
        deadline = time.monotonic() + self.wall_budget_s
        first_fire_t = None
        recovered_t = None
        reached = 0
        last_pools = None
        while time.monotonic() < deadline:
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                              self.namespace, self.job_name)
            report["restarts"] = int(k8s.annotations_of(job).get(
                RESTART_COUNT_ANNOTATION, "0"))
            placement = binding_of(job)
            pools = sorted({r.pool for r in placement.slices}) \
                if placement else None
            if pools is not None and pools != last_pools:
                last_pools = pools
                report["pools"].append(pools)
                report["rebinds"] = len(report["pools"]) - 1
            if k8s.condition_true(job, "Succeeded"):
                report["outcome"] = "succeeded"
                break
            if k8s.condition_true(job, "Failed"):
                report["outcome"] = "failed"
                report["failed_reason"] = k8s.get_condition(
                    job, "Failed").get("reason")
                break
            running = self._gang_running(cluster)
            if not running or k8s.condition_true(job, "Restarting"):
                time.sleep(0.02)
                continue
            on_flaky = any(p.get("spec", {}).get("nodeName") == flaky_node
                           for p in running)
            if first_fire_t is not None and recovered_t is None and \
                    not (on_flaky and fault.fired < fault.trips):
                # fully Running with nothing left for the fault to hit:
                # the gang has outrun the flaky host (migrated, or the
                # host's budget is spent)
                recovered_t = time.monotonic()
                report["recovery_s"] = round(
                    recovered_t - first_fire_t, 3)
            due = fault.fired < len(fault_steps) and \
                reached >= fault_steps[fault.fired]
            late = fault.fired >= len(fault_steps)
            if on_flaky and fault.fired < fault.trips and (due or late):
                if fault.maybe_fire(cluster, self.namespace,
                                    at_step=reached):
                    report["fires"] = fault.fired
                    if first_fire_t is None:
                        first_fire_t = time.monotonic()
                    continue
            # train to the next fault step (if one is pending and the
            # gang still sits on the flaky host) or to the end
            target = fault_steps[fault.fired] \
                if (on_flaky and fault.fired < len(fault_steps)) \
                else self.total_steps
            if reached < target:
                resume = self._latest_step(ckpt_dir) or 0
                self._run_segment(self._chief_env(cluster, chief),
                                  target)
                report["executed_steps"] += target - resume
                reached = target
            if reached >= self.total_steps:
                cluster.set_pod_phase(self.namespace, chief, "Succeeded")
        node = cluster.get("v1", "Node", "", flaky_node)
        report["flaky_node"] = flaky_node
        report["flaky_quarantined"] = is_quarantined(node)
        report["final_pools"] = last_pools
        report["migrated"] = bool(last_pools and
                                  self.FLAKY_POOL not in last_pools)
        report["useful_work_fraction"] = round(
            self.total_steps / max(1, report["executed_steps"]), 4)
        for c in mgr.controllers:
            c.stop()
        return report

    def clean_params(self):
        """The parity reference: same seed and steps, no flaky host."""
        env_map = {"KFTPU_CHECKPOINT_DIR":
                   os.path.join(self.workdir, "clean")}
        self._run_segment(env_map, self.total_steps)
        from ..cluster.chaos import final_params
        return final_params(env_map["KFTPU_CHECKPOINT_DIR"])


# ------------------------------------------------- control-plane soak
# ISSUE 14: the chaos tier that kills the CONTROL PLANE itself. Every
# prior soak assumed an immortal operator/scheduler; here both run as
# lease-elected replica sets (cluster/lease.py) over per-replica chaos
# clients (cluster/chaos.py ControllerChaos), and the faults are
# controller deaths mid-write, apiserver partitions, and split-brain
# windows — while a real TPUJob must still train to Succeeded with
# params identical to an undisturbed run.


def _make_audit_cluster():
    """A FakeCluster that audits the two invariants the acceptance
    criteria name: (1) duplicate pod creates (two leaders racing
    _ensure_pods — the second create hits AlreadyExists); (2) lost
    annotation writes (every observed restart-count value, in write
    order — a lost update shows up as a repeat or a skip; binding
    rewrites counted the same way)."""
    from ..api.trainingjob import BINDING_ANNOTATION
    from ..cluster.client import AlreadyExistsError
    from ..cluster.fake import FakeCluster
    from ..controllers.tpujob import RESTART_COUNT_ANNOTATION

    class Audit(FakeCluster):
        def __init__(self):
            super().__init__()
            self.duplicate_pod_creates = 0
            self.restart_count_writes: list[int] = []
            self.binding_writes = 0

        def create(self, obj):
            try:
                return super().create(obj)
            except AlreadyExistsError:
                if obj.get("kind") == "Pod":
                    self.duplicate_pod_creates += 1
                raise

        def _store_update(self, obj, *, check_rv=True):
            key = self._key(obj)
            prev = self._objects.get(key) or {}
            prev_anns = (prev.get("metadata") or {}) \
                .get("annotations") or {}
            out = super()._store_update(obj, check_rv=check_rv)
            if key[1] == "TPUJob":
                anns = (out.get("metadata") or {}) \
                    .get("annotations") or {}
                rc = anns.get(RESTART_COUNT_ANNOTATION)
                if rc is not None and \
                        rc != prev_anns.get(RESTART_COUNT_ANNOTATION):
                    self.restart_count_writes.append(int(rc))
                if anns.get(BINDING_ANNOTATION) != \
                        prev_anns.get(BINDING_ANNOTATION):
                    self.binding_writes += 1
            return out

    return Audit()


class _CtrlReplica:
    """One control-plane replica: its own 'connection' (ControllerChaos
    — killable, partitionable), a mutation recorder (the zero-writes-
    while-follower audit), a lease elector, and a fencing client
    wrapped around the controller's write path. The stack mirrors the
    deployed shape: replicas: 2 Deployments whose pods each hold one
    apiserver connection and one Lease identity."""

    def __init__(self, role: str, index: int, cluster,
                 make_reconciler, lease_name: str,
                 lease_duration_s: float):
        from ..cluster.chaos import ControllerChaos, RecordingKubeClient
        from ..cluster.lease import FencedKubeClient, LeaderElector
        from ..controllers.runtime import Controller
        self.role = role
        self.identity = f"{role}-{index}"
        self.chaos = ControllerChaos(cluster)
        self.recorder = RecordingKubeClient(self.chaos)
        self.elector = LeaderElector(
            client=self.chaos, identity=self.identity, name=lease_name,
            duration_s=lease_duration_s)
        self.fenced = FencedKubeClient(self.recorder, self.elector)
        self.controller = Controller(
            reconciler=make_reconciler(), client=self.fenced,
            elector=self.elector, retry_backoff_s=0.01,
            retry_backoff_max_s=0.1)
        self.controller.bind_watches()
        self.controller.enqueue_existing()
        self.ever_leader = False
        self.alive = True

    def pump(self) -> None:
        if not self.alive:
            return
        self.controller.run_pending(max_iters=50)
        if self.elector.is_leader:
            self.ever_leader = True

    def kill(self) -> None:
        """Process death: connection gone, in-memory state gone, lease
        left to EXPIRE (no graceful release — that is the point)."""
        self.alive = False
        self.chaos.kill()
        self.controller.stop()


@dataclass
class ControlPlaneSoak:
    """A real TPUJob trains to Succeeded while the operator and the
    scheduler are killed and re-elected and the apiserver partitions —
    the control-plane analog of ChaosSoak. Both roles run as TWO
    lease-elected replicas; a kill takes the current leader (armed to
    die right AFTER a write lands — the crash-consistency window) and
    spawns a replacement standby; the surviving standby must steal the
    lease, adopt the half-done state (half-created gangs, fresh
    bindings), and finish the job. Acceptance (bench.py --mode
    ctrl-chaos): Succeeded with params parity vs a clean run, zero
    duplicate pod creates, zero lost annotation writes, zero mutations
    from any replica that never led, and measured failover times."""

    workdir: str
    total_steps: int = 8
    checkpoint_every: int = 2
    operator_kills: int = 3
    scheduler_kills: int = 2
    partitions: int = 2
    lease_duration_s: float = 0.5
    seed: int = 0
    global_batch: int = 8
    wall_budget_s: float = 420.0
    namespace: str = "kubeflow"
    job_name: str = "ctrl-soak"

    _chief_env = PreemptionSoak._chief_env
    _run_segment = PreemptionSoak._run_segment
    _latest_step = staticmethod(PreemptionSoak._latest_step)

    def _manifest(self, ckpt_dir: str) -> dict:
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": self.job_name,
                         "namespace": self.namespace},
            "spec": {
                "checkpointDir": ckpt_dir,
                "schedulingPolicy": {"queue": "research", "priority": 0,
                                     "preemptible": False},
                "replicaSpecs": {"TPU": {
                    "tpuTopology": POOL_TOPOLOGY,
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {
                    "backoffLimit": self.operator_kills
                    + self.scheduler_kills + 6,
                    "restartBackoffSeconds": 0.02,
                    "restartBackoffMaxSeconds": 0.2,
                },
            },
        }

    def _fault_schedule(self) -> list:
        """Interleave the fault kinds over the training steps: one fault
        per step from step 2 on, operator kills first (they stress the
        gang-create path), scheduler kills next, partitions woven in."""
        kinds = []
        for i in range(max(self.operator_kills, self.scheduler_kills,
                           self.partitions)):
            if i < self.operator_kills:
                kinds.append("kill-operator")
            if i < self.scheduler_kills:
                kinds.append("kill-scheduler")
            if i < self.partitions:
                kinds.append("apiserver-partition")
        start = 2
        last = max(self.total_steps - 1, start)
        return [(min(start + i, last), kind)
                for i, kind in enumerate(kinds)]

    def run(self) -> dict:
        from ..cluster.lease import OPERATOR_LEASE, SCHEDULER_LEASE
        from ..controllers.tpujob import (RESTART_COUNT_ANNOTATION,
                                          TrainingJobReconciler)
        from .core import SliceScheduler
        from .queue import SchedulerConfig

        ckpt_dir = os.path.join(self.workdir, "job")
        cluster = _make_audit_cluster()
        cluster.add_tpu_slice_nodes(POOL_TOPOLOGY)
        cluster.create(self._manifest(ckpt_dir))

        # Health scoring stays out of this soak's way: the pods the
        # fault injector fails are CONTROLLER-KILL collateral, not host
        # evidence — at the default threshold the repeated crashes
        # would quarantine+cordon a host of the only pool and starve
        # the gang, turning a control-plane drill into a capacity test
        # (HealthSoak owns that scenario).
        from .health import HealthConfig
        sched_config = SchedulerConfig(
            grow_cooldown_s=0.0,
            health=HealthConfig(quarantine_threshold=1e9))
        roles = {
            "operator": dict(
                lease=OPERATOR_LEASE, next_index=0, replicas=[],
                make=lambda: TrainingJobReconciler("TPUJob")),
            "scheduler": dict(
                lease=SCHEDULER_LEASE, next_index=0, replicas=[],
                make=lambda: SliceScheduler(sched_config)),
        }
        retired: list = []   # killed replicas, kept for the write audit

        def spawn(role: str) -> _CtrlReplica:
            r = roles[role]
            rep = _CtrlReplica(role, r["next_index"], cluster, r["make"],
                               r["lease"], self.lease_duration_s)
            r["next_index"] += 1
            r["replicas"].append(rep)
            return rep

        for role in roles:
            spawn(role)
            spawn(role)

        report: dict = {"outcome": "timeout", "injected": [],
                        "segments": 0, "executed_steps": 0,
                        "failovers": {"operator": 0, "scheduler": 0},
                        "failover_s": [], "partitions": 0,
                        "checkpoint_dir": ckpt_dir}
        pending_failover: dict = {}   # role -> kill time

        def leader_of(role: str):
            return next((rep for rep in roles[role]["replicas"]
                         if rep.alive and rep.elector.is_leader), None)

        def pump(ticks: int = 2) -> None:
            for _ in range(ticks):
                for role in roles:
                    for rep in list(roles[role]["replicas"]):
                        rep.pump()
                    if role in pending_failover and \
                            leader_of(role) is not None:
                        report["failover_s"].append(round(
                            time.monotonic()
                            - pending_failover.pop(role), 3))
                        report["failovers"][role] += 1
                cluster.tick()

        def inject(kind: str) -> None:
            if kind == "apiserver-partition":
                # every live connection loses the apiserver: leaders
                # cannot renew, reconciles see transient errors
                report["injected"].append(kind)
                seconds = self.lease_duration_s * 2.5
                for role in roles:
                    for rep in roles[role]["replicas"]:
                        if rep.alive:
                            rep.chaos.partition(seconds)
                report["partitions"] += 1
                time.sleep(seconds + 0.05)
                return
            role = "operator" if kind == "kill-operator" else "scheduler"
            # a kill needs a leader to kill: right after a partition both
            # replicas may briefly be followers — wait for the next
            # election instead of silently counting a fault that never
            # happened (the bench's failovers-vs-kills check depends on
            # every counted kill being real)
            wait_leader = time.monotonic() + \
                max(5.0, self.lease_duration_s * 10)
            leader = leader_of(role)
            while leader is None and time.monotonic() < wait_leader:
                pump()
                time.sleep(0.01)
                leader = leader_of(role)
            if leader is None:
                report.setdefault("skipped", []).append(
                    f"{kind}: no {role} leader to kill")
                return
            report["injected"].append(kind)
            victim_pods = sorted(
                k8s.name_of(p)
                for p in cluster.list("v1", "Pod", self.namespace))
            # rotate the collateral victim across hosts so no single
            # node soaks up every crash attribution
            victim = victim_pods[len(report["injected"])
                                 % len(victim_pods)] \
                if victim_pods else None
            if kind == "kill-operator" and victim:
                # die mid-gang-create: fail a pod, then the leader dies
                # right after its FIRST recreate lands — a half-created
                # gang the successor must adopt
                leader.chaos.die_after("create", 1)
                cluster.fail_pod(self.namespace, victim,
                                 "chaos: worker died under the operator")
            else:
                # scheduler leader dies right after its next annotation
                # write lands (binding/state rewrite mid-flight; lease
                # renewals are exempt from kill-points, so this really
                # is a controller write)
                leader.chaos.die_after("update", 1)
                if victim:
                    cluster.fail_pod(self.namespace, victim,
                                     "chaos: worker died under the "
                                     "scheduler kill")
            # drive until the armed death fires (or the leader is idle —
            # then kill it outright; a quiescent leader dies too)
            deadline = time.monotonic() + 5.0
            while not leader.chaos.dead and \
                    time.monotonic() < deadline:
                pump()
                time.sleep(0.01)
            if not leader.chaos.dead:
                leader.chaos.kill()
            leader.kill()
            retired.append(leader)
            roles[role]["replicas"].remove(leader)
            pending_failover[role] = time.monotonic()
            spawn(role)   # the replacement standby

        pending = sorted(self._fault_schedule())
        deadline = time.monotonic() + self.wall_budget_s
        chief = f"{self.job_name}-worker-0-0"
        reached = 0
        while time.monotonic() < deadline:
            pump()
            job = cluster.get_or_none("tpu.kubeflow.org/v1alpha1",
                                      "TPUJob", self.namespace,
                                      self.job_name)
            if job is None:
                report["outcome"] = "deleted"
                break
            if k8s.condition_true(job, "Succeeded"):
                report["outcome"] = "succeeded"
                break
            if k8s.condition_true(job, "Failed"):
                report["outcome"] = "failed"
                report["failed_reason"] = k8s.get_condition(
                    job, "Failed").get("reason")
                break
            pods = cluster.list("v1", "Pod", self.namespace)
            running = [p for p in pods
                       if p.get("status", {}).get("phase") == "Running"]
            if len(running) != 2 or \
                    k8s.condition_true(job, "Restarting"):
                time.sleep(0.02)
                continue
            target = min(pending[0][0], self.total_steps) if pending \
                else self.total_steps
            result = self._run_segment(
                self._chief_env(cluster, chief), target)
            report["segments"] += 1
            report["executed_steps"] += int(result.steps)
            reached = max(reached, target)
            if pending and pending[0][0] <= reached:
                _, kind = pending.pop(0)
                inject(kind)
                continue
            if reached >= self.total_steps:
                cluster.set_pod_phase(self.namespace, chief, "Succeeded")
        job = cluster.get_or_none("tpu.kubeflow.org/v1alpha1", "TPUJob",
                                  self.namespace, self.job_name)
        if job is not None:
            report["gang_restarts"] = int(k8s.annotations_of(job).get(
                RESTART_COUNT_ANNOTATION, "0"))
        report["final_step"] = reached
        # ---- the write audit -------------------------------------------
        report["duplicate_pod_creates"] = cluster.duplicate_pod_creates
        rc = cluster.restart_count_writes
        report["restart_count_writes"] = rc
        # the invariant: observed restart-count values are EXACTLY
        # 1..N in write order — a lost update shows as a repeat or skip
        report["lost_annotation_writes"] = \
            rc != list(range(1, len(rc) + 1))
        report["binding_writes"] = cluster.binding_writes
        all_reps = retired + [rep for r in roles.values()
                              for rep in r["replicas"]]
        report["replicas_spawned"] = len(all_reps)
        report["never_leader_mutations"] = sum(
            len(rep.recorder.mutations) for rep in all_reps
            if not rep.ever_leader)
        report["fenced_rejections"] = sum(
            rep.fenced.rejected for rep in all_reps)
        for r in roles.values():
            for rep in r["replicas"]:
                rep.controller.stop()
        return report

    def clean_params(self):
        """The parity reference: same seed/steps/batch, no faults."""
        env_map = {"KFTPU_CHECKPOINT_DIR":
                   os.path.join(self.workdir, "clean")}
        self._run_segment(env_map, self.total_steps)
        from ..cluster.chaos import final_params
        return final_params(env_map["KFTPU_CHECKPOINT_DIR"])


def split_brain_drill(lease_duration_s: float = 0.4) -> dict:
    """The two-leaders-briefly window, made observable: partition the
    operator leader away from the apiserver, let the standby steal the
    lease at expiry, then prove the fence holds — the old leader
    demotes on its own clock, its write attempts raise FencingError
    client-side (counted, never reaching the wire), its recorder shows
    zero mutations after the steal, and no pod was ever double-created.
    This is the drill `bench.py --mode ctrl-chaos` asserts on."""
    from ..controllers.runtime import Controller
    from ..controllers.tpujob import TrainingJobReconciler
    from ..cluster.chaos import ControllerChaos, RecordingKubeClient
    from ..cluster.lease import (FencedKubeClient, FencingError,
                                 LeaderElector, OPERATOR_LEASE)

    cluster = _make_audit_cluster()
    cluster.add_tpu_slice_nodes(POOL_TOPOLOGY)
    cluster.create({
        "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "drill", "namespace": "kubeflow"},
        "spec": {"replicaSpecs": {"TPU": {
            "tpuTopology": POOL_TOPOLOGY,
            "template": {"spec": {"containers": [
                {"name": "jax", "image": "trainer:v1"}]}}}}},
    })

    class Rep:
        def __init__(self, ident: str):
            self.chaos = ControllerChaos(cluster)
            self.recorder = RecordingKubeClient(self.chaos)
            self.elector = LeaderElector(
                client=self.chaos, identity=ident,
                name=OPERATOR_LEASE, duration_s=lease_duration_s)
            self.fenced = FencedKubeClient(self.recorder, self.elector)
            self.controller = Controller(
                reconciler=TrainingJobReconciler("TPUJob"),
                client=self.fenced, elector=self.elector,
                retry_backoff_s=0.01, retry_backoff_max_s=0.1)
            self.controller.bind_watches()
            self.controller.enqueue_existing()

    a, b = Rep("op-a"), Rep("op-b")
    for _ in range(4):
        a.controller.run_pending()
        b.controller.run_pending()
        cluster.tick()
    report: dict = {"initial_leader_elected": a.elector.is_leader,
                    "pods_created": len(
                        cluster.list("v1", "Pod", "kubeflow"))}
    writes_before = len(a.recorder.mutations)

    # partition the leader; the standby steals at expiry
    a.chaos.partition(lease_duration_s * 3)
    deadline = time.monotonic() + lease_duration_s * 10
    while time.monotonic() < deadline and not b.elector.is_leader:
        b.controller.run_pending()
        a.controller.run_pending()
        time.sleep(0.02)
    report["stolen_by_standby"] = b.elector.is_leader
    report["old_leader_demoted"] = not a.elector.is_leader

    # the deposed leader tries to write anyway — the fence must reject
    # it client-side, before it can race the new leader
    try:
        a.fenced.patch("tpu.kubeflow.org/v1alpha1", "TPUJob",
                       "kubeflow", "drill",
                       {"metadata": {"annotations":
                                     {"drill/zombie-write": "1"}}})
        report["fenced_write_rejected"] = False
    except FencingError:
        report["fenced_write_rejected"] = True

    for _ in range(4):
        a.controller.run_pending()
        b.controller.run_pending()
        cluster.tick()
    report["old_leader_writes_after_steal"] = \
        len(a.recorder.mutations) - writes_before
    report["fenced_rejections"] = a.fenced.rejected
    report["doubled_pod_creates"] = cluster.duplicate_pod_creates
    job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob", "kubeflow",
                      "drill")
    report["zombie_write_landed"] = "drill/zombie-write" in \
        k8s.annotations_of(job)
    a.controller.stop()
    b.controller.stop()
    return report
