"""Preemption soak: checkpoint-resume parity through a REAL preemption.

The chaos-soak pattern (cluster/chaos.py) applied to the scheduler: a
preemptible low-priority job trains on the only pool, a high-priority job
arrives and reclaims its slices mid-run, the victim re-queues, re-binds
once the winner finishes, resumes from its own checkpoints, and
completes. The acceptance bar is numeric: the victim's final params must
match an UNCONTENDED run of the same seed to float tolerance — the
scheduler's preemption path must cost progress, never correctness.

Control plane is real (FakeCluster + SliceScheduler + the TPUJob
reconciler); the data plane is real too — each time a gang is fully
Running, a real training segment (runtime/worker.train, tiny transformer
on the CPU mesh) runs in-process with the env the operator rendered into
the chief pod. Used by ``bench.py --mode sched`` and the slow scheduler
tests.

jax-free at import time (worker.train imports lazily inside run()).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from ..api import k8s
from ..api.trainingjob import (BINDING_ANNOTATION, COND_QUEUED,
                               PREEMPTED_COUNT_ANNOTATION)

POOL_TOPOLOGY = "v5e-8"


@dataclass
class PreemptionSoak:
    """Two jobs contending for one v5e-8 pool; the scripted outcome is
    victim-preempted → winner-runs → victim-resumes, all through the
    real scheduler/operator loop."""

    workdir: str
    total_steps: int = 8
    checkpoint_every: int = 2
    preempt_at: int = 4          # victim's progress when the winner lands
    seed: int = 0
    global_batch: int = 8
    wall_budget_s: float = 300.0
    namespace: str = "kubeflow"

    def _manifest(self, name: str, ckpt_dir: str, priority: int,
                  preemptible: bool) -> dict:
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": self.namespace},
            "spec": {
                "checkpointDir": ckpt_dir,
                "schedulingPolicy": {"queue": "research",
                                     "priority": priority,
                                     "preemptible": preemptible},
                "replicaSpecs": {"TPU": {
                    "tpuTopology": POOL_TOPOLOGY,
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {"backoffLimit": 3},
            },
        }

    def _chief_env(self, cluster, chief: str) -> dict:
        pod = cluster.get("v1", "Pod", self.namespace, chief)
        return {e["name"]: e.get("value", "")
                for e in pod["spec"]["containers"][0].get("env", [])}

    def _run_segment(self, env_map: dict, target: int):
        from ..obs.trace import adopt_trace_env
        from ..runtime.worker import train  # lazy: pulls in jax
        # adopt the operator-rendered trace contract for the segment:
        # the in-process "worker" must read the SAME env a real pod
        # would, so its window spans stitch onto the job's trace id
        # (bench.py --mode obs asserts the end-to-end timeline; the
        # goodput ledger accounts the soak from the same stream)
        with adopt_trace_env(env_map):
            return train(
                workload="transformer", steps=target,
                global_batch=self.global_batch, sync_every=1,
                checkpoint_dir=env_map.get("KFTPU_CHECKPOINT_DIR"),
                checkpoint_every=self.checkpoint_every,
                resume_from=env_map.get("KFTPU_RESUME_FROM"),
                seed=self.seed, handle_sigterm=False, workload_kwargs={})

    def _gang_running(self, cluster, name: str) -> bool:
        pods = cluster.list("v1", "Pod", self.namespace,
                            selector={"kubeflow.org/job-name": name})
        running = [p for p in pods
                   if p.get("status", {}).get("phase") == "Running"]
        return len(running) == 2   # v5e-8 = 2 hosts

    def run(self) -> dict:
        from ..cluster.fake import FakeCluster
        from ..controllers.runtime import Manager
        from ..controllers.tpujob import TrainingJobReconciler
        from .core import SliceScheduler

        # preempt_at on a checkpoint boundary mirrors the real reclaim:
        # SIGTERM forces a save before exit 75, so the victim's on-disk
        # state is exactly its progress at preemption
        assert self.preempt_at % self.checkpoint_every == 0, \
            "preempt_at must land on a checkpoint boundary"
        ckpt_victim = os.path.join(self.workdir, "victim")
        ckpt_winner = os.path.join(self.workdir, "winner")
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes(POOL_TOPOLOGY)
        mgr = Manager(cluster)
        mgr.add(SliceScheduler())
        mgr.add(TrainingJobReconciler("TPUJob"))
        report: dict = {"events": [], "outcome": "timeout",
                        "checkpoint_dir": ckpt_victim}

        def pump(ticks: int = 3) -> None:
            for _ in range(ticks):
                mgr.run_pending()
                cluster.tick()
            mgr.run_pending()

        def job(name: str) -> dict:
            return cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                               self.namespace, name)

        cluster.create(self._manifest("victim", ckpt_victim,
                                      priority=0, preemptible=True))
        deadline = time.monotonic() + self.wall_budget_s
        pump()
        if not self._gang_running(cluster, "victim"):
            report["outcome"] = "victim-never-bound"
            return self._finish(report, mgr)

        # victim trains to the preemption point
        seg = self._run_segment(
            self._chief_env(cluster, "victim-worker-0-0"),
            self.preempt_at)
        # executed-step ledger: the ground truth bench.py --mode goodput
        # checks the span-derived restart-recompute number against
        report["victim_executed_steps"] = int(seg.steps)
        report["events"].append(f"victim reached step {self.preempt_at}")

        # the winner lands: higher priority, same (full-pool) shape
        cluster.create(self._manifest("winner", ckpt_winner,
                                      priority=10, preemptible=False))
        while time.monotonic() < deadline:
            pump()
            v = job("victim")
            if not k8s.annotations_of(v).get(BINDING_ANNOTATION) and \
                    k8s.condition_true(v, COND_QUEUED) and \
                    self._gang_running(cluster, "winner"):
                break
        else:
            report["outcome"] = "preemption-never-happened"
            return self._finish(report, mgr)
        v = job("victim")
        report["victim_preempted_count"] = int(k8s.annotations_of(v).get(
            PREEMPTED_COUNT_ANNOTATION, "0"))
        report["victim_resume_from"] = v["spec"].get("resumeFrom", "")
        report["events"].append("victim preempted, winner running")

        # winner trains to completion and succeeds
        self._run_segment(self._chief_env(cluster, "winner-worker-0-0"),
                          self.total_steps)
        cluster.set_pod_phase(self.namespace, "winner-worker-0-0",
                              "Succeeded")
        # winner done -> its binding releases -> victim re-binds
        while time.monotonic() < deadline:
            pump()
            if k8s.condition_true(job("winner"), "Succeeded") and \
                    self._gang_running(cluster, "victim"):
                break
        else:
            report["outcome"] = "victim-never-rebound"
            return self._finish(report, mgr)
        report["events"].append("winner succeeded, victim re-bound")

        # victim resumes from its own checkpoints and completes; the
        # resume step is whatever survived on disk — it must be the
        # forced save at preemption, not step 0 (a silent replay would
        # still pass the parity check while wasting the whole first run)
        env_map = self._chief_env(cluster, "victim-worker-0-0")
        report["victim_rebind_resume_env"] = env_map.get(
            "KFTPU_RESUME_FROM", "")
        report["victim_resume_step"] = self._latest_step(ckpt_victim)
        seg = self._run_segment(env_map, self.total_steps)
        report["victim_executed_steps"] += int(seg.steps)
        cluster.set_pod_phase(self.namespace, "victim-worker-0-0",
                              "Succeeded")
        while time.monotonic() < deadline:
            pump()
            if k8s.condition_true(job("victim"), "Succeeded"):
                report["outcome"] = "succeeded"
                break
        # the victim's final manifest rides along so callers can read
        # its annotations (trace id — bench.py --mode obs reconstructs
        # the victim's end-to-end timeline from the span sink)
        report["victim_manifest"] = job("victim")
        return self._finish(report, mgr)

    @staticmethod
    def _latest_step(ckpt_dir: str):
        from ..runtime.checkpoint import CheckpointManager  # lazy: jax
        mgr = CheckpointManager(ckpt_dir)
        try:
            return mgr.latest_step()
        finally:
            mgr.close()

    def _finish(self, report: dict, mgr) -> dict:
        for c in mgr.controllers:
            c.stop()
        return report

    def uncontended_params(self):
        """The parity reference: the victim's workload run start-to-finish
        with the same seed and no contention."""
        env_map = {"KFTPU_CHECKPOINT_DIR":
                   os.path.join(self.workdir, "clean")}
        self._run_segment(env_map, self.total_steps)
        from ..cluster.chaos import final_params
        return final_params(env_map["KFTPU_CHECKPOINT_DIR"])


@dataclass
class ElasticSoak:
    """Shrink-to-survive → grow-to-fill, end to end on the real loop.

    One ELASTIC TPUJob (``schedulingPolicy.minChips=4, maxChips=8``,
    ``weightUpdate=sharded`` so the optimizer state is genuinely
    distributed over the replica axes) trains on a single two-host
    v5e-8 pool. Mid-run a host VANISHES (cluster/chaos.py CapacityLoss
    deletes the node object): no same-size rectangle exists anywhere,
    so the pre-elastic scheduler could only strand the job in Queued —
    here the replan binds it DEGRADED at v5e-4 on the surviving host,
    the operator restarts the gang at the smaller shape with
    ``resumeFrom``, and the worker's restore reshapes the sharded
    optimizer state from replica degree 8 to 4 (runtime/checkpoint.py).
    Later the host returns; the grow-to-fill pass resizes the binding
    back to v5e-8 and the job finishes at full width.

    Acceptance is numeric: the job ends Succeeded; the final checkpoint
    restores IDENTICALLY (≤1e-5, in practice 0.0) into replica-degree-8
    and replica-degree-4 templates — the cross-degree round trip is
    lossless; and final params track an undisturbed same-seed run to a
    reported tolerance (cross-degree float drift is reduction-order
    only, ~1e-4 — reported, not hidden)."""

    workdir: str
    total_steps: int = 8
    checkpoint_every: int = 2
    lose_at: int = 3             # host vanishes after this many steps
    restore_at: int = 5          # ...and returns once the job reaches this
    # False = shrink-to-survive only: the host never comes back and the
    # job must still finish Succeeded at the degraded width (the
    # ``bench.py --mode chaos`` capacity-loss scenario; the full
    # shrink→grow arc runs under --mode sched)
    grow_phase: bool = True
    seed: int = 0
    global_batch: int = 8
    wall_budget_s: float = 300.0
    namespace: str = "kubeflow"
    job_name: str = "elastic-soak"

    POOL = "pool-a"

    def _manifest(self, ckpt_dir: str) -> dict:
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": self.job_name,
                         "namespace": self.namespace},
            "spec": {
                "checkpointDir": ckpt_dir,
                "weightUpdate": "sharded",
                "schedulingPolicy": {"queue": "research", "priority": 0,
                                     "minChips": 4, "maxChips": 8},
                "replicaSpecs": {"TPU": {
                    "tpuTopology": POOL_TOPOLOGY,
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {
                    "backoffLimit": 6,
                    "restartBackoffSeconds": 0.05,
                    "restartBackoffMaxSeconds": 0.2,
                },
            },
        }

    _chief_env = PreemptionSoak._chief_env
    _latest_step = staticmethod(PreemptionSoak._latest_step)

    def _ctx(self, devices: int):
        """A WorkerContext over the first ``devices`` CPU devices — the
        in-process stand-in for the resized gang's smaller mesh."""
        import jax

        from ..api.trainingjob import ShardingSpec
        from ..parallel.mesh import build_mesh
        from ..runtime.bootstrap import WorkerContext
        mesh = build_mesh(ShardingSpec(),
                          list(jax.devices())[:devices])
        return WorkerContext(contract=None, sharding=ShardingSpec(),
                             mesh=mesh, process_id=0, num_processes=1)

    def _run_segment(self, env_map: dict, target: int):
        """One real training segment at the CURRENTLY BOUND size: the
        chief env's topology contract names the resized shape, and the
        segment's mesh uses exactly that many devices — so restores
        genuinely cross replica degrees."""
        import jax

        from ..api.topology import TopologyContract, parse_topology
        from ..runtime.worker import train  # lazy: pulls in jax
        topo_name = env_map.get(TopologyContract.ENV_TOPOLOGY,
                                POOL_TOPOLOGY)
        chips = min(parse_topology(topo_name).num_chips,
                    len(jax.devices()))
        return train(
            workload="transformer", steps=target,
            global_batch=self.global_batch, sync_every=1,
            checkpoint_dir=env_map.get("KFTPU_CHECKPOINT_DIR"),
            checkpoint_every=self.checkpoint_every,
            resume_from=env_map.get("KFTPU_RESUME_FROM"),
            weight_update=env_map.get("KFTPU_WEIGHT_UPDATE"),
            seed=self.seed, handle_sigterm=False,
            ctx=self._ctx(chips), workload_kwargs={})

    def _state_template(self, degree: int):
        """An abstract TrainState template at the given replica degree,
        built exactly the way train() builds its state (same workload,
        optimizer, weight-update mode) — the restore target the
        cross-degree round-trip check reshapes into."""
        import jax

        from ..runtime.recipe import make_optimizer
        from ..runtime.trainstep import TrainStepBuilder
        from ..runtime.worker import WORKLOADS
        ctx = self._ctx(degree)
        spec = WORKLOADS["transformer"]()
        opt, _ = make_optimizer("momentum", 0.1, schedule="constant",
                                total_steps=self.total_steps)
        builder = TrainStepBuilder(mesh=ctx.mesh, loss_fn=spec.loss_fn,
                                   optimizer=opt, rules=spec.rules,
                                   param_logical_axes=spec.param_logical_axes,
                                   weight_update="sharded")
        return builder.init(spec.init_fn, jax.random.PRNGKey(self.seed))

    def roundtrip_delta(self, ckpt_dir: str,
                        degrees: tuple = (8, 4)) -> float:
        """Restore the newest checkpoint into templates at BOTH replica
        degrees and compare every leaf (params, sharded optimizer
        moments, rng, step): the cross-degree reshape must be lossless.
        Returns the max abs delta across all leaves."""
        import jax
        import numpy as np

        from ..runtime.checkpoint import CheckpointManager
        states = []
        for d in degrees:
            mgr = CheckpointManager(ckpt_dir)
            try:
                states.append(mgr.restore(self._state_template(d)))
            finally:
                mgr.close()
        deltas = jax.tree.map(
            lambda a, b: float(np.max(np.abs(
                np.asarray(a, dtype=np.float64)
                - np.asarray(b, dtype=np.float64)))) if hasattr(
                    a, "dtype") else 0.0,
            states[0], states[1])
        return max(jax.tree.leaves(deltas), default=0.0)

    def _gang_running(self, cluster, want: int) -> bool:
        pods = cluster.list("v1", "Pod", self.namespace,
                            selector={"kubeflow.org/job-name":
                                      self.job_name})
        running = [p for p in pods
                   if p.get("status", {}).get("phase") == "Running"]
        return len(running) == want

    def run(self) -> dict:
        from ..api.trainingjob import RESIZE_HISTORY_ANNOTATION
        from ..cluster.chaos import CapacityLoss
        from ..cluster.fake import FakeCluster
        from ..controllers.runtime import Manager
        from ..controllers.tpujob import TrainingJobReconciler
        from .core import SliceScheduler
        from .queue import SchedulerConfig, binding_of

        ckpt_dir = os.path.join(self.workdir, "job")
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes(POOL_TOPOLOGY, pool=self.POOL)
        lost_node = f"{self.POOL}-{POOL_TOPOLOGY}-1"
        fault = CapacityLoss(node=lost_node)
        mgr = Manager(cluster)
        # no grow cooldown: the soak compresses hours into seconds
        mgr.add(SliceScheduler(SchedulerConfig(grow_cooldown_s=0.0)))
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(self._manifest(ckpt_dir))

        chief = f"{self.job_name}-worker-0-0"
        report: dict = {"outcome": "timeout", "events": [],
                        "chips_seen": [], "checkpoint_dir": ckpt_dir}
        deadline = time.monotonic() + self.wall_budget_s

        def pump(ticks: int = 3) -> None:
            for _ in range(ticks):
                mgr.run_pending()
                cluster.tick()
            mgr.run_pending()

        def job() -> dict:
            return cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                               self.namespace, self.job_name)

        def note_chips() -> int:
            placement = binding_of(job())
            chips = placement.chips if placement else 0
            if chips and (not report["chips_seen"]
                          or report["chips_seen"][-1] != chips):
                report["chips_seen"].append(chips)
            return chips

        def wait_for(pods: int, chips: int, tag: str) -> bool:
            while time.monotonic() < deadline:
                pump()
                if note_chips() == chips and \
                        self._gang_running(cluster, pods):
                    return True
                time.sleep(0.02)
            report["outcome"] = f"timeout: {tag}"
            return False

        # phase 1: bind + train at nominal width until the host dies
        if not wait_for(2, 8, "never bound at nominal"):
            return self._finish(report, mgr)
        report["events"].append("bound at 8 chips (2 hosts)")
        self._run_segment(self._chief_env(cluster, chief), self.lose_at)
        report["events"].append(f"trained to step {self.lose_at} @8")

        # phase 2: the host vanishes -> shrink-to-survive at v5e-4
        fault.fire(cluster)
        if not wait_for(1, 4, "never shrank after capacity loss"):
            return self._finish(report, mgr)
        report["events"].append("host lost; re-bound DEGRADED at 4 chips")
        report["shrink_resume_step"] = self._latest_step(ckpt_dir)
        # cross-degree round trip at the shrink point: the state saved
        # at degree 8 must restore losslessly into the degree-4 layout
        report["roundtrip_delta_at_shrink"] = self.roundtrip_delta(
            ckpt_dir, degrees=(8, 4))
        if self.grow_phase:
            self._run_segment(self._chief_env(cluster, chief),
                              self.restore_at)
            report["events"].append(
                f"trained degraded to step {self.restore_at} @4")

            # phase 3: capacity returns -> grow-to-fill back to v5e-8
            fault.restore(cluster)
            if not wait_for(2, 8, "never grew after capacity returned"):
                return self._finish(report, mgr)
            report["events"].append("capacity back; grown to 8 chips")
            report["grow_resume_step"] = self._latest_step(ckpt_dir)
        self._run_segment(self._chief_env(cluster, chief),
                          self.total_steps)
        cluster.set_pod_phase(self.namespace, chief, "Succeeded")
        while time.monotonic() < deadline:
            pump()
            if k8s.condition_true(job(), "Succeeded"):
                report["outcome"] = "succeeded"
                break
        report["resize_history"] = k8s.annotations_of(job()).get(
            RESIZE_HISTORY_ANNOTATION, "")
        report["roundtrip_delta_final"] = self.roundtrip_delta(
            ckpt_dir, degrees=(8, 4))
        return self._finish(report, mgr)

    def clean_params(self):
        """The parity reference: same seed/steps/batch, full width the
        whole way (no capacity loss). Final params differ from the
        shrink→grow run only by cross-degree reduction order (~1e-4) —
        the report carries the measured delta."""
        env_map = {"KFTPU_CHECKPOINT_DIR":
                   os.path.join(self.workdir, "clean"),
                   "KFTPU_WEIGHT_UPDATE": "sharded"}
        self._run_segment(env_map, self.total_steps)
        from ..cluster.chaos import final_params
        return final_params(env_map["KFTPU_CHECKPOINT_DIR"])

    def _finish(self, report: dict, mgr) -> dict:
        for c in mgr.controllers:
            c.stop()
        return report


@dataclass
class HealthSoak:
    """Flaky-host migration drill: quarantine on vs off, end to end.

    One scheduler-managed TPUJob trains on a two-pool cluster whose
    first pool carries a FLAKY HOST (cluster/chaos.py HostFault): every
    time a gang pod lands on it, the pod dies — the recurring
    host-pinned failure the node-health subsystem exists for. With
    ``quarantine=True`` (health enabled in the scheduler config) the
    first crash records the suspect, the scheduler evacuates the
    binding off the host's cells within ONE rebind, and the gang
    finishes on the clean pool; with ``quarantine=False`` recovery is
    placement-blind — the gang crash-loops on the flaky host until the
    fault's trips budget runs out (the host "recovers"), burning a
    restart per trip. Both arms must finish Succeeded with final params
    IDENTICAL to a clean run (``bench.py --mode health`` asserts
    parity 0.0): the health path changes WHERE the gang runs, never
    what it computes.

    The fault fires on a step schedule (after steps 3, 4, 5 — off
    checkpoint boundaries) so the off arm pays real replay, making the
    useful-work fraction an honest A/B, not just a restart count."""

    workdir: str
    quarantine: bool = True
    total_steps: int = 6
    checkpoint_every: int = 2
    flaky_trips: int = 3
    seed: int = 0
    global_batch: int = 8
    wall_budget_s: float = 300.0
    namespace: str = "kubeflow"
    job_name: str = "health-soak"

    FLAKY_POOL = "pool-a"
    CLEAN_POOL = "pool-b"

    def _manifest(self, ckpt_dir: str) -> dict:
        return {
            "apiVersion": "tpu.kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": self.job_name,
                         "namespace": self.namespace},
            "spec": {
                "checkpointDir": ckpt_dir,
                "schedulingPolicy": {"queue": "research", "priority": 0,
                                     "preemptible": False},
                "replicaSpecs": {"TPU": {
                    "tpuTopology": POOL_TOPOLOGY,
                    "template": {"spec": {"containers": [
                        {"name": "jax", "image": "trainer:v1"}]}}}},
                "runPolicy": {
                    "backoffLimit": self.flaky_trips + 3,
                    # a small backoff so the scheduler's evacuation pass
                    # wins the race against the operator's recreate
                    "restartBackoffSeconds": 0.05,
                    "restartBackoffMaxSeconds": 0.2,
                },
            },
        }

    # the segment/env plumbing is PreemptionSoak's, verbatim — one
    # implementation of "run the real worker with the operator-rendered
    # env" shared by every scheduler soak
    _chief_env = PreemptionSoak._chief_env
    _run_segment = PreemptionSoak._run_segment
    _latest_step = staticmethod(PreemptionSoak._latest_step)

    def _gang_running(self, cluster) -> list[dict]:
        pods = cluster.list("v1", "Pod", self.namespace,
                            selector={"kubeflow.org/job-name":
                                      self.job_name})
        running = [p for p in pods
                   if p.get("status", {}).get("phase") == "Running"]
        return running if len(running) == 2 else []   # v5e-8 = 2 hosts

    def run(self) -> dict:
        from ..cluster.chaos import HostFault
        from ..cluster.fake import FakeCluster
        from ..controllers.runtime import Manager
        from ..controllers.tpujob import (RESTART_COUNT_ANNOTATION,
                                          TrainingJobReconciler)
        from .core import SliceScheduler
        from .health import HealthConfig, is_quarantined
        from .queue import SchedulerConfig, binding_of

        ckpt_dir = os.path.join(self.workdir, "job")
        cluster = FakeCluster()
        cluster.add_tpu_slice_nodes(POOL_TOPOLOGY, pool=self.FLAKY_POOL)
        cluster.add_tpu_slice_nodes(POOL_TOPOLOGY, pool=self.CLEAN_POOL)
        flaky_node = f"{self.FLAKY_POOL}-{POOL_TOPOLOGY}-1"
        fault = HostFault(node=flaky_node, mode="crash",
                          trips=self.flaky_trips)
        config = SchedulerConfig(health=HealthConfig(
            enabled=self.quarantine,
            # one crash (weight 1.0) is enough evidence in the drill;
            # 0.9 leaves room for the decay between fold and pass
            quarantine_threshold=0.9, release_threshold=0.5,
            quarantine_s=300.0))
        mgr = Manager(cluster)
        mgr.add(SliceScheduler(config))
        mgr.add(TrainingJobReconciler("TPUJob"))
        cluster.create(self._manifest(ckpt_dir))

        # fault schedule: fire once the job has banked these steps (off
        # the checkpoint_every=2 boundaries -> real replay in the off
        # arm); trips beyond the schedule fire immediately on recreate
        fault_steps = [3, 4, 5][:self.flaky_trips]
        chief = f"{self.job_name}-worker-0-0"
        report: dict = {"outcome": "timeout", "restarts": 0,
                        "fires": 0, "rebinds": 0, "pools": [],
                        "executed_steps": 0, "checkpoint_dir": ckpt_dir,
                        "quarantine": self.quarantine}
        deadline = time.monotonic() + self.wall_budget_s
        first_fire_t = None
        recovered_t = None
        reached = 0
        last_pools = None
        while time.monotonic() < deadline:
            mgr.run_pending()
            cluster.tick()
            mgr.run_pending()
            job = cluster.get("tpu.kubeflow.org/v1alpha1", "TPUJob",
                              self.namespace, self.job_name)
            report["restarts"] = int(k8s.annotations_of(job).get(
                RESTART_COUNT_ANNOTATION, "0"))
            placement = binding_of(job)
            pools = sorted({r.pool for r in placement.slices}) \
                if placement else None
            if pools is not None and pools != last_pools:
                last_pools = pools
                report["pools"].append(pools)
                report["rebinds"] = len(report["pools"]) - 1
            if k8s.condition_true(job, "Succeeded"):
                report["outcome"] = "succeeded"
                break
            if k8s.condition_true(job, "Failed"):
                report["outcome"] = "failed"
                report["failed_reason"] = k8s.get_condition(
                    job, "Failed").get("reason")
                break
            running = self._gang_running(cluster)
            if not running or k8s.condition_true(job, "Restarting"):
                time.sleep(0.02)
                continue
            on_flaky = any(p.get("spec", {}).get("nodeName") == flaky_node
                           for p in running)
            if first_fire_t is not None and recovered_t is None and \
                    not (on_flaky and fault.fired < fault.trips):
                # fully Running with nothing left for the fault to hit:
                # the gang has outrun the flaky host (migrated, or the
                # host's budget is spent)
                recovered_t = time.monotonic()
                report["recovery_s"] = round(
                    recovered_t - first_fire_t, 3)
            due = fault.fired < len(fault_steps) and \
                reached >= fault_steps[fault.fired]
            late = fault.fired >= len(fault_steps)
            if on_flaky and fault.fired < fault.trips and (due or late):
                if fault.maybe_fire(cluster, self.namespace,
                                    at_step=reached):
                    report["fires"] = fault.fired
                    if first_fire_t is None:
                        first_fire_t = time.monotonic()
                    continue
            # train to the next fault step (if one is pending and the
            # gang still sits on the flaky host) or to the end
            target = fault_steps[fault.fired] \
                if (on_flaky and fault.fired < len(fault_steps)) \
                else self.total_steps
            if reached < target:
                resume = self._latest_step(ckpt_dir) or 0
                self._run_segment(self._chief_env(cluster, chief),
                                  target)
                report["executed_steps"] += target - resume
                reached = target
            if reached >= self.total_steps:
                cluster.set_pod_phase(self.namespace, chief, "Succeeded")
        node = cluster.get("v1", "Node", "", flaky_node)
        report["flaky_node"] = flaky_node
        report["flaky_quarantined"] = is_quarantined(node)
        report["final_pools"] = last_pools
        report["migrated"] = bool(last_pools and
                                  self.FLAKY_POOL not in last_pools)
        report["useful_work_fraction"] = round(
            self.total_steps / max(1, report["executed_steps"]), 4)
        for c in mgr.controllers:
            c.stop()
        return report

    def clean_params(self):
        """The parity reference: same seed and steps, no flaky host."""
        env_map = {"KFTPU_CHECKPOINT_DIR":
                   os.path.join(self.workdir, "clean")}
        self._run_segment(env_map, self.total_steps)
        from ..cluster.chaos import final_params
        return final_params(env_map["KFTPU_CHECKPOINT_DIR"])
