"""Gang-scheduling queue: quota-aware, topology-aware TPU slice scheduler.

The subsystem between admission and pod creation (ISSUE 4): a slice
inventory that bin-packs gangs onto contiguous ICI sub-slices
(inventory.py), priority queues with namespace quotas (queue.py), the
planning pass + k8s reconcile loop with backfill and checkpoint-aware
preemption (core.py), the bench's seeded contended-cluster simulation
(sim.py), and the real-training preemption-parity soak (soak.py).

Everything here is jax-free at import time — the scheduler runs in the
operator process (soak.py imports the runtime lazily inside run()).
"""

from .inventory import Placement, PoolState, SliceInventory, SliceRect
from .queue import (JobRequest, QueueSpec, SchedulerConfig, binding_of,
                    elastic_topologies, ordered, over_quota, request_of,
                    resize_history)
from .core import (Plan, SliceScheduler, STATE_BOUND, STATE_PREEMPTED,
                   STATE_QUEUED, plan)

__all__ = [
    "Placement", "PoolState", "SliceInventory", "SliceRect",
    "JobRequest", "QueueSpec", "SchedulerConfig", "binding_of",
    "elastic_topologies", "ordered", "over_quota", "request_of",
    "resize_history",
    "Plan", "SliceScheduler", "plan",
    "STATE_BOUND", "STATE_PREEMPTED", "STATE_QUEUED",
]
