"""The gang scheduler: one planning pass + the k8s reconcile loop.

Sits between admission and pod creation. The TPUJob operator
(controllers/tpujob.py) creates NO pods for a scheduler-managed job (one
carrying ``spec.schedulingPolicy``) until this scheduler writes the slice
binding annotation; until then the job shows a ``Queued`` condition. One
planning pass:

1. Build the slice inventory from the cluster's TPU node pools
   (scheduler/inventory.py) and re-occupy it from every live binding.
2. Order the queue (priority desc, submission order; scheduler/queue.py)
   and walk it: quota-blocked jobs wait; placeable jobs bind (the
   placement annotation); the FIRST unplaceable job becomes the blocked
   head of line.
3. The blocked head's fallback ladder: SHRINK lower-priority elastic
   gangs (minChips/maxChips jobs resize to a smaller slice size — a
   checkpointed restart, no work lost) until the head fits; an elastic
   head that still cannot place binds DEGRADED below its nominal shape
   (shrink-to-survive — the lost-host case); only then PREEMPT —
   cheapest lower-priority preemptible gangs (fewest chips first) are
   unbound until the head fits. A victim is re-queued, not failed — the
   operator tears its gang down through the graceful path (SIGTERM →
   forced checkpoint → exit 75) and the job's own checkpoints make the
   eventual re-bind cheap.
4. Behind a blocked head, BACKFILL continues — but never into the head's
   reserved region (a geometry-only placement of the head's shape whose
   cells only ever drain), so backfill can never starve the head.
5. With nothing waiting on capacity, the idle-chip passes run: GROW one
   bound elastic gang into free chips, or MIGRATE one to enlarge the
   largest contiguous free rectangle (defragmentation) — one resize per
   pass, each executed by the operator as a checkpointed gang restart
   at the binding's new shape.

``plan()`` is pure (inventory in, actions out): the k8s loop
(SliceScheduler) and the bench's contended-cluster simulation
(scheduler/sim.py) run the identical policy code.
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from ..api import k8s
from ..api.trainingjob import (BINDING_ANNOTATION, COND_FAILED,
                               COND_SUCCEEDED, PREEMPTED_COUNT_ANNOTATION,
                               QUARANTINE_ANNOTATION,
                               RESIZE_HISTORY_ANNOTATION,
                               SCHED_REASON_ANNOTATION,
                               SCHED_STATE_ANNOTATION, SUSPECT_ANNOTATION,
                               TPU_API_VERSION, TrainingJob)
from ..cluster.client import (KubeClient, NotFoundError, apply_annotations,
                              update_with_conflict_retry)
from ..controllers.runtime import (Key, Reconciler, Result,
                                   ensure_trace_id, trace_job_event)
from ..obs import controlplane as ctrlobs
from ..obs import registry as obsreg
from . import health
from .inventory import POOL_LABEL, Placement, SliceInventory
from .queue import (JobRequest, SchedulerConfig, binding_matches,
                    binding_of, ordered, over_quota, request_of,
                    resize_history)

# resize-history entries kept on the annotation (audit trail + the grow
# cooldown's clock; older entries roll off)
RESIZE_HISTORY_MAX = 20

log = logging.getLogger(__name__)

# scheduler states surfaced via SCHED_STATE_ANNOTATION
STATE_QUEUED = "queued"
STATE_BOUND = "bound"
STATE_PREEMPTED = "preempted"


@dataclass
class Plan:
    """One pass's decisions, in apply order: resizes and victims release
    first (their chips are what the binds below may be counting on)."""

    binds: list = field(default_factory=list)       # (JobRequest, Placement)
    preempts: list = field(default_factory=list)    # JobRequest (victims)
    # elastic resize plans: (JobRequest, new Placement, reason) — the
    # binding rewrites the operator executes as a checkpointed gang
    # restart at the new shape (shrink-to-admit, grow-to-fill, defrag
    # migration; a shrink-to-survive of a QUEUED job rides in ``binds``
    # with a reduced-shape placement instead)
    resizes: list = field(default_factory=list)
    # key -> human reason a job stayed queued (quota, capacity, ...)
    waits: dict = field(default_factory=dict)


def _preempt_for(head: JobRequest, bound: list,
                 inventory: SliceInventory,
                 avoid: Optional[set] = None) -> Optional[list]:
    """Cheapest victim set that lets ``head`` fit, or None. Victims must
    be lower priority AND preemptible; candidates are released
    greedily cheapest-first (fewest chips, then lowest priority, then
    newest — the least sunk work) until the head places, then PRUNED:
    any victim whose chips turn out not to be needed (released early
    from the wrong pool before the one that mattered) is re-bound —
    nobody eats a SIGTERM for a placement they never blocked. The
    inventory is mutated only when a sufficient set exists."""
    # newest-first within equal (chips, priority): least sunk work lost.
    # Two stable sorts because seq may be a (timestamp, uid) tuple —
    # not negatable the way an int tiebreak would be.
    candidates = sorted(
        (r for r, _p in bound
         if r.preemptible and r.priority < head.priority),
        key=lambda r: r.seq, reverse=True)
    candidates.sort(key=lambda r: (r.chips, r.priority))
    if not candidates:
        return None
    placements = {r.key: p for r, p in bound}
    victims: list[JobRequest] = []
    snapshot = [[row[:] for row in p.grid]
                for p in inventory.pools.values()]
    fits = False
    for victim in candidates:
        inventory.release(victim.key)
        victims.append(victim)
        if inventory.place_gang(head.topology, head.num_slices,
                                avoid=avoid) is not None:
            fits = True
            break
    if not fits:
        # insufficient even with every candidate gone: restore occupancy
        for pool, grid in zip(inventory.pools.values(), snapshot):
            pool.grid = [row[:] for row in grid]
        return None
    # prune most-expensive-first so the cheap victims stay the preferred
    # cost when either would do
    for victim in sorted(victims, key=lambda r: -r.chips):
        inventory.bind(victim.key, placements[victim.key])
        if inventory.place_gang(head.topology, head.num_slices,
                                avoid=avoid) is not None:
            victims.remove(victim)    # not actually in the way
        else:
            inventory.release(victim.key)
    return victims


def _rects_free(inventory: SliceInventory, placement) -> bool:
    """Whether every cell of ``placement`` is currently free."""
    for rect in placement.slices:
        pool = inventory.pools.get(rect.pool)
        if pool is None or not pool.fits(rect.x, rect.y, rect.h, rect.w):
            return False
    return True


def _shrink_for(head: JobRequest, bound: list,
                inventory: SliceInventory,
                avoid: Optional[set] = None) -> Optional[list]:
    """Shrink set of elastic lower-priority bound gangs that lets
    ``head`` fit at its nominal shape, or None. The resize analog of
    ``_preempt_for`` and tried BEFORE it: a shrink is a checkpointed
    restart at a smaller replica degree — degraded-mode training — so
    no work is thrown away, where a preemption costs the victim its
    progress since the last checkpoint. Victims shrink one supported
    slice size at a time, lowest priority first (biggest current gang
    breaking ties — most chips freed per restart), until the head
    places; then resizes are PRUNED: any victim whose original rects
    are still free with the head placeable is restored — nobody eats a
    restart for chips the head never needed. Mutates the inventory only
    when a sufficient set exists. Returns [(victim, new Placement)]."""
    from .queue import elastic_topologies, placement_slice_chips
    candidates = []
    for r, p in bound:
        if r.priority >= head.priority or not r.elastic:
            continue
        cur = placement_slice_chips(p)
        opts = [t for t in elastic_topologies(r) if t.num_chips < cur]
        if opts:
            candidates.append((r, p, opts))
    if not candidates:
        return None
    candidates.sort(key=lambda c: (c[0].priority, -c[1].chips, c[0].key))
    snapshot = [[row[:] for row in p.grid]
                for p in inventory.pools.values()]
    resized: dict[str, tuple] = {}   # key -> (victim, original, new)
    fits = False
    for victim, original, opts in candidates:
        for topo in opts:   # descending: one supported size at a time
            inventory.release(victim.key)
            new_p = inventory.place_gang(topo, victim.num_slices,
                                         flexible=True)
            if new_p is None:
                # smaller shape unplaceable (pathological fragmentation):
                # restore the victim's current occupancy and move on
                cur_p = resized[victim.key][2] if victim.key in resized \
                    else original
                inventory.bind(victim.key, cur_p)
                break
            inventory.bind(victim.key, new_p)
            resized[victim.key] = (victim, original, new_p)
            if inventory.place_gang(head.topology, head.num_slices,
                                    avoid=avoid) is not None:
                fits = True
                break
        if fits:
            break
    if not fits:
        for pool, grid in zip(inventory.pools.values(), snapshot):
            pool.grid = [row[:] for row in grid]
        return None
    # prune most-chips-restored-first: keep the cheapest shrink set
    for key, (victim, original, new_p) in sorted(
            resized.items(), key=lambda kv: -kv[1][1].chips):
        inventory.release(victim.key)
        if _rects_free(inventory, original):
            inventory.bind(victim.key, original)
            if inventory.place_gang(head.topology, head.num_slices,
                                    avoid=avoid) is not None:
                del resized[key]    # never actually needed to shrink
                continue
            inventory.release(victim.key)
        inventory.bind(victim.key, new_p)
    return [(v, p) for v, _o, p in resized.values()]


def _place_degraded(inventory: SliceInventory, req: JobRequest,
                    avoid: Optional[set], reserved: set):
    """Shrink-to-survive placement for a QUEUED elastic job: walk the
    allowed shapes BELOW nominal, largest first, honoring the same
    avoid-preference semantics as the nominal attempt (suspect cells are
    a preference; the head-of-line reservation is inviolable). Returns
    the reduced-shape Placement or None — degraded-mode training
    instead of starving behind a lost host or a fragmented pool."""
    from .queue import elastic_topologies
    for topo in elastic_topologies(req):
        if topo.num_chips >= req.topology.num_chips:
            continue
        placement = inventory.place_gang(topo, req.num_slices,
                                         avoid=avoid or None,
                                         flexible=True)
        if placement is None and avoid and avoid != reserved:
            placement = inventory.place_gang(topo, req.num_slices,
                                             avoid=reserved or None,
                                             flexible=True)
        if placement is not None:
            return placement
    return None


def plan(queued: list[JobRequest], bound: list,
         inventory: SliceInventory, config: SchedulerConfig,
         avoid_cells: Optional[dict] = None,
         prefer_cells: Optional[set] = None) -> Plan:
    """Pure planning over a pre-occupied inventory. ``bound`` is
    [(JobRequest, Placement)] for every currently bound gang (their cells
    already occupied in ``inventory``). ``avoid_cells`` maps a job key to
    cells ITS placement must keep clear of — the suspect-host exclusion:
    a job evacuating a flaky host must not be re-placed onto it even
    while the host is still formally schedulable. ``prefer_cells`` are
    the advertised warm-pod slots (scheduler/warmpool.py): placements
    covering them adopt a pre-initialized pod, so ties tip toward them
    (preference only — never worth a worse fragmentation cut). Mutates
    the inventory to reflect its own decisions (callers pass a
    throwaway rebuild)."""
    out = Plan()
    avoid_cells = avoid_cells or {}
    live_bound = list(bound)
    reserved: set = set()
    head_blocked = False
    for req in ordered(queued, config):
        if over_quota(req, live_bound, config):
            out.waits[req.key] = (
                f"quota: queue {req.queue!r} namespace {req.namespace!r} "
                f"bound-chip quota would be exceeded")
            continue
        if head_blocked and not config.backfill:
            out.waits[req.key] = "waiting: behind blocked head of line"
            continue
        req_avoid = reserved | avoid_cells.get(req.key, set())
        placement = inventory.place_gang(req.topology, req.num_slices,
                                         avoid=req_avoid or None,
                                         prefer=prefer_cells)
        if placement is None and avoid_cells.get(req.key):
            # suspect exclusion is PREFERENCE, not a constraint: when
            # no placement clear of the suspect exists (single-pool
            # cluster, full-pool gang), running on the suspect beats
            # starving forever — retry honoring only the head-of-line
            # reservation, which must never be violated
            placement = inventory.place_gang(req.topology,
                                             req.num_slices,
                                             avoid=reserved or None,
                                             prefer=prefer_cells)
        if placement is not None:
            inventory.bind(req.key, placement)
            out.binds.append((req, placement))
            live_bound.append((req, placement))
            continue
        if head_blocked:
            out.waits[req.key] = "capacity: no contiguous slice free " \
                                 "(backfill could not place clear of " \
                                 "the head-of-line reservation)"
            continue
        # The blocked head of line. Resize paths come FIRST — both end
        # at a checkpoint boundary so no work is thrown away: (1) shrink
        # elastic lower-priority gangs until the head fits at nominal
        # (instead of preempting them to zero), (2) shrink the head
        # ITSELF below nominal (degraded-mode training — the lost-host /
        # no-same-size-rectangle case; better to run at half width than
        # to starve or crash-loop). Only then preemption, else reserve —
        # the suspect exclusion stays preference-only throughout: a head
        # that cannot place clear of its suspect falls back to ignoring
        # it rather than deadlocking the queue.
        head_avoid = avoid_cells.get(req.key, set())
        if config.elastic:
            shrunk = _shrink_for(req, live_bound, inventory,
                                 avoid=head_avoid or None)
            if shrunk is None and head_avoid:
                shrunk = _shrink_for(req, live_bound, inventory)
                if shrunk is not None:
                    head_avoid = set()
            if shrunk is not None:
                new_by_key = {v.key: p for v, p in shrunk}
                live_bound = [(r, new_by_key.get(r.key, p))
                              for r, p in live_bound]
                out.resizes.extend(
                    (v, p, "shrink: admitting blocked head")
                    for v, p in shrunk)
                placement = inventory.place_gang(
                    req.topology, req.num_slices,
                    avoid=head_avoid or None)
                if placement is not None:
                    inventory.bind(req.key, placement)
                    out.binds.append((req, placement))
                    live_bound.append((req, placement))
                    continue
            if req.elastic:
                placement = _place_degraded(inventory, req,
                                            avoid=head_avoid or None,
                                            reserved=reserved)
                if placement is not None:
                    # bound at a reduced shape: the binding itself is
                    # the resize plan — grow-to-fill restores the
                    # nominal shape once capacity returns
                    inventory.bind(req.key, placement)
                    out.binds.append((req, placement))
                    live_bound.append((req, placement))
                    continue
        if config.preemption:
            victims = _preempt_for(req, live_bound, inventory,
                                   avoid=head_avoid or None)
            if victims is None and head_avoid:
                victims = _preempt_for(req, live_bound, inventory)
                if victims is not None:
                    head_avoid = set()
            if victims is not None:
                victim_keys = {v.key for v in victims}
                live_bound = [(r, p) for r, p in live_bound
                              if r.key not in victim_keys]
                out.preempts.extend(victims)
                placement = inventory.place_gang(req.topology,
                                                 req.num_slices,
                                                 avoid=head_avoid or None)
                if placement is not None:
                    inventory.bind(req.key, placement)
                    out.binds.append((req, placement))
                    live_bound.append((req, placement))
                    continue
        head_blocked = True
        reserved = inventory.reserve_for(req.topology, req.num_slices,
                                         avoid=head_avoid or None)
        if not reserved and head_avoid:
            reserved = inventory.reserve_for(req.topology,
                                             req.num_slices)
        out.waits[req.key] = (
            "capacity: head of line, waiting for reserved slices to "
            "drain" if reserved else
            "capacity: request can never fit this cluster's pools")
    if config.elastic and not head_blocked:
        _plan_grow_and_defrag(out, live_bound, inventory, config)
    # One action per job per pass: a gang BOUND this pass and then
    # resized by a later head's shrink (or the grow pass) folds into a
    # single bind at the final shape — it has no running pods yet, so
    # there is nothing to restart and no separate resize to record.
    bind_idx = {r.key: i for i, (r, _p) in enumerate(out.binds)}
    folded = []
    for req, placement, reason in out.resizes:
        i = bind_idx.get(req.key)
        if i is not None:
            out.binds[i] = (out.binds[i][0], placement)
        else:
            folded.append((req, placement, reason))
    out.resizes = folded
    return out


def _plan_grow_and_defrag(out: Plan, live_bound: list,
                          inventory: SliceInventory,
                          config: SchedulerConfig) -> None:
    """The idle-capacity passes, run only when nothing is waiting on
    capacity (head not blocked — with backfill on, every remaining wait
    is quota): (1) GROW one bound elastic gang into the idle chips,
    largest allowed shape first, highest priority gang first; (2) if
    nothing grew, MIGRATE one bound elastic gang whose re-placement
    strictly enlarges the cluster's largest contiguous free rectangle
    (defragmentation — stranded slivers are what quietly halve a
    cluster's effective capacity). One resize per pass: each is a
    checkpointed gang restart, and the next pass sees the new state —
    incremental beats a same-pass restart storm. Gangs inside the grow
    cooldown (req.grow_ok False) are skipped; both passes respect
    per-(queue, namespace) quotas via the gang's ACTUAL chip count."""
    from ..api.topology import parse_topology
    from .queue import elastic_topologies, placement_slice_chips

    def actual_bound_chips(queue: str, namespace: str,
                           skip_key: str) -> int:
        return sum(p.chips for r, p in live_bound
                   if r.queue == queue and r.namespace == namespace
                   and r.key != skip_key)

    candidates = sorted(
        ((r, p) for r, p in live_bound if r.elastic and r.grow_ok),
        key=lambda rp: (-rp[0].priority, rp[0].seq, rp[0].key))
    if config.grow:
        for req, placement in candidates:
            cur = placement_slice_chips(placement)
            ups = [t for t in elastic_topologies(req)
                   if t.num_chips > cur]
            if not ups:
                continue
            quota = config.queue(req.queue).quota_for(req.namespace)
            others = actual_bound_chips(req.queue, req.namespace,
                                        req.key)
            inventory.release(req.key)
            new_p = None
            for topo in ups:    # largest allowed shape first
                total = topo.num_chips * req.num_slices
                if quota is not None and others + total > quota:
                    continue
                new_p = inventory.place_gang(topo, req.num_slices,
                                             flexible=True)
                if new_p is not None:
                    break
            if new_p is None:
                inventory.bind(req.key, placement)
                continue
            inventory.bind(req.key, new_p)
            out.resizes.append((req, new_p, "grow: idle capacity"))
            return
    if not config.defrag:
        return
    def frag_score() -> int:
        return max((p.max_free_rect()
                    for p in inventory.pools.values()), default=0)
    before = frag_score()
    for req, placement in candidates:
        try:
            topo = parse_topology(placement.topology)
        except ValueError:
            continue
        inventory.release(req.key)
        new_p = inventory.place_gang(topo, req.num_slices,
                                     flexible=True)
        if new_p is None or new_p.slices == placement.slices:
            inventory.bind(req.key, placement)
            continue
        inventory.bind(req.key, new_p)
        if frag_score() > before:
            out.resizes.append((req, new_p, "defrag: migrating to "
                                "enlarge the largest free rectangle"))
            return
        inventory.release(req.key)
        inventory.bind(req.key, placement)


class SliceScheduler(Reconciler):
    """The reconcile-loop host for plan(): every TPUJob or Node event
    triggers a full scheduling pass (level-triggered — the pass reads
    desired state fresh, so per-key granularity would buy nothing)."""

    # where the deployed scheduler reads its policy (the ConfigMap the
    # tpu-scheduler manifest renders; manifests/training.py)
    CONFIG_MAP = ("kubeflow", "tpu-scheduler-config")
    CONFIG_KEY = "config.json"

    def __init__(self, config: Optional[SchedulerConfig] = None):
        # an explicitly passed config wins forever (tests, sim, embedded
        # use); otherwise each pass reads the tpu-scheduler-config
        # ConfigMap so deployed quota/backfill/preemption policy is
        # actually LIVE, not a rendered artifact nothing consumes
        self._explicit_config = config
        self._cm_rv: Optional[str] = None
        self._cm_config = SchedulerConfig()
        # when each still-queued job was first seen waiting: feeds the
        # queue-wait histogram at bind time and the "queued" trace event
        # exactly once per wait (a preempted job re-enters and waits
        # again — that is a second, separately measured wait)
        self._queued_since: dict[str, float] = {}
        # queues ever exported, so a queue that drains to zero exports
        # zeros instead of its stale last depth
        self._known_queues: set = set()
        # last Ready state per TPU node: a True→False transition folds a
        # not-ready health event (flappy hosts quarantine themselves);
        # tracked even with health disabled so re-enabling does not read
        # one old flap as fresh evidence
        self._node_ready: dict[str, bool] = {}
        # nodes whose health gauges were exported (deleted nodes must
        # drop their series, not freeze their last score)
        self._health_exported: set = set()
        self.primary = (TPU_API_VERSION, "TPUJob")
        # reconcile-metrics label (controllers/runtime.py): the primary
        # kind is TPUJob here too, and the operator owns that label
        self.controller_name = "scheduler"
        # Node events (pool added/drained) re-plan too; map_event routes
        # them to a synthetic pass key since nodes carry no owner ref
        self.owns = [("v1", "Node")]

    @property
    def config(self) -> SchedulerConfig:
        return self._explicit_config or self._cm_config

    def _refresh_config(self, client: KubeClient) -> None:
        if self._explicit_config is not None:
            return
        cm = client.get_or_none("v1", "ConfigMap", *self.CONFIG_MAP)
        if cm is None:
            self._cm_rv, self._cm_config = None, SchedulerConfig()
            return
        rv = cm.get("metadata", {}).get("resourceVersion")
        if rv is not None and rv == self._cm_rv:
            return   # unchanged since last pass: keep the parsed config
        try:
            self._cm_config = SchedulerConfig.from_dict(json.loads(
                (cm.get("data") or {}).get(self.CONFIG_KEY, "") or "{}"))
        except (ValueError, TypeError) as e:
            # a malformed ConfigMap must not take the scheduler down —
            # fall back to defaults and keep binding
            log.warning("scheduler: bad %s/%s %s (%s); using defaults",
                        *self.CONFIG_MAP, self.CONFIG_KEY, e)
            self._cm_config = SchedulerConfig()
        self._cm_rv = rv

    def map_event(self, client: KubeClient, obj: dict) -> list[Key]:
        if obj.get("kind") == "Node":
            return [("", "#cluster-pass")]
        return []

    # ---------------------------------------------------------- node health

    def _health_pass(self, client: KubeClient, nodes: list[dict],
                     now: float) -> list[dict]:
        """Score, quarantine, and release TPU hosts from the failure
        evidence in their health annotations (scheduler/health.py).
        Write-on-change throughout: a steady-state pass writes nothing.
        Returns the node list with this pass's patches folded in, so
        the inventory built right after sees them."""
        cfg = self.config.health
        score_g = obsreg.gauge(
            "kftpu_node_health_score",
            "decayed failure score per TPU host (scheduler/health.py)",
            labels=("node",))
        quar_g = obsreg.gauge(
            "kftpu_node_quarantined",
            "1 while the host carries the quarantine annotation",
            labels=("node",))
        tracer_event = None
        from ..obs.trace import default_tracer
        tracer = default_tracer("scheduler")
        if tracer is not None:
            tracer_event = tracer.event
        out, seen = [], set()
        _UNSET = object()
        for node in nodes:
            name = k8s.name_of(node)
            if POOL_LABEL not in k8s.labels_of(node):
                out.append(node)
                continue
            seen.add(name)
            ready = k8s.condition_true(node, "Ready")
            flapped = self._node_ready.get(name) is True and not ready
            self._node_ready[name] = ready
            if cfg.enabled and flapped:
                # Ready→NotReady transition: evidence, exactly once per
                # flap — a chronically flapping host earns quarantine
                rec = health.record_host_event(
                    client, name, health.EVENT_NOT_READY, now=now,
                    half_life_s=cfg.half_life_s)
                if rec is not None:
                    node = client.get_or_none("v1", "Node", "", name) \
                        or node
            score = health.decayed_score(node, now, cfg.half_life_s)
            quarantine = health.quarantine_of(node)
            patch_val = _UNSET
            # spec.unschedulable to set alongside (None = untouched):
            # cell carving alone cannot stop the kube scheduler from
            # placing a SUB-SLICE gang's pods back on the host (pods
            # pin by pool label only) — the cordon closes that hole
            cordon = None
            if cfg.enabled:
                if quarantine is None and \
                        score >= cfg.quarantine_threshold:
                    patch_val = health.quarantine_record(
                        f"health score {score:.2f} >= "
                        f"{cfg.quarantine_threshold:g}", score, now,
                        cfg.quarantine_s, cordoned=True)
                    cordon = True
                    obsreg.counter(
                        "kftpu_sched_quarantines_total",
                        "hosts quarantined for crossing the health "
                        "threshold").inc()
                    if tracer_event:
                        tracer_event("node-quarantined", node=name,
                                     score=round(score, 3))
                    log.warning("scheduler: quarantining %s "
                                "(score %.2f)", name, score)
                elif quarantine is not None and \
                        health.release_eligible(node, cfg, now):
                    patch_val = None   # kube null-delete
                    if quarantine["cordoned"]:
                        cordon = False  # only OUR cordon is undone
                    obsreg.counter(
                        "kftpu_sched_quarantine_releases_total",
                        "quarantines auto-released after expiry + score "
                        "decay (probation)").inc()
                    if tracer_event:
                        tracer_event("node-released", node=name,
                                     score=round(score, 3))
                    log.info("scheduler: releasing %s from quarantine "
                             "(score %.2f)", name, score)
                elif quarantine is not None \
                        and quarantine["until"] is not None \
                        and now >= quarantine["until"] \
                        and quarantine["reason"] != health.MANUAL_REASON:
                    # expired but still hot: extend (probation re-up),
                    # one write per expiry period
                    patch_val = health.quarantine_record(
                        quarantine["reason"], score, now,
                        cfg.quarantine_s,
                        cordoned=quarantine["cordoned"])
            elif quarantine is not None and \
                    quarantine["reason"] != health.MANUAL_REASON:
                # health switched OFF: release every auto-quarantine
                # now — "placement-blind" must not strand chips behind
                # annotations nothing will ever expire (manual
                # quarantines are a human's call and stay)
                patch_val = None
                if quarantine["cordoned"]:
                    cordon = False
                obsreg.counter(
                    "kftpu_sched_quarantine_releases_total",
                    "quarantines auto-released after expiry + score "
                    "decay (probation)").inc()
                log.info("scheduler: health disabled; releasing %s "
                         "from quarantine", name)
            if patch_val is not _UNSET:
                # conflict-safe: the operator folds health evidence onto
                # this same node concurrently — a stale-read write here
                # re-reads and re-applies instead of clobbering the fold
                def _mutate(obj: dict, patch_val=patch_val,
                            cordon=cordon) -> dict:
                    apply_annotations(obj, {QUARANTINE_ANNOTATION:
                                            patch_val})
                    if cordon is not None:
                        obj.setdefault("spec", {})["unschedulable"] = \
                            cordon
                    return obj
                try:
                    node = update_with_conflict_retry(
                        client, "v1", "Node", "", name, _mutate)
                except Exception as e:  # noqa: BLE001 — health writes
                    # must never take down the scheduling pass
                    log.warning("scheduler: quarantine patch for %s "
                                "failed: %s", name, e)
            score_g.labels(node=name).set(round(score, 6))
            quar_g.labels(node=name).set(
                1 if health.is_quarantined(node) else 0)
            out.append(node)
        for stale in self._health_exported - seen:
            score_g.remove(node=stale)
            quar_g.remove(node=stale)
            self._node_ready.pop(stale, None)
        self._health_exported = seen
        return out

    # ------------------------------------------------------------- the pass

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        del key  # every pass is cluster-wide
        # the audit seam: direct-drive callers (tests, bench, sim-replay)
        # get write attribution too; under the controller runtime the
        # client arrives already audited and ctrl_pass joins the
        # runtime's open pass context instead of double-counting
        if not isinstance(client, ctrlobs.AuditingKubeClient):
            client = ctrlobs.AuditingKubeClient(client,
                                                self.controller_name)
        with ctrlobs.ctrl_pass(self.controller_name) as pctx:
            return self._plan_pass(client, pctx)

    def _plan_pass(self, client: KubeClient,
                   pctx: "ctrlobs.PassContext") -> Result:
        t_pass = time.perf_counter()
        now = time.time()
        with pctx.phase(ctrlobs.PHASE_SNAPSHOT):
            self._refresh_config(client)
            raw_nodes = client.list("v1", "Node")
        with pctx.phase(ctrlobs.PHASE_HEALTH):
            nodes = self._health_pass(client, raw_nodes, now)
        inventory = SliceInventory.from_nodes(nodes)
        health_on = self.config.health.enabled
        queued: list[JobRequest] = []
        bound: list = []
        manifests: dict[str, dict] = {}
        avoid_cells: dict[str, set] = {}
        with pctx.phase(ctrlobs.PHASE_SNAPSHOT):
            # the job-scan loop is snapshot work (parse + binding
            # validation); its corrective writes (evacuations,
            # stale-binding drops) are timed here too
            jobs_scanned = self._scan_jobs(client, inventory, health_on,
                                           now, queued, bound,
                                           manifests, avoid_cells)
        return self._finish_pass(client, pctx, inventory, queued, bound,
                                 manifests, avoid_cells, jobs_scanned,
                                 len(nodes), t_pass)

    def _scan_jobs(self, client: KubeClient, inventory: SliceInventory,
                   health_on: bool, now: float, queued: list,
                   bound: list, manifests: dict,
                   avoid_cells: dict) -> int:
        """Parse + validate every TPUJob manifest against the inventory
        (the pass's job snapshot): re-occupy valid bindings, queue the
        rest, evacuate gangs off suspect hosts. Returns manifests
        scanned (completed jobs included — the skip is part of the
        scan)."""
        job_manifests = client.list(*self.primary)
        for manifest in job_manifests:
            if k8s.condition_true(manifest, COND_SUCCEEDED) or \
                    k8s.condition_true(manifest, COND_FAILED):
                continue
            try:
                job = TrainingJob.from_manifest(manifest)
            except ValueError as e:
                log.warning("scheduler: skipping unparseable job: %s", e)
                continue
            req = request_of(job, manifest)
            if req is None:
                continue   # not scheduler-managed
            manifest = ensure_trace_id(client, manifest)
            manifests[req.key] = manifest
            placement = binding_of(manifest)
            ok = placement is not None \
                and binding_matches(placement, job) \
                and inventory.valid_binding(placement)
            suspect = health.suspect_of(manifest) if health_on else None
            suspect_cells = inventory.cells_by_node.get(suspect, set()) \
                if suspect else set()
            if ok and suspect_cells and any(
                    not suspect_cells.isdisjoint(r.cells())
                    for r in placement.slices):
                # failure-domain-aware rebind: the operator pinned this
                # gang's last teardown on a host the binding still
                # covers — evacuate instead of crash-looping in place
                log.info("scheduler: evacuating %s off suspect host %s",
                         req.key, suspect)
                self._patch_state(client, manifest, STATE_QUEUED,
                                  f"rebinding: evacuating suspect host "
                                  f"{suspect}", binding=None)
                # counted AFTER the patch succeeded (the pass-wide
                # invariant): a transient apiserver error above requeues
                # the pass, and the retry must not double-count
                obsreg.counter(
                    "kftpu_sched_suspect_evacuations_total",
                    "bindings dropped to migrate a gang off a suspect "
                    "host").inc()
                self._trace_event(manifest, "evacuating-suspect",
                                  node=suspect)
                ok = False
            if ok:
                try:
                    inventory.bind(req.key, placement)
                except ValueError as e:
                    # overlapping bindings (scheduler-replica overlap
                    # during a rollout, a hand-edited annotation): the
                    # LATER job in list order loses its binding and
                    # re-queues — one bad annotation must degrade to a
                    # requeue, never crash every future pass
                    log.warning("scheduler: conflicting binding for "
                                "%s (%s); requeueing it", req.key, e)
                    ok = False
                    self._patch_state(client, manifest, STATE_QUEUED,
                                      "rebinding: binding no longer "
                                      "matches spec/pools", binding=None)
                    queued.append(req)
                    if suspect_cells:
                        avoid_cells[req.key] = suspect_cells
                    continue
            if ok:
                # grow/defrag hysteresis: a gang resized more recently
                # than the cooldown is not grown or migrated again (a
                # shrink stays allowed — it happens via requeue+replan)
                hist = resize_history(manifest)
                if hist:
                    try:
                        last = float(hist[-1].get("time", 0))
                    except (TypeError, ValueError):
                        last = 0.0
                    req.grow_ok = now - last >= self.config.grow_cooldown_s
                bound.append((req, placement))
                if suspect:
                    # bound clear of the suspect (already migrated, or
                    # the node left the cluster): the record is spent —
                    # clear it so future replans stop avoiding the host
                    self._clear_suspect(client, manifest)
            else:
                if placement is not None and \
                        binding_of(manifests[req.key]) is not None and \
                        not suspect_cells:
                    # stale binding (spec reshaped under it, pool gone,
                    # host down/quarantined): drop it so the job
                    # re-queues cleanly
                    self._patch_state(client, manifest, STATE_QUEUED,
                                      "rebinding: binding no longer "
                                      "matches spec/pools/hosts",
                                      binding=None)
                queued.append(req)
                if suspect_cells:
                    # the replan must keep clear of the suspect even
                    # while the host is still formally schedulable
                    avoid_cells[req.key] = suspect_cells
        return len(job_manifests)

    def _finish_pass(self, client: KubeClient,
                     pctx: "ctrlobs.PassContext",
                     inventory: SliceInventory, queued: list, bound: list,
                     manifests: dict, avoid_cells: dict,
                     jobs_scanned: int, nodes_scanned: int,
                     t_pass: float) -> Result:
        """Plan + apply + warm pass, phase-attributed (plan / writes /
        warm-pass)."""
        self._note_queued(queued, manifests)
        with pctx.phase(ctrlobs.PHASE_PLAN):
            inventory.carve_down()
            # warm-pod pools (scheduler/warmpool.py): the slots
            # advertised LAST pass are this pass's placement preference
            # — a bind that lands on one adopts a pre-initialized pod
            # instead of cold-starting, so ties tip toward them
            from . import warmpool
            warm_slots = warmpool.slots_of(client) \
                if self.config.warm_pods > 0 else []
            prefer = warmpool.slot_cells(warm_slots, inventory) or None
            decisions = plan(queued, bound, inventory, self.config,
                             avoid_cells=avoid_cells, prefer_cells=prefer)
        # metrics/events fire AFTER their patch succeeded (the same
        # invariant as the operator's gang-restart counter): a transient
        # apiserver error requeues the whole pass, and the retry must
        # not double-count a preemption or observe a bogus second wait
        with pctx.phase(ctrlobs.PHASE_WRITES):
            for req, new_placement, reason in decisions.resizes:
                old = next((p for r, p in bound if r.key == req.key), None)
                self._apply_resize(client, manifests[req.key], old,
                                   new_placement, reason)
            for victim in decisions.preempts:
                self._apply_preempt(client, manifests[victim.key])
                obsreg.counter(
                    "kftpu_sched_preemptions_total",
                    "gangs reclaimed (requeued, not failed) for "
                    "higher-priority work", labels=("queue",)).labels(
                        queue=victim.queue).inc()
                self._trace_event(manifests[victim.key], "preempted",
                                  queue=victim.queue, chips=victim.chips)
            now = time.time()
            for req, placement in decisions.binds:
                if warm_slots:
                    # stamp the adopted warm slots into the binding: the
                    # operator retires exactly these pre-initialized pods
                    # and marks the gang warm-started
                    placement.warm_hosts = warmpool.covered_slots(
                        placement, warm_slots, inventory)
                # a rebind retires the job's suspect record: the new
                # placement was planned around it, evidence already folded
                extra = {SUSPECT_ANNOTATION: None} \
                    if health.suspect_of(manifests[req.key]) else {}
                resized = placement.chips != req.chips
                extra_fn = None
                if resized:
                    # a non-nominal bind IS the resize — below nominal it
                    # is shrink-to-survive, above it a grow folded into
                    # the bind (gang placed straight into idle capacity)
                    # — recorded on the history annotation so dashboards
                    # and the grow cooldown see it (extra_fn: appended
                    # onto the FRESH object's history per write attempt)
                    reason = ("shrink: degraded bind (no nominal "
                              "rectangle free)"
                              if placement.chips < req.chips else
                              "grow: bound above nominal into idle "
                              "capacity")
                    extra_fn = (lambda obj, req=req, placement=placement,
                                reason=reason, now=now: {
                                    RESIZE_HISTORY_ANNOTATION:
                                    self._history_json(
                                        obj, req.chips, placement.chips,
                                        reason, now)})
                self._patch_state(client, manifests[req.key], STATE_BOUND,
                                  "bound", binding=placement,
                                  extra=extra or None, extra_fn=extra_fn)
                if resized:
                    self._count_resize(manifests[req.key], req.chips,
                                       placement.chips, reason)
                waited = now - self._queued_since.pop(req.key, now)
                obsreg.histogram(
                    "kftpu_sched_queue_wait_seconds",
                    "admission→bind wait per gang (preempted gangs wait "
                    "again)", labels=("queue",)).labels(
                        queue=req.queue).observe(waited)
                self._trace_event(
                    manifests[req.key], "bound", queue=req.queue,
                    chips=req.chips, wait_seconds=round(waited, 3),
                    pools=sorted({r.pool for r in placement.slices}))
            for req in queued:
                if req.key in decisions.waits:
                    self._mark_queued(client, manifests[req.key],
                                      decisions.waits[req.key])
        with pctx.phase(ctrlobs.PHASE_WARM):
            pending_warm = {
                (w["pool"], int(w["host"]))
                for _r, p in [*bound, *decisions.binds]
                for w in (p.warm_hosts or [])}
            self._warm_pass(client, inventory, pending_warm)
        self._export_queue_gauges(queued, bound, decisions)
        obsreg.gauge(
            "kftpu_sched_pass_jobs_scanned",
            "TPUJob manifests scanned by the last plan pass").set(
                jobs_scanned)
        obsreg.gauge(
            "kftpu_sched_pass_nodes_scanned",
            "nodes scanned by the last plan pass").set(nodes_scanned)
        pctx.note(jobs_scanned=jobs_scanned, nodes_scanned=nodes_scanned,
                  queued=len(queued), bound=len(bound),
                  binds=len(decisions.binds),
                  preempts=len(decisions.preempts))
        obsreg.histogram(
            "kftpu_sched_plan_seconds",
            "wall time of one cluster-wide scheduling pass").observe(
                time.perf_counter() - t_pass)
        return Result()

    def _warm_pass(self, client: KubeClient, inventory: SliceInventory,
                   pending_warm: Optional[set] = None) -> None:
        """Advertise up to config.warm_pods still-free hosts as warm
        slots (post-plan occupancy: a host a bind just took is no
        longer free) and reconcile the pre-initialized pods onto them
        (scheduler/warmpool.py). Deterministic slot choice keeps warm
        pods from churning across steady passes; with the knob at 0
        any leftover pods/slots from a previous config are retired.
        Failures downgrade to a warning — warmth is an optimization,
        the pass must bind regardless."""
        import os

        from ..runtime.compile_cache import SHARED_CACHE_ROOT_ENV
        from . import warmpool
        n = max(0, int(self.config.warm_pods))
        try:
            slots = warmpool.free_hosts(inventory)[:n] if n else []
            warmpool.write_slots(client, slots)
            created, deleted = warmpool.reconcile_warm_pods(
                client, slots, inventory,
                cache_dir=os.environ.get(SHARED_CACHE_ROOT_ENV, ""),
                keep=pending_warm)
            obsreg.gauge(
                "kftpu_sched_warm_slots",
                "idle hosts currently advertised as warm-pod slots"
            ).set(len(slots))
            if created or deleted:
                obsreg.counter(
                    "kftpu_sched_warm_pods_total",
                    "warm pods created/retired by the scheduler's "
                    "warm pass", labels=("action",)).labels(
                        action="created").inc(created)
                obsreg.counter(
                    "kftpu_sched_warm_pods_total",
                    "warm pods created/retired by the scheduler's "
                    "warm pass", labels=("action",)).labels(
                        action="deleted").inc(deleted)
                log.info("scheduler: warm pool now %d slots "
                         "(+%d/-%d pods)", len(slots), created, deleted)
        except Exception as e:  # noqa: BLE001 — warmth is optional
            log.warning("scheduler: warm-pool pass failed: %s", e)

    # -------------------------------------------------------- observability

    def _trace_event(self, manifest: dict, name: str, **attrs) -> None:
        trace_job_event("scheduler", manifest, name, **attrs)

    def _note_queued(self, queued: list, manifests: dict) -> None:
        """First-seen bookkeeping for the wait histogram + exactly one
        "queued" trace event per wait; keys that left the queue by any
        path (bound, deleted, finished) are pruned."""
        now = time.time()
        current = {r.key for r in queued}
        for stale in set(self._queued_since) - current:
            del self._queued_since[stale]
        for req in queued:
            if req.key not in self._queued_since:
                self._queued_since[req.key] = now
                self._trace_event(manifests[req.key], "queued",
                                  queue=req.queue, chips=req.chips,
                                  priority=req.priority)

    def _export_queue_gauges(self, queued: list, bound: list,
                             decisions: Plan) -> None:
        """Per-queue depth and capacity gauges; a queue that drains
        exports zeros (not its stale last values)."""
        depth = obsreg.gauge("kftpu_sched_queue_depth",
                             "gangs waiting for a binding",
                             labels=("queue",))
        qchips = obsreg.gauge("kftpu_sched_queued_chips",
                              "chips demanded by waiting gangs",
                              labels=("queue",))
        bgangs = obsreg.gauge("kftpu_sched_bound_gangs",
                              "gangs currently bound to slices",
                              labels=("queue",))
        bchips = obsreg.gauge("kftpu_sched_bound_chips",
                              "chips currently bound to gangs",
                              labels=("queue",))
        newly_bound = {req.key for req, _ in decisions.binds}
        preempted = {req.key for req in decisions.preempts}
        stats: dict[str, list] = {}
        for req in queued:
            s = stats.setdefault(req.queue, [0, 0, 0, 0])
            if req.key not in newly_bound:
                s[0] += 1
                s[1] += req.chips
        for req, _ in bound:
            s = stats.setdefault(req.queue, [0, 0, 0, 0])
            if req.key not in preempted:
                s[2] += 1
                s[3] += req.chips
        for req, _ in decisions.binds:
            s = stats.setdefault(req.queue, [0, 0, 0, 0])
            s[2] += 1
            s[3] += req.chips
        self._known_queues |= set(stats)
        for q in self._known_queues:
            d, qc, bg, bc = stats.get(q, (0, 0, 0, 0))
            depth.labels(queue=q).set(d)
            qchips.labels(queue=q).set(qc)
            bgangs.labels(queue=q).set(bg)
            bchips.labels(queue=q).set(bc)

    # -------------------------------------------------------------- patches

    def _patch_state(self, client: KubeClient, manifest: dict, state: str,
                     reason: str, binding: Optional[Placement],
                     extra: Optional[dict] = None,
                     extra_fn=None) -> None:
        """Conflict-safe state write (cluster/client.py
        update_with_conflict_retry): the operator bumps restart counters
        and gang shapes on the SAME object concurrently — a stale-read
        write here must re-read and re-apply, never clobber.
        ``extra_fn(fresh_obj) -> annotation updates`` computes values
        that depend on the object's CURRENT state (preempt counts,
        resize histories) per attempt, so a retry never replays a stale
        read. Write-on-change: an object already in the desired state is
        left untouched (no MODIFIED event, no reconcile loop)."""

        def _mutate(obj: dict) -> Optional[dict]:
            updates: dict = {SCHED_STATE_ANNOTATION: state,
                             SCHED_REASON_ANNOTATION: reason,
                             **(extra or {})}
            if extra_fn is not None:
                updates.update(extra_fn(obj))
            # kube null-delete semantics: a removed binding writes None
            updates[BINDING_ANNOTATION] = (
                json.dumps(binding.to_dict())
                if binding is not None else None)
            anns = k8s.annotations_of(obj)
            dirty = any(
                (value is None and key in anns)
                or (value is not None and anns.get(key) != value)
                for key, value in updates.items())
            return apply_annotations(obj, updates) if dirty else None

        try:
            update_with_conflict_retry(client, *k8s.key_of(manifest),
                                       _mutate)
        except NotFoundError:
            pass   # deleted mid-pass: the delete event re-plans anyway

    def _clear_suspect(self, client: KubeClient, manifest: dict) -> None:
        def _mutate(obj: dict) -> Optional[dict]:
            if SUSPECT_ANNOTATION not in k8s.annotations_of(obj):
                return None   # already cleared by a concurrent pass
            return apply_annotations(obj, {SUSPECT_ANNOTATION: None})
        try:
            update_with_conflict_retry(client, *k8s.key_of(manifest),
                                       _mutate)
        except NotFoundError:
            pass   # deleted mid-pass: nothing left to clear

    def _mark_queued(self, client: KubeClient, manifest: dict,
                     reason: str) -> None:
        anns = k8s.annotations_of(manifest)
        if anns.get(SCHED_STATE_ANNOTATION) in (STATE_QUEUED,
                                                STATE_PREEMPTED) and \
                anns.get(SCHED_REASON_ANNOTATION) == reason:
            return  # idempotent: no write, no MODIFIED event, no loop
        state = STATE_PREEMPTED \
            if anns.get(SCHED_STATE_ANNOTATION) == STATE_PREEMPTED \
            else STATE_QUEUED
        self._patch_state(client, manifest, state, reason, binding=None)

    @staticmethod
    def _history_json(manifest: dict, from_chips: int, to_chips: int,
                      reason: str, now: float) -> str:
        """The updated resize-history annotation value: prior entries
        (malformed → dropped) plus this resize, capped at
        RESIZE_HISTORY_MAX, newest last."""
        hist = resize_history(manifest)
        hist.append({"time": round(now, 3), "fromChips": from_chips,
                     "toChips": to_chips, "reason": reason})
        return json.dumps(hist[-RESIZE_HISTORY_MAX:])

    def _count_resize(self, manifest: dict, from_chips: int,
                      to_chips: int, reason: str) -> None:
        direction = "grow" if to_chips > from_chips else \
            "shrink" if to_chips < from_chips else "migrate"
        obsreg.counter(
            "kftpu_sched_resizes_total",
            "elastic gang resizes applied (binding rewritten; the "
            "operator executes a checkpointed restart at the new "
            "shape)", labels=("direction",)).labels(
                direction=direction).inc()
        self._trace_event(manifest, "resized", direction=direction,
                          from_chips=from_chips, to_chips=to_chips,
                          reason=reason)

    def _apply_resize(self, client: KubeClient, manifest: dict,
                      old: Optional[Placement], new_placement: Placement,
                      reason: str) -> None:
        """Rewrite a bound gang's binding to the resized placement. The
        operator sees the binding's shape diverge from the running
        gang's and restarts it through the graceful GangResized path
        (SIGTERM → forced checkpoint → exit 75 → recreate at the new
        shape with resumeFrom) — a resize never burns backoff budget
        and never loses work past the forced save."""
        now = time.time()
        from_chips = old.chips if old is not None else 0
        self._patch_state(
            client, manifest, STATE_BOUND, f"resized: {reason}",
            binding=new_placement,
            # history APPENDS, so it must be computed from the object
            # as-written: a retry against a concurrently-updated history
            # re-reads and re-appends instead of dropping entries
            extra_fn=lambda obj: {
                RESIZE_HISTORY_ANNOTATION: self._history_json(
                    obj, from_chips, new_placement.chips, reason, now)})
        # counted AFTER the patch succeeded (the pass-wide invariant)
        self._count_resize(manifest, from_chips, new_placement.chips,
                           reason)
        log.info("scheduler: resized %s/%s %d -> %d chips (%s)",
                 k8s.namespace_of(manifest, "default"),
                 k8s.name_of(manifest), from_chips, new_placement.chips,
                 reason)

    def _apply_preempt(self, client: KubeClient, manifest: dict) -> None:
        """Unbind a victim: the operator observes the missing binding and
        tears the gang down through the graceful path, leaving the job
        QUEUED with resumeFrom set — preemption is a requeue, never a
        failure (no backoff budget burned). The count increments off the
        FRESH read per attempt (extra_fn), so a concurrent writer can
        never make one preemption read as zero or two."""
        self._patch_state(
            client, manifest, STATE_PREEMPTED,
            "preempted by a higher-priority job", binding=None,
            extra_fn=lambda obj: {
                PREEMPTED_COUNT_ANNOTATION: str(int(
                    k8s.annotations_of(obj).get(
                        PREEMPTED_COUNT_ANNOTATION, "0")) + 1)})
