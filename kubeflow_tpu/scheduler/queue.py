"""Priority queues with namespace quotas for the slice scheduler.

The reference platform delegated this to kube-batch/Volcano queues; here
the queue model is first-class and small: every scheduler-managed job
names a queue (``spec.schedulingPolicy.queue``, default "default"), jobs
are ordered by (priority desc, submission order) — strict priority with
FIFO ties — and each queue may cap the chips a NAMESPACE can hold bound
at once (the multi-tenant fairness floor: one team's burst cannot occupy
the whole cluster). Quota counts BOUND chips only: queued demand is free.

jax-free; consumed by scheduler/core.py (the k8s reconcile loop) and
scheduler/sim.py (the bench's contended-cluster simulation) so both run
the identical ordering/quota code.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

from ..api import k8s
from ..api.topology import SliceTopology, parse_topology
from ..api.trainingjob import (BINDING_ANNOTATION, DEFAULT_QUEUE,
                               RESIZE_HISTORY_ANNOTATION, TrainingJob)
from .health import HealthConfig
from .inventory import Placement


@dataclass
class QueueSpec:
    """One queue's policy: per-namespace bound-chip quotas.

    ``quota_chips`` maps namespace → max chips bound at once; the "*" key
    is the default for namespaces not named; absent/None = unlimited.
    """

    name: str
    quota_chips: dict = field(default_factory=dict)

    def quota_for(self, namespace: str) -> Optional[int]:
        q = self.quota_chips.get(namespace, self.quota_chips.get("*"))
        return int(q) if q is not None else None


@dataclass
class SchedulerConfig:
    """The scheduler's whole policy surface (rendered as the
    tpu-scheduler ConfigMap by manifests/training.py; bench.py flips the
    booleans to A/B FIFO vs backfill vs preemption)."""

    queues: dict = field(default_factory=dict)   # name -> QueueSpec
    # backfill: once the head-of-line job is blocked, later jobs may
    # still bind — but never into the head's reserved region
    backfill: bool = True
    # preemption: a blocked higher-priority job may reclaim preemptible
    # lower-priority gangs (cheapest victims first)
    preemption: bool = True
    # strict priority ordering; off = pure submission order (FIFO)
    priority_order: bool = True
    # node-health policy (scheduler/health.py): decay half-life,
    # quarantine/release thresholds, and the enabled master switch for
    # the whole feedback loop (scoring, quarantine, suspect evacuation)
    health: HealthConfig = field(default_factory=HealthConfig)
    # elastic gang resizing (jobs carrying schedulingPolicy minChips/
    # maxChips): the master switch for every resize plan — shrink a
    # lower-priority gang to admit a blocked head, shrink a gang whose
    # host died when no same-size rectangle exists, grow into idle
    # chips, migrate to defragment. Off = elastic bounds are ignored
    # and every gang keeps the fixed-shape contract.
    elastic: bool = True
    # grow-to-fill: bound elastic gangs may expand into idle chips once
    # the queue has drained (each grow is a checkpointed gang restart)
    grow: bool = True
    # defragmentation: migrate a bound elastic gang when re-placing it
    # strictly enlarges the cluster's largest contiguous free rectangle
    defrag: bool = True
    # a gang is not grown/migrated again until this long after its last
    # resize (restart-storm hysteresis; shrinks are urgent and exempt)
    grow_cooldown_s: float = 300.0
    # warm-pod pool size: the scheduler keeps up to this many
    # pre-initialized pods on idle hosts (scheduler/warmpool.py) and
    # prefers placements that adopt them — rebinds/resizes/scale-ups
    # start warm instead of cold. 0 = no warm pool (the default: warm
    # pods hold chips idle-but-initialized, an explicit capacity trade).
    warm_pods: int = 0

    def queue(self, name: str) -> QueueSpec:
        return self.queues.get(name) or QueueSpec(name)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "SchedulerConfig":
        d = dict(d or {})
        queues = {}
        for name, spec in (d.get("queues") or {}).items():
            queues[name] = QueueSpec(
                name=name, quota_chips=dict((spec or {}).get(
                    "quotaChips", {})))
        return cls(queues=queues,
                   backfill=bool(d.get("backfill", True)),
                   preemption=bool(d.get("preemption", True)),
                   priority_order=bool(d.get("priorityOrder", True)),
                   health=HealthConfig.from_dict(d.get("health")),
                   elastic=bool(d.get("elastic", True)),
                   grow=bool(d.get("grow", True)),
                   defrag=bool(d.get("defrag", True)),
                   grow_cooldown_s=float(
                       d.get("growCooldownSeconds", 300.0)),
                   warm_pods=int(d.get("warmPods", 0)))


@dataclass
class JobRequest:
    """The scheduler's view of one gang: what it needs and where it sits
    in the order. ``seq`` is the FIFO tiebreaker (submission order) —
    any totally-ordered value; the k8s loop uses submission_seq()'s
    (creationTimestamp, uid-tail) tuple, the sim uses plain ints."""

    namespace: str
    name: str
    queue: str
    priority: int
    preemptible: bool
    topology: SliceTopology
    num_slices: int
    seq: object
    # elastic bounds (schedulingPolicy.minChips/maxChips): total-chip
    # envelope the scheduler may resize this gang within; None = that
    # bound pins to the nominal shape (both None = fixed-shape job)
    min_chips: Optional[int] = None
    max_chips: Optional[int] = None
    # grow/defrag hysteresis: False while the job's last resize is
    # younger than the config cooldown (the k8s loop computes this from
    # the resize-history annotation; the sim leaves it True)
    grow_ok: bool = True

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def chips(self) -> int:
        """NOMINAL gang size (the spec shape) — quota and ordering use
        this; a resized gang's ACTUAL size lives on its Placement."""
        return self.topology.num_chips * self.num_slices

    @property
    def elastic(self) -> bool:
        return self.min_chips is not None or self.max_chips is not None


def elastic_topologies(req: JobRequest) -> list[SliceTopology]:
    """Every slice topology an elastic gang may run at: its generation's
    supported slice sizes whose TOTAL (x num_slices) falls inside the
    [minChips, maxChips] envelope, LARGEST first. Empty for fixed-shape
    jobs. The nominal shape is always a member (admission pins the
    envelope around it), so walking this list from the top is "try the
    biggest allowed, degrade one supported size at a time"."""
    if not req.elastic:
        return []
    gen = req.topology.generation
    nominal = req.topology.num_chips * req.num_slices
    lo = req.min_chips if req.min_chips is not None else nominal
    hi = req.max_chips if req.max_chips is not None else nominal
    out = []
    for c in sorted(gen.supported_chip_counts, reverse=True):
        if lo <= c * req.num_slices <= hi:
            out.append(parse_topology(f"{gen.name}-{c}"))
    return out


def placement_slice_chips(placement: Placement) -> int:
    """Per-slice chip count of a (possibly resized) placement."""
    return placement.slices[0].chips if placement.slices \
        else placement.chips


def resize_history(manifest: dict) -> list[dict]:
    """Parse the resize-history annotation; [] when absent/malformed (a
    corrupt history only costs the audit trail + grow hysteresis, never
    a pass)."""
    import json
    raw = k8s.annotations_of(manifest).get(RESIZE_HISTORY_ANNOTATION)
    if not raw:
        return []
    try:
        hist = json.loads(raw)
    except ValueError:
        return []
    return [h for h in hist if isinstance(h, dict)] \
        if isinstance(hist, list) else []


_UID_NUM = re.compile(r"(\d+)$")


def submission_seq(manifest: dict) -> tuple:
    """Stable submission order for a job manifest:
    (creationTimestamp, uid numeric tail). A real apiserver stamps
    creationTimestamp (RFC3339 — lexicographic == chronological), which
    carries the FIFO contract; UUID uids contribute nothing there.
    FakeCluster sets no timestamp but mints "uid-N" monotonically, so
    the numeric uid tail orders its jobs (parsed, not lexical —
    "uid-10" must follow "uid-9"). Jobs tying on both fall back to the
    caller's key tiebreaker."""
    meta = manifest.get("metadata", {})
    ts = str(meta.get("creationTimestamp", "") or "")
    m = _UID_NUM.search(str(meta.get("uid", "")))
    return (ts, int(m.group(1)) if m else 0)


def request_of(job: TrainingJob, manifest: dict) -> Optional[JobRequest]:
    """JobRequest for a scheduler-managed job with a TPU gang; None for
    jobs the scheduler does not own (no schedulingPolicy, or no TPU
    replicas — CPU-only legacy kinds keep the legacy path)."""
    policy = job.scheduling_policy
    tpu = job.tpu_spec
    if policy is None or tpu is None or tpu.topology is None:
        return None
    return JobRequest(
        namespace=job.namespace, name=job.name,
        queue=policy.queue or DEFAULT_QUEUE,
        priority=policy.priority, preemptible=policy.preemptible,
        topology=tpu.topology, num_slices=tpu.num_slices,
        seq=submission_seq(manifest),
        min_chips=policy.min_chips, max_chips=policy.max_chips)


def binding_of(manifest: dict) -> Optional[Placement]:
    """Parse the binding annotation; None when absent or malformed (a
    corrupt binding reads as unbound — the scheduler re-places, which is
    always safe: placement is idempotent against the same inventory).
    THE one parse of the scheduling.kubeflow.org/binding wire contract:
    the operator's gate (controllers/tpujob.py) and the scheduler's pass
    (scheduler/core.py) both consume this + binding_matches, so the two
    sides of the annotation cannot drift."""
    import json
    raw = k8s.annotations_of(manifest).get(BINDING_ANNOTATION)
    if not raw:
        return None
    try:
        return Placement.from_dict(json.loads(raw))
    except (ValueError, KeyError, TypeError):
        return None


def binding_matches(placement: Placement, job: TrainingJob) -> bool:
    """Whether a persisted binding still describes a shape this job may
    RUN at. Fixed-shape jobs: exactly the spec shape — a spec reshaped
    under its binding reads as unbound on both sides (the operator must
    not create a gang on a stale placement; the scheduler re-plans it).
    ELASTIC jobs (schedulingPolicy minChips/maxChips) additionally
    accept a scheduler-resized shape: same generation, same slice
    count, total chips inside the envelope — that binding is the
    resize plan the operator executes, not drift."""
    tpu = job.tpu_spec
    if tpu is None or tpu.topology is None:
        return False
    if placement.topology == tpu.topology.name \
            and placement.num_slices == tpu.num_slices:
        return True
    policy = job.scheduling_policy
    if policy is None or not policy.elastic \
            or placement.num_slices != tpu.num_slices:
        return False
    try:
        topo = parse_topology(placement.topology)
    except ValueError:
        return False
    if topo.generation.name != tpu.topology.generation.name:
        return False
    total = topo.num_chips * placement.num_slices
    if placement.slices and placement.chips != total:
        return False   # rects disagree with the claimed topology
    lo, hi = policy.chip_bounds(tpu.topology.num_chips * tpu.num_slices)
    return lo <= total <= hi


def ordered(requests: list[JobRequest],
            config: SchedulerConfig) -> list[JobRequest]:
    """The scheduling order: strict priority then submission order (and
    pure FIFO when priority_order is off — the bench's baseline arm).
    One merged order across queues: queues scope QUOTA and dashboards,
    not ordering — cross-queue starvation is governed by priority."""
    if config.priority_order:
        return sorted(requests, key=lambda r: (-r.priority, r.seq, r.key))
    return sorted(requests, key=lambda r: (r.seq, r.key))


def bound_chips(bound: list, queue: str, namespace: str) -> int:
    """Chips currently bound for (queue, namespace) — the quota meter.
    ``bound`` is [(JobRequest, Placement)]."""
    return sum(p.chips for r, p in bound
               if r.queue == queue and r.namespace == namespace)


def over_quota(req: JobRequest, bound: list,
               config: SchedulerConfig) -> bool:
    quota = config.queue(req.queue).quota_for(req.namespace)
    if quota is None:
        return False
    return bound_chips(bound, req.queue, req.namespace) + req.chips > quota
