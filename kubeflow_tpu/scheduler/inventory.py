"""Slice inventory: the cluster's TPU chips as ICI-topology grids.

The unit of placement is a CONTIGUOUS sub-slice: a gang's chips must form
an axis-aligned rectangle of the pool's physical chip mesh, because XLA
compiles collectives over the ICI torus — a fragmented allocation would
route neighbor exchanges through chips the job does not own (Podracer's
gang-allocated slices, arxiv 2104.06272). So the inventory models every
TPU node pool as a 2D occupancy grid over its topology's ``ici_mesh``
(api/topology.py is the single source of truth for what a topology name
means) and bin-packs job gangs onto free rectangles.

Placement scoring is fragmentation-first: among all feasible rectangles
(both orientations, every pool) the inventory picks the one that leaves
the LARGEST contiguous free rectangle behind — stranding chips in slivers
no future gang can use is the failure mode that quietly halves a
cluster's effective capacity. Ties break best-fit (tightest pool first)
and then lexicographically, so placement is fully deterministic: the same
request sequence always produces the same packing (tests pin this).

Wire format: a gang's placement serializes to the JSON carried by the
``scheduling.kubeflow.org/binding`` annotation (api/trainingjob.py
BINDING_ANNOTATION) — one rect per slice::

    {"topology": "v5e-8", "numSlices": 1, "chips": 8,
     "slices": [{"pool": "pool-a", "x": 0, "y": 0, "h": 2, "w": 4}]}

jax-free, like the rest of the control plane.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional

from ..api import k8s
from ..api.topology import SliceTopology, parse_topology
from . import health

# node labels the inventory reads (the ones GKE TPU node pools carry and
# cluster/fake.py add_tpu_slice_nodes renders)
POOL_LABEL = "kubeflow.org/pool"
TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"

# sentinel owner for cells of unavailable hosts (NotReady, quarantined,
# missing from the node list): carved out of placeable rectangles by
# carve_down(), never released by a job teardown
DOWN_OWNER = "\x00down"


@dataclass(frozen=True)
class SliceRect:
    """One slice's chips: an axis-aligned rectangle of a pool's grid."""

    pool: str
    x: int          # row of the top-left chip
    y: int          # col of the top-left chip
    h: int
    w: int

    @property
    def chips(self) -> int:
        return self.h * self.w

    def cells(self) -> Iterable[tuple[str, int, int]]:
        for i in range(self.x, self.x + self.h):
            for j in range(self.y, self.y + self.w):
                yield (self.pool, i, j)

    def to_dict(self) -> dict:
        return {"pool": self.pool, "x": self.x, "y": self.y,
                "h": self.h, "w": self.w}

    @classmethod
    def from_dict(cls, d: dict) -> "SliceRect":
        return cls(pool=d["pool"], x=int(d["x"]), y=int(d["y"]),
                   h=int(d["h"]), w=int(d["w"]))


@dataclass
class Placement:
    """A whole gang's assignment: one rect per slice (slices may land in
    different pools — DCN-level data parallelism does not need ICI
    contiguity ACROSS slices, only within each)."""

    topology: str
    num_slices: int
    slices: list[SliceRect]
    # warm-pod slots this placement covers, stamped by the scheduler at
    # bind time ([{"pool": p, "host": i}] — scheduler/warmpool.py): the
    # operator adopts exactly these pre-initialized pods instead of
    # cold-creating. Advisory: absent/extra entries never invalidate a
    # binding (binding_matches ignores it).
    warm_hosts: list = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.warm_hosts is None:
            self.warm_hosts = []

    @property
    def chips(self) -> int:
        return sum(r.chips for r in self.slices)

    def to_dict(self) -> dict:
        d = {"topology": self.topology, "numSlices": self.num_slices,
             "chips": self.chips,
             "slices": [r.to_dict() for r in self.slices]}
        if self.warm_hosts:
            d["warmHosts"] = [dict(w) for w in self.warm_hosts]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Placement":
        warm = []
        for w in d.get("warmHosts", []) or []:
            if isinstance(w, dict) and "pool" in w and "host" in w:
                warm.append({"pool": str(w["pool"]),
                             "host": int(w["host"])})
        return cls(topology=d["topology"],
                   num_slices=int(d.get("numSlices", 1)),
                   slices=[SliceRect.from_dict(r)
                           for r in d.get("slices", [])],
                   warm_hosts=warm)


class PoolState:
    """Occupancy grid over one node pool's physical chip mesh."""

    def __init__(self, name: str, topology: SliceTopology):
        self.name = name
        self.topology = topology
        rows, cols = (topology.ici_mesh + (1, 1))[:2]
        self.rows, self.cols = rows, cols
        # owner key per cell ("" = free); owners are "ns/name" job keys
        self.grid: list[list[str]] = [[""] * cols for _ in range(rows)]

    @property
    def total_chips(self) -> int:
        return self.rows * self.cols

    @property
    def free_chips(self) -> int:
        return sum(1 for row in self.grid for c in row if not c)

    def owners(self) -> set[str]:
        return {c for row in self.grid for c in row if c}

    def fits(self, x: int, y: int, h: int, w: int) -> bool:
        if x + h > self.rows or y + w > self.cols:
            return False
        return all(not self.grid[i][j]
                   for i in range(x, x + h) for j in range(y, y + w))

    def occupy(self, owner: str, rect: SliceRect) -> None:
        for _, i, j in rect.cells():
            if self.grid[i][j]:
                raise ValueError(
                    f"pool {self.name} cell ({i},{j}) already owned by "
                    f"{self.grid[i][j]!r} (binding drift — rebuild the "
                    f"inventory from bindings before placing)")
            self.grid[i][j] = owner

    def release(self, owner: str) -> int:
        freed = 0
        for row in self.grid:
            for j, c in enumerate(row):
                if c == owner:
                    row[j] = ""
                    freed += 1
        return freed

    def max_free_rect(self) -> int:
        """Area of the largest all-free rectangle (the classic
        histogram-stack sweep) — the fragmentation score's numerator."""
        best = 0
        heights = [0] * self.cols
        for row in self.grid:
            for j, c in enumerate(row):
                heights[j] = 0 if c else heights[j] + 1
            stack: list[tuple[int, int]] = []   # (start col, height)
            for j, hgt in enumerate(heights + [0]):
                start = j
                while stack and stack[-1][1] >= hgt:
                    s, sh = stack.pop()
                    best = max(best, sh * (j - s))
                    start = s
                stack.append((start, hgt))
        return best


class SliceInventory:
    """All pools of the cluster; the scheduler's placement engine."""

    def __init__(self, pools: Optional[list[PoolState]] = None):
        self.pools: dict[str, PoolState] = {
            p.name: p for p in sorted(pools or [], key=lambda p: p.name)}
        # cells of unavailable hosts (NotReady / quarantined / missing):
        # carve_down() occupies the still-free ones AFTER live bindings
        # re-occupy, so a Ready-condition flap never invalidates a
        # healthy gang's binding by itself
        self.down_cells: set = set()
        # node name -> that host's cells (suspect-evacuation lookups)
        self.cells_by_node: dict[str, set] = {}

    # -- construction -------------------------------------------------------

    @classmethod
    def from_nodes(cls, nodes: list[dict]) -> "SliceInventory":
        """Group nodes by pool label; each labeled pool is one physical
        slice of its topology label's mesh (the shape cluster/fake.py
        add_tpu_slice_nodes provisions and GKE TPU node pools mirror).
        Every pool advertises its FULL grid; hosts that are NotReady,
        quarantined (kubeflow.org/quarantine — scheduler/health.py), or
        missing from the node list contribute their exact cells to
        ``down_cells`` instead of the old bottom-row truncation, so the
        carve-out lands on the failing host, not whichever host happened
        to own the last row. Hosts map to cells row-major in natural
        node-name order (health.host_cells)."""
        by_pool: dict[str, tuple[SliceTopology, list]] = {}
        for node in nodes:
            labels = k8s.labels_of(node)
            pool = labels.get(POOL_LABEL)
            topo_name = labels.get(TOPOLOGY_LABEL)
            if not pool or not topo_name:
                continue
            try:
                topo = parse_topology(topo_name)
            except ValueError:
                continue
            available = k8s.condition_true(node, "Ready") \
                and not health.is_quarantined(node)
            prev = by_pool.get(pool)
            hosts = prev[1] if prev else []
            hosts.append((k8s.name_of(node), available))
            by_pool[pool] = (topo, hosts)
        pools, down, by_node = [], set(), {}
        for name, (topo, hosts) in sorted(by_pool.items()):
            state = PoolState(name, topo)
            hosts.sort(key=lambda h: health.host_sort_key(h[0]))
            # Host index comes from the node's NAME (its trailing
            # integer) when the POOL parses consistently — every name
            # yields a distinct in-range index — so a deleted middle
            # node does not shift its neighbors' cell attribution one
            # block over (positional assignment would carve/quarantine
            # the wrong chips). A pool whose names do NOT form such a
            # set (hash-suffixed GKE names where trailing digits are
            # noise, duplicates, out-of-range) falls back to positional
            # assignment for the WHOLE pool: consistent-but-wrong beats
            # half-trusted, and the natural sort keeps it deterministic.
            name_idx = [health.host_name_index(n) for n, _a in hosts]
            trusted = (len(hosts) <= topo.num_hosts
                       and all(i is not None and 0 <= i < topo.num_hosts
                               for i in name_idx)
                       and len(set(name_idx)) == len(name_idx))
            used: set = set()
            assigned: list = []
            if trusted:
                for (node_name, available), idx in zip(hosts, name_idx):
                    used.add(idx)
                    assigned.append((node_name, available, idx))
            else:
                for idx, (node_name, available) in enumerate(hosts):
                    if idx >= topo.num_hosts:
                        break   # more nodes than the topology has hosts
                    used.add(idx)
                    assigned.append((node_name, available, idx))
            for node_name, available, i in assigned:
                cells = set(health.host_cells(name, topo, i))
                by_node[node_name] = cells
                if not available:
                    down |= cells
            # hosts the topology expects but no node claims (deleted
            # node objects): their chips are down too
            for i in range(topo.num_hosts):
                if i not in used:
                    down |= set(health.host_cells(name, topo, i))
            pools.append(state)
        inv = cls(pools)
        inv.down_cells = down
        inv.cells_by_node = by_node
        return inv

    def carve_down(self) -> int:
        """Occupy every still-free down cell with the DOWN sentinel so
        placement scoring and rect search both see them as unusable.
        Bindings over down cells were already rejected by
        valid_binding, so nothing live sits under the carve; repeated
        Ready-condition flaps are absorbed by write-on-change
        idempotence plus flap scoring (a chronically flapping host
        quarantines itself — scheduler/core.py folds a not-ready event
        per Ready→NotReady transition)."""
        carved = 0
        for pool_name, x, y in self.down_cells:
            pool = self.pools.get(pool_name)
            if pool is None or x >= pool.rows or y >= pool.cols:
                continue
            if not pool.grid[x][y]:
                pool.grid[x][y] = DOWN_OWNER
                carved += 1
        return carved

    # -- accounting ---------------------------------------------------------

    @property
    def total_chips(self) -> int:
        return sum(p.total_chips for p in self.pools.values())

    @property
    def free_chips(self) -> int:
        return sum(p.free_chips for p in self.pools.values())

    def bind(self, owner: str, placement: Placement) -> None:
        for rect in placement.slices:
            pool = self.pools.get(rect.pool)
            if pool is None:
                raise ValueError(f"binding names unknown pool {rect.pool!r}")
            pool.occupy(owner, rect)

    def release(self, owner: str) -> int:
        return sum(p.release(owner) for p in self.pools.values())

    def valid_binding(self, placement: Placement) -> bool:
        """Whether a persisted binding still fits this inventory's
        geometry (pool exists, rect in range) AND stays clear of down
        hosts (NotReady / quarantined / deleted) — a pool deleted, a
        host lost, or a host quarantined under a bound job must requeue
        it for a replan, not crash the pass or leave the gang pinned to
        chips that cannot run it."""
        for rect in placement.slices:
            pool = self.pools.get(rect.pool)
            if pool is None or rect.x + rect.h > pool.rows \
                    or rect.y + rect.w > pool.cols:
                return False
            if self.down_cells and not \
                    self.down_cells.isdisjoint(rect.cells()):
                return False
        return True

    # -- placement ----------------------------------------------------------

    @staticmethod
    def _orientations(topo: SliceTopology,
                      flexible: bool = False) -> list[tuple[int, int]]:
        if not flexible:
            h, w = (topo.ici_mesh + (1, 1))[:2]
            return [(h, w)] if h == w else [(h, w), (w, h)]
        # flexible (elastic-resize) placement: ANY rectangle of the
        # right chip count, not just the canonical ICI mesh — a gang
        # shrunk onto a pool's surviving host must be able to take that
        # host's 1 x chips_per_host strip even though the named
        # topology's default mesh is square. Near-square shapes first
        # (fewest ICI hops), deterministic order.
        n = topo.num_chips
        shapes = sorted(
            {(h, n // h) for h in range(1, n + 1) if n % h == 0},
            key=lambda hw: (abs(hw[0] - hw[1]), hw[0]))
        return shapes

    def _candidates(self, topo: SliceTopology,
                    avoid: Optional[set] = None,
                    flexible: bool = False,
                    prefer: Optional[set] = None
                    ) -> Iterable[tuple[tuple, SliceRect]]:
        """Every feasible rect for ONE slice, with its score key (lower =
        better). Score: maximize the pool's largest free rectangle AFTER
        the cut (fragmentation), then best-fit (least free pool space),
        then warm-slot overlap (``prefer`` cells — a rect covering a
        pre-initialized warm pod adopts it instead of cold-starting;
        preference only, never worth fragmenting the pool over), then
        deterministic position order."""
        for pname in sorted(self.pools):
            pool = self.pools[pname]
            for h, w in self._orientations(topo, flexible=flexible):
                for x in range(pool.rows - h + 1):
                    for y in range(pool.cols - w + 1):
                        if not pool.fits(x, y, h, w):
                            continue
                        rect = SliceRect(pname, x, y, h, w)
                        if avoid and not avoid.isdisjoint(rect.cells()):
                            continue
                        pool.occupy("\x00probe", rect)
                        after = pool.max_free_rect()
                        pool.release("\x00probe")
                        warm = len(prefer & set(rect.cells())) \
                            if prefer else 0
                        key = (-after, pool.free_chips, -warm,
                               pname, x, y, h)
                        yield key, rect

    def place_gang(self, topology: SliceTopology, num_slices: int,
                   avoid: Optional[set] = None,
                   flexible: bool = False,
                   prefer: Optional[set] = None) -> Optional[Placement]:
        """Greedy per-slice best-placement for a whole gang, or None when
        any slice cannot be cut. ``avoid`` is a set of (pool, x, y) cells
        placements must not touch (the head-of-line reservation —
        scheduler/core.py). ``flexible`` admits any rectangle of the
        topology's chip count, not just its canonical mesh (elastic
        resize placement — scheduler/core.py resize paths). ``prefer``
        cells tip otherwise-tied candidates (warm-pod slots —
        scheduler/warmpool.py). The inventory is left UNCHANGED; callers
        bind() the returned placement explicitly."""
        rects: list[SliceRect] = []
        try:
            for _ in range(num_slices):
                best = min(self._candidates(topology, avoid,
                                            flexible=flexible,
                                            prefer=prefer),
                           key=lambda kr: kr[0], default=None)
                if best is None:
                    return None
                rect = best[1]
                self.pools[rect.pool].occupy("\x00tentative", rect)
                rects.append(rect)
        finally:
            for p in self.pools.values():
                p.release("\x00tentative")
        return Placement(topology=topology.name, num_slices=num_slices,
                         slices=rects)

    def reserve_for(self, topology: SliceTopology, num_slices: int,
                    avoid: Optional[set] = None) -> set:
        """The head-of-line reservation: a geometry-only placement
        (job occupancy ignored — those chips will free when their gangs
        finish) whose cells backfill jobs must keep clear, so the blocked
        head's target region only ever DRAINS. Down-host cells DO carry
        into the ghost (a reservation on a dead or quarantined host
        would never drain), as does the head's own ``avoid`` set (a
        suspect host the head is evacuating). Empty set when the request
        can never fit this cluster (reserving would deadlock the queue
        behind an impossible job)."""
        ghost = SliceInventory(
            [PoolState(p.name, p.topology) for p in self.pools.values()])
        for name, pool in self.pools.items():
            # mirror ONLY the down sentinel: those cells never drain
            ghost.pools[name].grid = [
                [c if c == DOWN_OWNER else "" for c in row]
                for row in pool.grid]
        placement = ghost.place_gang(topology, num_slices, avoid=avoid)
        if placement is None:
            return set()
        return {cell for rect in placement.slices for cell in rect.cells()}
