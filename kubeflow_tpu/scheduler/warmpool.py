"""Warm-pod pools: pre-initialized pods on idle slice rectangles.

The third rung of the warm-start stack (after the shared compile cache
and the AOT executable export — runtime/compile_cache.py, runtime/aot.py):
even a warm-cached restart pays pod scheduling + image pull + TPU
runtime/backend bring-up before the first byte of cache is read. The
scheduler therefore advertises up to ``SchedulerConfig.warm_pods`` idle
HOSTS (free of any binding after each planning pass) as warm slots, and
keeps one pre-initialized pod on each — backend up, cache volume
mounted, executables prefetchable. A bind whose placement covers a warm
slot ADOPTS it: the binding records the covered slots (``warmHosts`` on
the Placement wire format), the operator retires the warm pod and stamps
the gang's pods with the adoption annotation + ``KFTPU_WARM_START`` env,
and the rebind starts against an already-initialized host instead of a
cold one. Preemption re-binds, elastic resizes, and quarantine
migrations all ride the same path — they are exactly the restarts the
warm pool exists for.

This module is the CONTRACT between the two processes (the binding_of
pattern): slot wire format, warm-pod naming/labels, and the parse
helpers both sides consume. The scheduler maintains the pods
(scheduler/core.py warm pass); the operator adopts them
(controllers/tpujob.py). jax-free.
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from ..api import k8s
from ..api.topology import parse_topology
from ..cluster.fake import TPU_RESOURCE
from .inventory import POOL_LABEL, Placement, SliceInventory
from . import health

log = logging.getLogger(__name__)

# label carried by every warm pod (the operator's adoption lookup and
# kubectl's view of the pool)
WARM_POD_LABEL = "kubeflow.org/warm-pod"
# the warm pod's slot, as a JSON {"pool": p, "host": i} annotation
WARM_HOST_ANNOTATION = "scheduling.kubeflow.org/warm-host"
# stamped on every gang pod created over an adopted slot (audit +
# dashboards); value = JSON list of adopted {"pool","host"} slots
ADOPTED_ANNOTATION = "scheduling.kubeflow.org/adopted-warm-pods"
# rendered into adopted gangs' workers: the host is pre-initialized, so
# the AOT/compile-cache rungs see a warm filesystem (informational —
# the worker's start_kind histogram still measures what actually ran)
WARM_START_ENV = "KFTPU_WARM_START"

# where warm pods and the slots ConfigMap live (the scheduler's own
# namespace — warm pods are cluster infrastructure, not job children)
WARM_POOL_NAMESPACE = "kubeflow"
SLOTS_CONFIG_MAP = "tpu-warm-pool"
SLOTS_KEY = "slots.json"


def warm_pod_name(pool: str, host: int) -> str:
    return f"warm-{pool}-h{host}"


def slots_of(client) -> list[dict]:
    """Parse the advertised warm slots; [] when absent/malformed (a
    corrupt advertisement only costs warmth, never a pass)."""
    cm = client.get_or_none("v1", "ConfigMap", WARM_POOL_NAMESPACE,
                            SLOTS_CONFIG_MAP)
    if cm is None:
        return []
    try:
        slots = json.loads((cm.get("data") or {}).get(SLOTS_KEY, "") or
                           "[]")
    except ValueError:
        return []
    out = []
    for s in slots if isinstance(slots, list) else []:
        try:
            out.append({"pool": str(s["pool"]), "host": int(s["host"])})
        except (KeyError, TypeError, ValueError):
            continue   # one malformed slot must not cost the pass
    return out


def slot_cells(slots: list[dict], inventory: SliceInventory) -> set:
    """Every cell the advertised slots cover — the placement-preference
    set plan() nudges binds toward (adoption beats a cold rectangle)."""
    cells: set = set()
    for s in slots:
        pool = inventory.pools.get(s["pool"])
        if pool is None:
            continue
        cells |= set(health.host_cells(s["pool"], pool.topology,
                                       s["host"]))
    return cells


def covered_slots(placement: Placement, slots: list[dict],
                  inventory: SliceInventory) -> list[dict]:
    """The advertised slots a placement's rects overlap — what the
    scheduler stamps into the binding's ``warmHosts`` so the operator
    knows exactly which warm pods this gang adopts."""
    placed = {c for r in placement.slices for c in r.cells()}
    out = []
    for s in slots:
        pool = inventory.pools.get(s["pool"])
        if pool is None:
            continue
        cells = set(health.host_cells(s["pool"], pool.topology,
                                      s["host"]))
        if cells & placed:
            out.append(dict(s))
    return out


def build_warm_pod(pool: str, host: int, topology_name: str,
                   image: str = "ghcr.io/kubeflow-tpu/worker:v0.1.0",
                   cache_dir: str = "",
                   node_name: str = "") -> dict:
    """The pre-initialized pod for one slot: pinned to the slot's pool
    AND (when the inventory can name it) the slot's exact node — the
    pool selector alone would let kube park the pod on a different
    host, making the advertised slot a fiction — requesting the host's
    TPU chips (initialize() needs real device access, and a
    zero-resource pod would double-book a host a gang occupies),
    running the prewarm entrypoint (backend init + cache mount held
    open), carrying the slot annotation the adoption path reads. With a
    shared cache root the tpu-compile-cache claim is mounted there so
    the prewarm actually touches the volume a landing gang will read."""
    try:
        chips = parse_topology(topology_name).chips_per_host \
            if topology_name else 0
    except ValueError:
        chips = 0
    container: dict = {
        "name": "prewarm",
        "image": image,
        "command": ["python", "-m", "kubeflow_tpu.runtime.bootstrap",
                    "--prewarm"],
        "env": ([{"name": "KFTPU_COMPILE_CACHE_DIR",
                  "value": cache_dir}] if cache_dir else []),
    }
    if chips:
        container["resources"] = {"limits": {TPU_RESOURCE: chips}}
    spec: dict = {
        "restartPolicy": "Never",
        "nodeSelector": {POOL_LABEL: pool},
        "containers": [container],
    }
    if node_name:
        spec["nodeName"] = node_name
    if cache_dir and "://" not in cache_dir:
        container["volumeMounts"] = [{"name": "kftpu-cache",
                                      "mountPath": cache_dir}]
        spec["volumes"] = [{"name": "kftpu-cache",
                            "persistentVolumeClaim":
                            {"claimName": "tpu-compile-cache"}}]
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": warm_pod_name(pool, host),
            "namespace": WARM_POOL_NAMESPACE,
            "labels": {WARM_POD_LABEL: "true"},
            "annotations": {WARM_HOST_ANNOTATION: json.dumps(
                {"pool": pool, "host": host,
                 "topology": topology_name})},
        },
        "spec": spec,
    }


def node_for_slot(inventory: SliceInventory, pool: str,
                  host: int) -> str:
    """The node name owning the slot's cells, or "" when the inventory
    cannot say (sim-built inventories carry no node map) — the warm
    pod then degrades to the pool selector alone."""
    pstate = inventory.pools.get(pool)
    if pstate is None:
        return ""
    cells = set(health.host_cells(pool, pstate.topology, host))
    for node, owned in inventory.cells_by_node.items():
        if cells <= owned:
            return node
    return ""


def list_warm_pods(client) -> list[dict]:
    return client.list("v1", "Pod", WARM_POOL_NAMESPACE,
                       selector={WARM_POD_LABEL: "true"})


def reconcile_warm_pods(client, slots: list[dict],
                        inventory: SliceInventory,
                        cache_dir: str = "",
                        keep: Optional[set] = None) -> tuple[int, int]:
    """Make the live warm pods match the advertised slots: create a pod
    per slot that lacks one, delete pods whose slot is no longer
    advertised (the host got bound, went down, or the knob shrank).
    ``keep`` is the set of (pool, host) slots named by a live binding's
    warmHosts — those pods are PENDING ADOPTION by the operator, which
    runs after this pass; retiring them here would race the adoption
    into a cold create. Write-on-change; returns (created, deleted)."""
    from ..cluster.client import NotFoundError
    keep = keep or set()
    wanted = {(s["pool"], s["host"]): s for s in slots}
    have: dict[tuple, dict] = {}
    deleted = 0
    for pod in list_warm_pods(client):
        try:
            meta = json.loads(k8s.annotations_of(pod).get(
                WARM_HOST_ANNOTATION, "") or "{}")
            slot_key = (str(meta["pool"]), int(meta["host"]))
        except (KeyError, TypeError, ValueError):
            slot_key = None
        # a DEAD prewarm (ImagePullBackOff crash, prewarm init failure
        # — restartPolicy Never) must not satisfy its slot: retire it
        # so the create loop below brings a live one back, instead of
        # the slot staying "warm" behind a corpse forever
        dead = pod.get("status", {}).get("phase") in ("Failed",
                                                      "Succeeded")
        if not dead and slot_key is not None and slot_key in keep \
                and slot_key not in wanted:
            continue   # pending adoption: the operator retires it
        if dead or slot_key is None or slot_key not in wanted \
                or slot_key in have:
            # unparseable, stale, or duplicate: retire it
            try:
                client.delete("v1", "Pod", WARM_POOL_NAMESPACE,
                              k8s.name_of(pod))
                deleted += 1
            except NotFoundError:
                pass
            continue
        have[slot_key] = pod
    created = 0
    for slot_key, slot in wanted.items():
        if slot_key in have:
            continue
        pool = inventory.pools.get(slot["pool"])
        topo_name = pool.topology.name if pool is not None else ""
        client.create(build_warm_pod(
            slot["pool"], slot["host"], topo_name, cache_dir=cache_dir,
            node_name=node_for_slot(inventory, slot["pool"],
                                    slot["host"])))
        created += 1
    return created, deleted


def write_slots(client, slots: list[dict]) -> None:
    """Persist the advertised slots (write-on-change: a steady-state
    pass writes nothing)."""
    body = json.dumps(sorted(slots, key=lambda s: (s["pool"],
                                                   s["host"])))
    cm = client.get_or_none("v1", "ConfigMap", WARM_POOL_NAMESPACE,
                            SLOTS_CONFIG_MAP)
    if cm is not None and (cm.get("data") or {}).get(SLOTS_KEY) == body:
        return
    if cm is None:
        if not slots:
            return   # feature off and never on: no empty CM litter
        obj = k8s.make("v1", "ConfigMap", SLOTS_CONFIG_MAP,
                       WARM_POOL_NAMESPACE)
        obj["data"] = {SLOTS_KEY: body}
        client.create(obj)
    else:
        client.patch("v1", "ConfigMap", WARM_POOL_NAMESPACE,
                     SLOTS_CONFIG_MAP, {"data": {SLOTS_KEY: body}})


def free_hosts(inventory: SliceInventory) -> list[dict]:
    """Hosts whose every cell is free (no binding, not down) — the
    candidate warm slots, deterministically ordered (sorted pools,
    ascending host index) so repeated passes advertise the same slots
    and warm pods never churn while the cluster is steady."""
    out = []
    for pname in sorted(inventory.pools):
        pool = inventory.pools[pname]
        for host in range(pool.topology.num_hosts):
            cells = health.host_cells(pname, pool.topology, host)
            if all(0 <= x < pool.rows and 0 <= y < pool.cols
                   and not pool.grid[x][y] for _p, x, y in cells):
                out.append({"pool": pname, "host": host})
    return out
