"""Node-health scoring, quarantine, and suspect-host contracts.

Recovery used to be placement-blind: a gang whose host is flaky, slow,
or repeatedly dying restarts onto the SAME sub-rectangle forever, and
the inventory only dropped hosts already marked NotReady — nothing fed
runtime failure evidence back into placement ("Dynamic Scheduling of
MPI-based Distributed Deep Learning Training Jobs", PAPERS.md, motivates
rescheduling off observed behavior, not static capacity). This module is
the shared vocabulary of that feedback loop:

- **Health scoring.** Each TPU host carries an exponential-decay failure
  score in its ``kubeflow.org/health`` annotation. Writers fold events
  in (``fold_event``): the operator attributes pod crashes / stalled
  workers / step-time skew to the host they ran on; the scheduler folds
  Ready-condition flaps. The annotation itself carries ``(score, time)``
  so any writer can decay-then-add without shared clocks — and the fold
  is conflict-safe: record_host_event rides
  cluster/client.py update_with_conflict_retry, so concurrent folds
  both land. The decay is
  the forgiveness: a host that stops failing earns its way back.
- **Quarantine.** When a host's decayed score crosses
  ``HealthConfig.quarantine_threshold`` the scheduler writes the
  ``kubeflow.org/quarantine`` annotation (reason + expiry);
  ``SliceInventory.from_nodes`` carves quarantined hosts out of
  placeable rectangles. Release is probational: expiry passed AND score
  decayed below ``release_threshold`` — a transient blip does not
  permanently shrink the fleet, a still-failing host gets its
  quarantine extended. ``reason: "manual"`` (a human's kubectl
  annotate) is never auto-released.
- **Suspect rebind.** When the operator tears a gang down for a fault
  attributable to one host, it records the node in the job's
  ``scheduling.kubeflow.org/suspect-host`` annotation; the scheduler
  replans the binding EXCLUDING that host's cells and clears the
  annotation on the rebind — the gang migrates instead of crash-looping
  in place, without waiting for the score to cross the quarantine
  threshold.

The annotation names live in api/trainingjob.py (single definition);
the parse/fold helpers live HERE and are consumed by BOTH the operator
(controllers/tpujob.py) and the scheduler (scheduler/core.py) — the
binding_of pattern, enforced by tests/test_lint.py. jax-free, like the
rest of the scheduler.
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass
from typing import Iterable, Optional

from ..api import k8s
from ..api.trainingjob import (HEALTH_ANNOTATION, QUARANTINE_ANNOTATION,
                               SUSPECT_ANNOTATION)
from ..api.topology import SliceTopology

log = logging.getLogger(__name__)

# Event kinds and their score weights (the shared evidence vocabulary —
# weights are part of the wire contract because the WRITER applies them
# at fold time). A pod crash or a stalled worker is hard evidence; a
# step-time skew observation (straggler: healthy chief, one slow
# worker) is soft and accumulates.
EVENT_POD_CRASH = "pod-crash"
EVENT_STALL = "stall"
EVENT_WORKER_STALL = "worker-stall"
EVENT_NOT_READY = "not-ready"
EVENT_STEP_SKEW = "step-skew"
# numeric-integrity anomaly (runtime/sentinel.py → the operator's
# rollback path): NaN/spike/replica-disagreement evidence naming this
# host. Weighted ABOVE a crash — silent data corruption wastes a full
# rollback per occurrence and crashes nothing on its own — so two trips
# (2 × 2.0 ≥ quarantine_threshold 3.0) quarantine the host.
EVENT_NUMERIC_ANOMALY = "numeric-anomaly"

EVENT_WEIGHTS = {
    EVENT_POD_CRASH: 1.0,
    EVENT_STALL: 1.0,
    EVENT_WORKER_STALL: 1.0,
    EVENT_NOT_READY: 1.0,
    EVENT_STEP_SKEW: 0.25,
    EVENT_NUMERIC_ANOMALY: 2.0,
}

# quarantine reason a human writes; never auto-released
MANUAL_REASON = "manual"

# Step-skew detection (the straggler signal: healthy chief, one slow
# worker). A worker whose heartbeat step trails the chief's by at least
# STEP_SKEW_MIN_STEPS on STEP_SKEW_STREAK consecutive reconciles is a
# straggler — the operator folds one step-skew event per full streak
# (controllers/tpujob.py), so a single slow window never scores but a
# persistently slow host accumulates toward quarantine. BOTH heartbeats
# must be FRESH (beat age under the job's stall timeout, or
# STEP_SKEW_FRESH_S when no watchdog is configured): a frozen heartbeat
# is a hung WORKER, not a slow host — without the freshness gate a
# wedged pod on a watchdog-less job would slowly quarantine a healthy
# host on step-skew evidence alone.
STEP_SKEW_MIN_STEPS = 4
STEP_SKEW_STREAK = 3
STEP_SKEW_FRESH_S = 300.0


@dataclass
class HealthConfig:
    """The scheduler's health policy surface (the ``health`` key of the
    tpu-scheduler ConfigMap; scheduler/queue.py SchedulerConfig carries
    one). ``enabled=False`` is the placement-blind baseline: no
    scoring, no quarantine writes, no suspect evacuation — the bench's
    quarantine-off arm."""

    enabled: bool = True
    # score half-life: a weight-1 event reads as 0.5 after this long
    half_life_s: float = 600.0
    # decayed score at/above which a host is quarantined
    quarantine_threshold: float = 3.0
    # score at/below which an EXPIRED quarantine releases (probation:
    # expiry alone is not enough — a still-failing host stays out)
    release_threshold: float = 1.0
    # quarantine duration per grant (extended while the score stays hot)
    quarantine_s: float = 900.0

    KEYS = ("enabled", "halfLifeSeconds", "quarantineThreshold",
            "releaseThreshold", "quarantineSeconds")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "HealthConfig":
        d = dict(d or {})
        unknown = set(d) - set(cls.KEYS)
        if unknown:
            # a typo'd knob must fail loudly at render/parse time, not
            # silently run with the default it meant to override
            raise ValueError(
                f"unknown health config keys {sorted(unknown)}; "
                f"valid: {list(cls.KEYS)}")
        return cls(
            enabled=bool(d.get("enabled", True)),
            half_life_s=float(d.get("halfLifeSeconds", 600.0)),
            quarantine_threshold=float(d.get("quarantineThreshold", 3.0)),
            release_threshold=float(d.get("releaseThreshold", 1.0)),
            quarantine_s=float(d.get("quarantineSeconds", 900.0)))

    def to_dict(self) -> dict:
        return {"enabled": self.enabled,
                "halfLifeSeconds": self.half_life_s,
                "quarantineThreshold": self.quarantine_threshold,
                "releaseThreshold": self.release_threshold,
                "quarantineSeconds": self.quarantine_s}


# ---------------------------------------------------------- health score


def health_of(node: dict) -> dict:
    """The raw health record off a node's annotation: ``{"score": s,
    "time": t, "events": n, "last": kind}``; zeros when absent or
    malformed (garbage degrades to healthy, never crashes a pass)."""
    raw = k8s.annotations_of(node).get(HEALTH_ANNOTATION)
    if not raw:
        return {"score": 0.0, "time": 0.0, "events": 0, "last": ""}
    try:
        d = json.loads(raw)
        return {"score": float(d.get("score", 0.0)),
                "time": float(d.get("time", 0.0)),
                "events": int(d.get("events", 0)),
                "last": str(d.get("last", ""))}
    except (AttributeError, TypeError, ValueError):
        return {"score": 0.0, "time": 0.0, "events": 0, "last": ""}


def decayed_score(node: dict, now: Optional[float] = None,
                  half_life_s: float = 600.0) -> float:
    """The host's CURRENT score: the stored score decayed from its
    stored timestamp to ``now``. A future-stamped record (writer clock
    skew) decays from now — clamped, never infinitely fresh."""
    now = time.time() if now is None else now
    rec = health_of(node)
    if rec["score"] <= 0.0:
        return 0.0
    age = max(0.0, now - rec["time"])
    return rec["score"] * 0.5 ** (age / max(half_life_s, 1e-9))


def fold_event(rec: dict, kind: str, now: float,
               half_life_s: float = 600.0,
               weight: Optional[float] = None) -> dict:
    """Pure fold: decay the stored score to ``now``, add the event's
    weight. Any writer can do this without coordination because the
    record carries its own timestamp. ``weight`` overrides the node
    EVENT_WEIGHTS lookup — other evidence vocabularies (the serving
    fleet's per-replica circuit breakers, serving/fleet.py) reuse this
    exact scoring shape with their own kinds and weights."""
    age = max(0.0, now - rec.get("time", 0.0))
    decayed = float(rec.get("score", 0.0)) * \
        0.5 ** (age / max(half_life_s, 1e-9))
    w = EVENT_WEIGHTS.get(kind, 1.0) if weight is None else float(weight)
    return {"score": round(decayed + w, 6),
            "time": now, "events": int(rec.get("events", 0)) + 1,
            "last": kind}


def record_host_event(client, node_name: str, kind: str,
                      job_key: str = "", now: Optional[float] = None,
                      half_life_s: float = 600.0) -> Optional[dict]:
    """Fold one failure event into a node's health annotation —
    conflict-safe (cluster/client.py update_with_conflict_retry): the
    fold recomputes off the FRESH record per attempt and the write
    carries the read's resourceVersion, so two writers folding the
    same instant (operator recording a crash while the scheduler folds
    a flap) both land — the blind-patch version of this RMW could lose
    one. Still best-effort by contract: evidence recording must never
    block a recovery path — any error logs and returns None."""
    from ..cluster.client import apply_annotations, update_with_conflict_retry
    now = time.time() if now is None else now
    out: dict = {}

    def _mutate(obj: dict) -> dict:
        rec = fold_event(health_of(obj), kind, now,
                         half_life_s=half_life_s)
        out.clear()
        out.update(rec)
        return apply_annotations(obj, {HEALTH_ANNOTATION:
                                       json.dumps(rec)})

    try:
        update_with_conflict_retry(client, "v1", "Node", "", node_name,
                                   _mutate)
        log.info("health: %s on %s (job %s) -> score %.2f",
                 kind, node_name, job_key or "?", out["score"])
        return dict(out)
    except Exception as e:  # noqa: BLE001 — evidence must not kill recovery
        log.warning("health: recording %s on %s failed: %s",
                    kind, node_name, e)
        return None


# ------------------------------------------------------------ quarantine


def quarantine_of(node: dict) -> Optional[dict]:
    """The node's quarantine record ``{"reason": r, "score": s,
    "since": t, "until": t|None, "cordoned": bool}``, or None when
    absent/malformed. ``cordoned`` marks that the SCHEDULER cordoned
    the node alongside the quarantine (so release knows to uncordon —
    it must never uncordon a human's cordon). THE one parse of the
    quarantine wire contract — inventory, scheduler, operator tooling,
    and dashboard all read through here."""
    raw = k8s.annotations_of(node).get(QUARANTINE_ANNOTATION)
    if not raw:
        return None
    try:
        d = json.loads(raw)
        until = d.get("until")
        return {"reason": str(d.get("reason", "")),
                "score": float(d.get("score", 0.0)),
                "since": float(d.get("since", 0.0)),
                "until": float(until) if until is not None else None,
                "cordoned": bool(d.get("cordoned", False))}
    except (AttributeError, TypeError, ValueError):
        # unparseable quarantine reads as quarantined-forever-manual:
        # fail SAFE (keep the host out) and let a human fix the JSON
        return {"reason": MANUAL_REASON, "score": 0.0, "since": 0.0,
                "until": None, "cordoned": False}


def quarantine_record(reason: str, score: float, now: float,
                      duration_s: Optional[float],
                      cordoned: bool = False) -> str:
    """Serialize a quarantine annotation value; ``duration_s=None``
    means no expiry (manual release only). ``cordoned=True`` records
    that the writer also cordoned the node (``spec.unschedulable``) —
    planner-level cell carving alone cannot stop the kube scheduler
    from placing a SUB-SLICE gang's pods back onto the host, because
    pods pin only by pool label; the cordon closes that hole."""
    return json.dumps({
        "reason": reason, "score": round(score, 6), "since": now,
        "until": (now + duration_s) if duration_s is not None else None,
        "cordoned": cordoned})


def is_quarantined(node: dict) -> bool:
    """Whether placement must keep off this host NOW. An expired
    quarantine still counts until the scheduler's release pass clears
    the annotation — release is a policy decision (the score must have
    decayed too), not a timer."""
    return quarantine_of(node) is not None


def release_eligible(node: dict, cfg: HealthConfig,
                     now: Optional[float] = None) -> bool:
    """Probational auto-release: expiry passed AND the decayed score is
    back under the release threshold. Manual quarantines (or records
    without an expiry) never auto-release."""
    now = time.time() if now is None else now
    q = quarantine_of(node)
    if q is None or q["reason"] == MANUAL_REASON or q["until"] is None:
        return False
    if now < q["until"]:
        return False
    return decayed_score(node, now, cfg.half_life_s) <= \
        cfg.release_threshold


# --------------------------------------------------------- suspect hosts


def suspect_of(manifest: dict) -> Optional[str]:
    """The node name the operator attributed this job's last gang
    teardown to, or None. Consumed by the scheduler's replan pass
    (exclude the suspect's cells) and cleared on the rebind."""
    raw = k8s.annotations_of(manifest).get(SUSPECT_ANNOTATION)
    return raw or None


# ------------------------------------------------- host <-> cell mapping


def host_cells(pool: str, topology: SliceTopology,
               host_index: int) -> Iterable[tuple[str, int, int]]:
    """The inventory cells one host contributes: hosts tile the pool's
    ICI grid row-major, ``chips_per_host`` cells each (host 0 owns cells
    0..cph-1, host 1 the next cph, ...) — the same order
    cluster/fake.py add_tpu_slice_nodes provisions nodes and
    api/topology.py render_contracts numbers processes."""
    rows, cols = (topology.ici_mesh + (1, 1))[:2]
    cph = topology.chips_per_host
    start = host_index * cph
    for k in range(start, min(start + cph, rows * cols)):
        yield (pool, k // cols, k % cols)


def host_sort_key(name: str) -> tuple:
    """Natural order for node names: the trailing integer sorts
    numerically ("pool-v5e-32-10" after "pool-v5e-32-9"), so host
    indices are stable however many hosts a pool has."""
    import re
    m = re.search(r"(\d+)$", name)
    return (name[:m.start()], int(m.group(1))) if m else (name, -1)


def host_name_index(name: str) -> Optional[int]:
    """The host index a node's NAME claims (its trailing integer — the
    shape cluster/fake.py add_tpu_slice_nodes and GKE's per-host node
    naming produce), or None for unnumbered names. Used by
    inventory.from_nodes so a DELETED middle node keeps every other
    host's cell attribution fixed: positional assignment would shift
    all subsequent hosts one block over, carving the wrong chips."""
    import re
    m = re.search(r"(\d+)$", name)
    return int(m.group(1)) if m else None
