"""Seeded contended-cluster simulation for the gang scheduler.

The bench vehicle (``bench.py --mode sched``): a fixed slice pool, a
seeded mix of job sizes/priorities/arrivals, and the REAL scheduler core
(scheduler/core.py plan() over scheduler/inventory.py) driven in
discrete time — so the measured deltas between FIFO, priority+backfill,
and priority+backfill+preemption are properties of the shipped policy
code, not of a parallel reimplementation.

Preemption is modeled with the checkpoint contract the control plane
actually provides: a reclaimed gang loses only the work since its last
checkpoint (``checkpoint_every`` ticks) and re-queues; the recomputed
ticks are reported so the utilization win is never silently subsidized
by thrown-away work.

jax-free and wall-clock-free: one tick is one abstract device-time unit.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional

from ..api.topology import parse_topology
from ..obs import goodput as gp
from . import health
from .inventory import PoolState, SliceInventory
from .queue import JobRequest, SchedulerConfig
from .core import plan

# the bench arms, in dominance order; "elastic" = preempt + elastic gang
# resizing (shrink-to-admit / shrink-to-survive / grow-to-fill / defrag)
# for the jobs that carry minChips/maxChips bounds
POLICIES = ("fifo", "backfill", "preempt", "elastic")


@dataclass
class DegradedHost:
    """A host-pinned recurring fault for the sim (the flaky-host /
    slow-host class ``bench.py --mode health`` measures): between
    ``start`` and ``end`` ticks, any gang whose placement covers the
    host's cells fails every ``fail_every`` ticks — it loses the work
    since its last checkpoint, exactly the real crash-loop cost. With
    node-health ON the first failure quarantines the host (its cells
    carve out of the inventory, the victim requeues and re-places
    elsewhere); with it OFF the binding is placement-blind and the gang
    crash-loops in place until the degradation ends."""

    pool: str                # sim pool name ("pool-0-v5e-32")
    host: int                # host index (row-major cell blocks)
    start: int
    end: int
    fail_every: int = 2
    # ticks past `end` before a quarantined host is released back (the
    # probation analog of the real decay-based auto-release)
    probation: int = 10


def policy_config(policy: str,
                  quotas: Optional[dict] = None) -> SchedulerConfig:
    """The A/B arms: fifo = submission order only; backfill = priority
    order + head-reservation backfill; preempt = backfill + reclaiming
    preemptible lower-priority gangs; elastic = preempt + resize plans
    for min/max-bounded gangs (config.elastic is OFF in every other arm
    so the same bounded workload measures the policy, not the jobs)."""
    if policy not in POLICIES:
        raise ValueError(f"unknown policy {policy!r}; one of {POLICIES}")
    cfg = SchedulerConfig.from_dict({"queues": quotas or {}})
    cfg.priority_order = policy != "fifo"
    cfg.backfill = policy != "fifo"
    cfg.preemption = policy in ("preempt", "elastic")
    cfg.elastic = policy == "elastic"
    return cfg


@dataclass
class SimJob:
    """One synthetic gang: shape, priority, and how long it runs."""

    name: str
    topology: str
    priority: int = 0
    preemptible: bool = False
    num_slices: int = 1
    queue: str = "default"
    namespace: str = "default"
    arrival: int = 0            # tick the job is submitted
    work: int = 10              # device ticks to completion (at NOMINAL
    #                             size — a shrunk gang progresses
    #                             proportionally slower, a grown one
    #                             faster: pure data parallelism)
    # elastic bounds (schedulingPolicy.minChips/maxChips); None = fixed
    min_chips: Optional[int] = None
    max_chips: Optional[int] = None
    # -- runtime state (the sim's, not the user's) --
    done: float = field(default=0.0, repr=False)
    high_water: float = field(default=0.0, repr=False)
    checkpointed: float = field(default=0.0, repr=False)
    first_bound: Optional[int] = field(default=None, repr=False)
    finished: Optional[int] = field(default=None, repr=False)
    preemptions: int = field(default=0, repr=False)
    recomputed: float = field(default=0.0, repr=False)
    resizes: int = field(default=0, repr=False)
    # startup debt: device ticks this gang still owes before its next
    # useful step (restart cost — pod start + backend init + compile or
    # cache load or AOT load, set at every bind/resize)
    startup_left: float = field(default=0.0, repr=False)
    startup_paid: float = field(default=0.0, repr=False)
    # goodput-ledger bookkeeping (obs/goodput.py vocabulary): which
    # category the outstanding debt belongs to, queue-wait ticks
    # accumulated across (re)queues, and chip-weighted accumulators the
    # per-run goodput table is built from
    debt_kind: str = field(default="startup", repr=False)
    queued_at: Optional[int] = field(default=None, repr=False)
    wait_ticks: int = field(default=0, repr=False)
    startup_chip: float = field(default=0.0, repr=False)
    resize_chip: float = field(default=0.0, repr=False)
    recompute_chip: float = field(default=0.0, repr=False)
    goodput_chip: float = field(default=0.0, repr=False)

    @property
    def nominal_chips(self) -> int:
        return parse_topology(self.topology).num_chips * self.num_slices

    def request(self, seq: int, fifo: bool) -> JobRequest:
        return JobRequest(
            namespace=self.namespace, name=self.name, queue=self.queue,
            priority=0 if fifo else self.priority,
            preemptible=self.preemptible,
            topology=parse_topology(self.topology),
            num_slices=self.num_slices, seq=seq,
            min_chips=self.min_chips, max_chips=self.max_chips)


def make_workload(seed: int, n_jobs: int = 24,
                  sizes: tuple = ("v5e-4", "v5e-8", "v5e-16", "v5e-32"),
                  max_priority: int = 2, preemptible_frac: float = 0.6,
                  mean_interarrival: int = 2,
                  work_range: tuple = (6, 30),
                  elastic_frac: float = 0.0) -> list[SimJob]:
    """Seeded mixed workload: small jobs outnumber big ones ~2:1 per
    size step (the long-tail shape a shared research cluster sees), up
    to FULL-POOL gangs — the jobs whose head-of-line blocking is what a
    FIFO queue dies on. Priorities uniform; small jobs skew preemptible
    (big jobs are the expensive-to-lose ones); arrivals a seeded
    renewal process. ``elastic_frac`` of the jobs carry minChips/
    maxChips bounds (quarter-size floor, double-size ceiling) — inert
    under every policy except "elastic" (policy_config flips
    config.elastic, not the workload, so the A/B is paired)."""
    rng = random.Random(seed)
    # elastic membership draws from its OWN stream: the legacy arms'
    # workloads (priorities, arrivals, work) must stay bit-identical to
    # the pre-elastic bench so their numbers remain comparable
    elastic_rng = random.Random(seed ^ 0xE1A5)
    jobs, t = [], 0
    weights = [2 ** (len(sizes) - 1 - i) for i in range(len(sizes))]
    for i in range(n_jobs):
        topo = rng.choices(sizes, weights=weights)[0]
        big = topo == sizes[-1]
        chips = parse_topology(topo).num_chips
        elastic = elastic_rng.random() < elastic_frac
        jobs.append(SimJob(
            name=f"job-{i:03d}", topology=topo,
            priority=rng.randint(0, max_priority),
            preemptible=not big and rng.random() < preemptible_frac,
            min_chips=max(1, chips // 4) if elastic else None,
            max_chips=min(2 * chips, 256) if elastic else None,
            arrival=t, work=rng.randint(*work_range)))
        t += rng.randint(0, 2 * mean_interarrival)
    return jobs


def _percentile(values: list, frac: float) -> float:
    if not values:
        return 0.0
    xs = sorted(values)
    return float(xs[min(len(xs) - 1, int(len(xs) * frac))])


def simulate(jobs: list[SimJob], pools: tuple = ("v5e-32",),
             policy: str = "preempt", checkpoint_every: int = 4,
             quotas: Optional[dict] = None,
             degraded: tuple = (),
             node_health: bool = True,
             restart_ticks: float = 0.0,
             max_ticks: int = 100_000) -> dict:
    """Run one seeded workload to completion under one policy. Returns
    the metrics row the bench table is built from. ``degraded`` is a
    sequence of DegradedHost events; ``node_health`` flips the
    quarantine feedback loop (the bench's A/B: with it off, a gang on a
    degraded host crash-loops in place — the placement-blind
    baseline). ``restart_ticks`` is the per-(re)start cost in device
    ticks — pod start + backend init + first-step compile (cold), cache
    load (warm), or AOT executable load — charged at EVERY bind and
    resize before the gang makes useful progress. The shipped default 0
    reproduces the historical free-restart model; bench.py --mode
    warmstart re-runs the A/Bs with MEASURED costs
    (compare_restart_costs) so the preemption/elastic win rates are no
    longer subsidized by free restarts."""
    cfg = policy_config(policy, quotas=quotas)
    fifo = policy == "fifo"
    jobs = sorted(jobs, key=lambda j: (j.arrival, j.name))
    pool_states = [
        PoolState(f"pool-{i}-{name}", parse_topology(name))
        for i, name in enumerate(pools)]
    pool_by_name = {p.name: p for p in pool_states}
    total_chips = sum(p.total_chips for p in pool_states)
    by_key = {f"{j.namespace}/{j.name}": j for j in jobs}

    def dh_cells(pool_name: str, host: int) -> set:
        pool = pool_by_name.get(pool_name)
        if pool is None:
            return set()
        return set(health.host_cells(pool_name, pool.topology, host))

    pending = list(jobs)            # not yet arrived
    queued: list[tuple[int, SimJob]] = []    # (seq, job)
    bound: dict[str, tuple] = {}    # key -> (JobRequest, Placement)
    seq_of: dict[str, int] = {}     # key -> submission seq (stable)
    # (pool, host) -> release tick for hosts the health loop pulled
    quarantined: dict[tuple, int] = {}
    seq_counter = 0
    busy_chip_ticks = 0
    host_faults = 0
    t = 0
    while t < max_ticks:
        while pending and pending[0].arrival <= t:
            job = pending.pop(0)
            seq_of[f"{job.namespace}/{job.name}"] = seq_counter
            job.queued_at = t
            queued.append((seq_counter, job))
            seq_counter += 1

        # host-pinned faults land before the pass (the operator's
        # teardown precedes the scheduler's replan in the real loop)
        for dh in degraded:
            if not (dh.start <= t < dh.end) or \
                    (t - dh.start) % dh.fail_every:
                continue
            cells = dh_cells(dh.pool, dh.host)
            for key in list(bound):
                _req, placement = bound[key]
                if all(cells.isdisjoint(r.cells())
                       for r in placement.slices):
                    continue
                job = by_key[key]
                lost = job.done - job.checkpointed
                job.recomputed += lost
                job.done = job.checkpointed
                host_faults += 1
                if node_health:
                    # quarantine + failure-domain-aware rebind: the
                    # host carves out, the victim requeues (ORIGINAL
                    # seq) and re-places clear of it next pass
                    quarantined[(dh.pool, dh.host)] = \
                        dh.end + dh.probation
                    del bound[key]
                    job.queued_at = t
                    queued.append((seq_of[key], job))
                # placement-blind: the binding survives and the gang
                # crash-loops in place until the degradation ends

        # one scheduler pass over a fresh inventory (exactly what the
        # k8s loop does each reconcile)
        inventory = SliceInventory(
            [PoolState(p.name, p.topology) for p in pool_states])
        for key, (req, placement) in bound.items():
            inventory.bind(key, placement)
        inventory.down_cells = set()
        for (pool, host), until in list(quarantined.items()):
            if t >= until:
                del quarantined[(pool, host)]   # probation release
                continue
            inventory.down_cells |= dh_cells(pool, host)
        inventory.carve_down()
        requests = [job.request(seq, fifo) for seq, job in queued]
        decisions = plan(requests, list(bound.values()), inventory, cfg)

        for req, new_placement, _reason in decisions.resizes:
            job = by_key[req.key]
            # resize-at-boundary contract: the graceful teardown forces
            # a checkpoint before exit 75, so a shrink/grow/migration
            # reshapes the gang WITHOUT recompute — the structural
            # difference vs preemption the elastic arm is measuring.
            # It still restarts the gang, so the startup debt is paid
            # again (free only in the historical restart_ticks=0 model).
            job.checkpointed = job.done
            job.resizes += 1
            job.startup_left = restart_ticks
            job.debt_kind = gp.BADPUT_RESIZE
            bound[req.key] = (bound[req.key][0], new_placement)
        for victim in decisions.preempts:
            job = by_key[victim.key]
            # checkpoint contract: lose only work since the last save
            lost = job.done - job.checkpointed
            job.recomputed += lost
            job.done = job.checkpointed
            job.preemptions += 1
            del bound[victim.key]
            # ORIGINAL seq: the real scheduler's seq is uid/timestamp-
            # derived and survives preemption, so a requeued victim
            # keeps its FIFO standing — the sim must measure the same
            # requeue policy the k8s loop ships
            job.queued_at = t
            queued.append((seq_of[victim.key], job))
        for req, placement in decisions.binds:
            job = by_key[req.key]
            if job.first_bound is None:
                job.first_bound = t
            if placement.chips != req.chips:
                job.resizes += 1   # shrink-to-survive: a degraded bind
            job.startup_left = restart_ticks
            job.debt_kind = gp.BADPUT_STARTUP
            if job.queued_at is not None:
                job.wait_ticks += max(0, t - job.queued_at)
                job.queued_at = None
            bound[req.key] = (req, placement)
            queued = [(s, j) for s, j in queued if j is not job]

        # device time advances: every bound gang makes one tick of
        # progress — scaled by its CURRENT size over nominal (pure data
        # parallelism at fixed global batch: throughput ∝ chips, so a
        # half-size degraded gang banks half a work unit per tick) —
        # checkpointing on the checkpoint_every cadence of ticks RUN.
        # Utilization counts USEFUL work only: a tick re-running steps a
        # preemption threw away is not utilization — the win must not be
        # subsidized by its own waste (recomputed_ticks reports it).
        finished_keys = []
        for key, (req, placement) in bound.items():
            job = by_key[key]
            # startup debt first: chips held, no progress, no
            # utilization credit — the restart cost the warm-start
            # stack exists to shrink
            frac = 1.0
            if job.startup_left > 0:
                paid = min(1.0, job.startup_left)
                job.startup_left -= paid
                job.startup_paid += paid
                # chip-weighted, by debt category: restart debt after a
                # resize is resize downtime, after a (re)bind startup —
                # the goodput-table decomposition (obs/goodput.py)
                if job.debt_kind == gp.BADPUT_RESIZE:
                    job.resize_chip += paid * placement.chips
                else:
                    job.startup_chip += paid * placement.chips
                frac = 1.0 - paid
                if frac <= 0:
                    continue
            if job.done >= job.high_water:
                busy_chip_ticks += placement.chips * frac
                job.goodput_chip += placement.chips * frac
            else:
                # replaying steps a preemption/fault threw away
                job.recompute_chip += placement.chips * frac
            prev = job.done
            job.done += frac * placement.chips / req.chips
            job.high_water = max(job.high_water, job.done)
            # save on crossing each checkpoint_every-step PROGRESS
            # boundary (the worker's step % N == 0 contract; for
            # speed-1 fixed gangs this is exactly the integral cadence)
            if int(job.done) // checkpoint_every > \
                    int(prev) // checkpoint_every:
                job.checkpointed = float(
                    int(job.done) // checkpoint_every * checkpoint_every)
            if job.done >= job.work:
                job.finished = t + 1
                finished_keys.append(key)
        for key in finished_keys:
            del bound[key]

        t += 1
        if not pending and not queued and not bound:
            break
        if not pending and not bound and not decisions.binds \
                and not finished_keys:
            # stalled forever: nothing is running (so no chips will ever
            # free), nothing finished THIS tick (this pass's plan already
            # saw the empty cluster), nothing else arrives, and the pass
            # placed nothing — the plan is deterministic, so every
            # future tick repeats it (e.g. a v5e-32 job against
            # v5e-16-only pools). Stop and report the survivors as
            # unfinished instead of grinding max_ticks scheduler passes.
            break

    unfinished = [j.name for j in jobs if j.finished is None]
    makespan = max((j.finished for j in jobs if j.finished is not None),
                   default=0)
    waits = [j.first_bound - j.arrival for j in jobs
             if j.first_bound is not None]
    # close out waits still open at termination (never-bound survivors)
    for job in jobs:
        if job.queued_at is not None:
            job.wait_ticks += max(0, t - job.queued_at)
            job.queued_at = None
    # the goodput table, in the SAME category vocabulary the real
    # cluster's ledger reports (obs/goodput.py) so a sim arm's
    # decomposition is comparable to a deployment's. Chip-weighted:
    # queue wait at the gang's nominal demand, debts at the width
    # actually held. Compile/cache-load is folded into the sim's single
    # restart cost (startup/resize); checkpoint and stall are free in
    # the sim's model — reported as zeros, not omitted, so tables line
    # up column-for-column.
    goodput_chip = sum(j.goodput_chip for j in jobs)
    badput_chip = {c: 0.0 for c in gp.BADPUT_CATEGORIES}
    badput_chip[gp.BADPUT_QUEUE_WAIT] = float(
        sum(j.wait_ticks * j.nominal_chips for j in jobs))
    badput_chip[gp.BADPUT_STARTUP] = sum(j.startup_chip for j in jobs)
    badput_chip[gp.BADPUT_RESIZE] = sum(j.resize_chip for j in jobs)
    badput_chip[gp.BADPUT_RECOMPUTE] = sum(
        j.recompute_chip for j in jobs)
    accounted = goodput_chip + sum(badput_chip.values())
    goodput_table = {
        "unit": "chip_ticks",
        gp.GOODPUT: round(goodput_chip, 2),
        "badput": {c: round(v, 2) for c, v in badput_chip.items()},
        "goodput_fraction": round(goodput_chip / accounted, 4)
        if accounted else 0.0,
    }
    return {
        "policy": policy,
        "jobs": len(jobs),
        "total_chips": total_chips,
        "makespan_ticks": makespan,
        "chip_utilization": round(
            busy_chip_ticks / (total_chips * makespan), 4)
        if makespan else 0.0,
        "queue_wait_p50": _percentile(waits, 0.50),
        "queue_wait_p90": _percentile(waits, 0.90),
        "queue_wait_mean": round(sum(waits) / len(waits), 2)
        if waits else 0.0,
        "preemptions": sum(j.preemptions for j in jobs),
        "recomputed_ticks": round(sum(j.recomputed for j in jobs), 2),
        "startup_ticks": round(sum(j.startup_paid for j in jobs), 2),
        "resizes": sum(j.resizes for j in jobs),
        "host_faults": host_faults,
        "useful_work_fraction": round(
            sum(j.done for j in jobs)
            / max(1, sum(j.done + j.recomputed for j in jobs)), 4),
        "goodput": goodput_table,
        "unfinished": unfinished,
    }


def compare_policies(seeds: list, n_jobs: int = 24,
                     pools: tuple = ("v5e-32", "v5e-16"),
                     checkpoint_every: int = 4,
                     quotas: Optional[dict] = None,
                     elastic_frac: float = 1.0) -> dict:
    """The bench table: each policy over the same seeded workloads,
    metrics averaged across seeds (same jobs per seed for every arm —
    paired comparison, seed noise cancels inside the ratio).
    ``elastic_frac`` of each workload's jobs carry minChips/maxChips;
    only the "elastic" arm's config acts on them, so the bounded
    workload is identical across arms."""
    rows: dict = {p: [] for p in POLICIES}
    for seed in seeds:
        jobs = make_workload(seed, n_jobs=n_jobs,
                             elastic_frac=elastic_frac)
        for policy in POLICIES:
            # fresh copies: simulate mutates job state
            fresh = [SimJob(**{k: getattr(j, k) for k in (
                "name", "topology", "priority", "preemptible",
                "num_slices", "queue", "namespace", "arrival", "work",
                "min_chips", "max_chips")})
                for j in jobs]
            rows[policy].append(simulate(
                fresh, pools=pools, policy=policy,
                checkpoint_every=checkpoint_every, quotas=quotas))
    out = {}
    for policy, runs in rows.items():
        agg = {}
        for metric in ("makespan_ticks", "chip_utilization",
                       "queue_wait_p50", "queue_wait_p90",
                       "queue_wait_mean", "preemptions",
                       "recomputed_ticks", "resizes"):
            agg[metric] = round(
                sum(r[metric] for r in runs) / len(runs), 4)
        # the per-arm goodput decomposition (obs/goodput.py vocabulary),
        # seed-averaged — comparable to the real cluster's ledger table
        agg["goodput_fraction"] = round(
            sum(r["goodput"]["goodput_fraction"] for r in runs)
            / len(runs), 4)
        agg["badput_chip_ticks"] = {
            c: round(sum(r["goodput"]["badput"][c] for r in runs)
                     / len(runs), 2)
            for c in gp.BADPUT_CATEGORIES}
        agg["unfinished"] = sum(len(r["unfinished"]) for r in runs)
        out[policy] = agg
    return out


def compare_restart_costs(seeds: list, costs: dict,
                          n_jobs: int = 24,
                          pools: tuple = ("v5e-32", "v5e-16"),
                          checkpoint_every: int = 4,
                          policies: tuple = ("preempt", "elastic"),
                          elastic_frac: float = 1.0) -> dict:
    """The honest-restart re-run of the scheduler A/B: the same seeded
    workloads under each policy, once per restart-cost arm. ``costs``
    maps arm name → per-restart device ticks, e.g. ``{"free": 0,
    "cold": 2.3, "warm": 0.5, "aot": 0.2}`` — bench.py --mode warmstart
    derives cold/warm/aot from MEASURED startup→first-step seconds.
    "free" is the historical model every prior sched/elastic number was
    published under; the spread between it and "cold" is how optimistic
    those numbers were, and "warm"/"aot" are what the warm-start stack
    buys back. Paired across arms (same jobs per seed)."""
    out: dict = {}
    for policy in policies:
        arms: dict = {a: [] for a in costs}
        for seed in seeds:
            jobs = make_workload(seed, n_jobs=n_jobs,
                                 elastic_frac=elastic_frac)
            for arm, ticks in costs.items():
                fresh = [SimJob(**{k: getattr(j, k) for k in (
                    "name", "topology", "priority", "preemptible",
                    "num_slices", "queue", "namespace", "arrival",
                    "work", "min_chips", "max_chips")})
                    for j in jobs]
                arms[arm].append(simulate(
                    fresh, pools=pools, policy=policy,
                    checkpoint_every=checkpoint_every,
                    restart_ticks=float(ticks)))
        table = {}
        for arm, runs in arms.items():
            agg = {"restart_ticks": round(float(costs[arm]), 3)}
            for metric in ("makespan_ticks", "chip_utilization",
                           "queue_wait_p50", "recomputed_ticks",
                           "startup_ticks", "preemptions", "resizes"):
                agg[metric] = round(
                    sum(r[metric] for r in runs) / len(runs), 4)
            agg["unfinished"] = sum(len(r["unfinished"]) for r in runs)
            table[arm] = agg
        out[policy] = table
    return out


def degraded_workload(seed: int, pools: tuple) -> list[DegradedHost]:
    """Seeded degraded-host schedule for one sim run: one flaky host on
    the first (largest) pool, failing every other tick through the
    thick of the contention window."""
    rng = random.Random(seed ^ 0x5EED)
    topo = parse_topology(pools[0])
    host = rng.randrange(topo.num_hosts)
    start = rng.randint(4, 10)
    return [DegradedHost(pool=f"pool-0-{pools[0]}", host=host,
                         start=start, end=start + rng.randint(25, 40),
                         fail_every=2)]


def compare_health(seeds: list, n_jobs: int = 24,
                   pools: tuple = ("v5e-32", "v5e-16"),
                   checkpoint_every: int = 4) -> dict:
    """The ``bench.py --mode health`` sim table: the same seeded
    workloads + the same seeded degraded-host schedule, quarantine ON
    vs OFF (paired comparison — the only difference is whether failure
    evidence feeds placement). Quarantine must strictly reduce
    recomputed ticks: crash-looping on a known-bad host is pure
    waste."""
    arms = {"quarantine_on": True, "quarantine_off": False}
    rows: dict = {a: [] for a in arms}
    for seed in seeds:
        jobs = make_workload(seed, n_jobs=n_jobs)
        degraded = degraded_workload(seed, pools)
        for arm, enabled in arms.items():
            fresh = [SimJob(**{k: getattr(j, k) for k in (
                "name", "topology", "priority", "preemptible",
                "num_slices", "queue", "namespace", "arrival", "work")})
                for j in jobs]
            rows[arm].append(simulate(
                fresh, pools=pools, policy="preempt",
                checkpoint_every=checkpoint_every,
                degraded=tuple(degraded), node_health=enabled))
    out = {}
    for arm, runs in rows.items():
        agg = {}
        for metric in ("makespan_ticks", "chip_utilization",
                       "recomputed_ticks", "host_faults",
                       "useful_work_fraction", "queue_wait_p50"):
            agg[metric] = round(
                sum(r[metric] for r in runs) / len(runs), 4)
        agg["unfinished"] = sum(len(r["unfinished"]) for r in runs)
        out[arm] = agg
    return out
