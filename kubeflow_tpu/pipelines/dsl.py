"""Pipeline authoring DSL: Python DAG → Workflow manifest.

The kfp.dsl + compiler role for this platform (the reference era shipped
the Kubeflow Pipelines SDK out-of-repo; in-repo it only had the manifests
— kubeflow/pipeline/*.libsonnet — and hand-written Argo Workflows,
testing/workflows/components/workflows.libsonnet:33-60). Here authoring is
first-class: steps are containers or launched manifests (the kubebench
resource-template idiom — e.g. "create this TPUJob, wait for Succeeded"),
compiled to the Workflow shape `workflows/engine.py` reconciles, so the
whole loop — author → compile → submit → reconcile → run history — runs
in-platform.

    p = Pipeline("train-then-report", namespace="kubeflow",
                 parameters={"steps": "100"})
    prep  = p.container("prep", image="busybox",
                        command=["sh", "-c", "echo prep"])
    train = p.launch("train", manifest=tpu_job_manifest,
                     success_condition="condition: Succeeded=True",
                     after=[prep])
    p.container("report", image="busybox",
                command=["report", "--steps=$(workflow.parameters.steps)"],
                after=[train])
    wf = p.compile()          # Workflow manifest (argoproj.io/v1alpha1)
    p.submit(client)          # create it on the cluster
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional, Sequence, Union

from ..api import k8s
from ..workflows.engine import WORKFLOW_API_VERSION, WORKFLOW_KIND

__all__ = ["Pipeline", "Step"]


@dataclass(frozen=True)
class Step:
    """Handle returned by Pipeline.container()/launch(); pass via
    ``after=`` to order steps."""

    name: str


StepRef = Union[Step, str]


def _names(after: Optional[Sequence[StepRef]]) -> list[str]:
    return [s.name if isinstance(s, Step) else str(s) for s in (after or [])]


@dataclass
class _Task:
    name: str
    template: dict
    dependencies: list[str] = field(default_factory=list)


class Pipeline:
    """A DAG of steps compiling to one Workflow manifest."""

    def __init__(self, name: str, namespace: str = "kubeflow",
                 parameters: Optional[dict] = None,
                 volumes: Optional[list[dict]] = None,
                 labels: Optional[dict] = None):
        k8s.validate_name(name)
        self.name = name
        self.namespace = namespace
        self.parameters = dict(parameters or {})
        self.volumes = list(volumes or [])
        self.labels = dict(labels or {})
        self._tasks: list[_Task] = []

    # -- step authoring ------------------------------------------------------

    def container(self, name: str, *, image: str,
                  command: Optional[list[str]] = None,
                  args: Optional[list[str]] = None,
                  env: Optional[dict] = None,
                  volume_mounts: Optional[list[dict]] = None,
                  active_deadline_s: Optional[int] = None,
                  after: Optional[Sequence[StepRef]] = None) -> Step:
        """A pod step. ``$(workflow.parameters.X)`` / ``$(workflow.name)``
        placeholders in command/args/env substitute at launch."""
        container: dict = {"image": image}
        if command:
            container["command"] = list(command)
        if args:
            container["args"] = list(args)
        if env:
            container["env"] = [{"name": k, "value": str(v)}
                                for k, v in env.items()]
        if volume_mounts:
            container["volumeMounts"] = list(volume_mounts)
        tmpl: dict = {"container": container}
        if active_deadline_s:
            tmpl["activeDeadlineSeconds"] = int(active_deadline_s)
        return self._add(name, tmpl, after)

    def launch(self, name: str, *, manifest: dict,
               success_condition: str = "condition: Succeeded=True",
               failure_condition: str = "condition: Failed=True",
               active_deadline_s: Optional[int] = None,
               after: Optional[Sequence[StepRef]] = None) -> Step:
        """A resource step: create ``manifest`` (a TPUJob, StudyJob, any
        CR) and wait for the success/failure condition — how a pipeline
        orchestrates training jobs (the kubebench launch idiom,
        kubebench-job.libsonnet:53)."""
        if not manifest.get("apiVersion") or not manifest.get("kind") \
                or not k8s.name_of(manifest):
            raise ValueError(f"step {name!r}: manifest needs apiVersion, "
                             "kind and metadata.name (an incomplete "
                             "manifest would hang the workflow — no "
                             "reconciler ever matches it)")
        tmpl: dict = {"resource": {
            "action": "create",
            "manifest": copy.deepcopy(manifest),
            "successCondition": success_condition,
            "failureCondition": failure_condition,
        }}
        if active_deadline_s:
            tmpl["activeDeadlineSeconds"] = int(active_deadline_s)
        return self._add(name, tmpl, after)

    def _add(self, name: str, template: dict,
             after: Optional[Sequence[StepRef]]) -> Step:
        k8s.validate_name(name)
        # the engine names pods '{workflow}-{step}': the COMBINED name must
        # be a valid DNS label too, or pod creation fails only at runtime
        k8s.validate_name(f"{self.name}-{name}")
        if name == "main":
            raise ValueError("step name 'main' is reserved for the "
                             "entrypoint template")
        if any(t.name == name for t in self._tasks):
            raise ValueError(f"duplicate step name {name!r}")
        deps = _names(after)
        known = {t.name for t in self._tasks}
        unknown = [d for d in deps if d not in known]
        if unknown:
            raise ValueError(f"step {name!r} depends on unknown {unknown} "
                             "(declare steps before referencing them)")
        template = dict(template, name=name)
        self._tasks.append(_Task(name, template, deps))
        return Step(name)

    # -- compile / submit ----------------------------------------------------

    def compile(self) -> dict:
        """The Workflow manifest (pure function of the declared steps —
        declaration order guarantees the DAG is acyclic by construction)."""
        if not self._tasks:
            raise ValueError(f"pipeline {self.name!r} has no steps")
        entry = {"name": "main", "dag": {"tasks": [
            {"name": t.name, "template": t.name,
             **({"dependencies": list(t.dependencies)}
                if t.dependencies else {})}
            for t in self._tasks]}}
        wf = k8s.make(WORKFLOW_API_VERSION, WORKFLOW_KIND, self.name,
                      self.namespace, labels=self.labels or None)
        wf["spec"] = {
            "entrypoint": "main",
            # deepcopy: compiled manifests must not alias internal state
            # (or each other) — mutating one output must never change what
            # a later compile()/submit() produces
            "templates": [entry] + [copy.deepcopy(t.template)
                                    for t in self._tasks],
        }
        if self.parameters:
            wf["spec"]["arguments"] = {"parameters": [
                {"name": k, "value": str(v)}
                for k, v in self.parameters.items()]}
        if self.volumes:
            wf["spec"]["volumes"] = list(self.volumes)
        return wf

    def schedule(self, cron: Optional[str] = None, *,
                 interval_s: Optional[int] = None, enabled: bool = True,
                 max_concurrency: int = 1, max_history: int = 10) -> dict:
        """A ScheduledWorkflow manifest firing this pipeline on a cron
        (``"0 * * * *"``) or periodic interval — the recurring-run (kfp
        "job") surface. Create it on the cluster to activate."""
        if (not cron) == (interval_s is None):
            raise ValueError("exactly one of cron / interval_s required")
        if interval_s is not None and interval_s < 1:
            # 0 silently never fires; negatives fire on every reconcile
            raise ValueError(f"interval_s must be >= 1, got {interval_s}")
        from .scheduled import (SCHEDULED_WF_API_VERSION, SCHEDULED_WF_KIND,
                                parse_cron)
        if cron:
            parse_cron(cron)  # author-time validation, not first-fire
        # every firing instantiates a fresh Workflow named
        # '{pipeline}-{index}', so two classes of name break only at run N:
        for t in self._tasks:
            # 1. step pod names gain the instance index — re-check the
            #    DNS-label budget with index headroom
            k8s.validate_name(f"{self.name}-4294967295-{t.name}")
            # 2. a launch() manifest with a FIXED name collides on the
            #    second firing (the engine does a bare create) — require a
            #    run-unique name via the $(workflow.name) placeholder
            res = t.template.get("resource")
            if res and "$(workflow.name)" not in \
                    k8s.name_of(res["manifest"]):
                raise ValueError(
                    f"step {t.name!r}: a scheduled pipeline fires many "
                    "runs, but the launched manifest's metadata.name "
                    f"({k8s.name_of(res['manifest'])!r}) is fixed — the "
                    "second firing would fail with AlreadyExists. Embed "
                    "$(workflow.name) in the name to make it run-unique")
        wf = self.compile()
        swf = k8s.make(SCHEDULED_WF_API_VERSION, SCHEDULED_WF_KIND,
                       self.name, self.namespace,
                       labels=self.labels or None)
        swf["spec"] = {
            "enabled": enabled,
            "maxConcurrency": int(max_concurrency),
            "maxHistory": int(max_history),
            "trigger": ({"cronSchedule": {"cron": cron}} if cron else
                        {"periodicSchedule":
                         {"intervalSecond": int(interval_s)}}),
            "workflow": {"spec": wf["spec"]},
        }
        return swf

    def submit(self, client, **overrides) -> dict:
        """Create the Workflow on the cluster; ``overrides`` replace
        parameter values for this run (the kfp run-with-params surface)."""
        wf = self.compile()
        if overrides:
            unknown = set(overrides) - set(self.parameters)
            if unknown:
                raise ValueError(f"unknown parameters {sorted(unknown)}; "
                                 f"declared: {sorted(self.parameters)}")
            for p in wf["spec"]["arguments"]["parameters"]:
                if p["name"] in overrides:
                    p["value"] = str(overrides[p["name"]])
        return client.create(wf)
