"""Run persistence: the pipeline-persistenceagent + DB analog.

The reference persists run history through a persistence agent watching
Argo Workflows into MySQL behind the pipeline apiserver
(pipeline-persistenceagent.libsonnet, pipeline-apiserver.libsonnet +
mysql.libsonnet). Here: a sqlite-backed RunStore (stdlib, file or
in-memory) and a PersistenceAgent reconciler that records every
Workflow's lifecycle — so run history survives Workflow deletion and is
queryable over the pipeline API long after the cluster objects are gone.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from typing import Optional

from ..api import k8s
from ..cluster.client import KubeClient, NotFoundError
from ..controllers.runtime import Key, Reconciler, Result
from ..workflows.engine import (TERMINAL, WORKFLOW_API_VERSION,
                                WORKFLOW_KIND)
from .scheduled import SCHEDULE_LABEL

_SCHEMA = """
CREATE TABLE IF NOT EXISTS runs (
    run_id      TEXT PRIMARY KEY,     -- namespace/name
    name        TEXT NOT NULL,
    namespace   TEXT NOT NULL,
    schedule    TEXT,                 -- owning ScheduledWorkflow, if any
    phase       TEXT NOT NULL,
    message     TEXT,
    created_at  REAL NOT NULL,
    finished_at REAL,
    nodes       TEXT                  -- JSON status.nodes snapshot
);
CREATE TABLE IF NOT EXISTS pipelines (
    pipeline_id TEXT PRIMARY KEY,     -- name
    description TEXT,
    created_at  REAL NOT NULL,
    workflow    TEXT NOT NULL         -- JSON Workflow spec template
);
"""


class RunStore:
    """sqlite-backed store for run history + uploaded pipeline templates."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        # one connection guarded by a lock: writers are reconcilers and the
        # API server; sqlite serializes anyway and this keeps :memory: usable
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        self._lock = threading.Lock()
        with self._lock:
            self._conn.executescript(_SCHEMA)
            self._conn.commit()

    # -- runs ---------------------------------------------------------------

    def upsert_run(self, wf: dict, clock=time.time) -> None:
        name = k8s.name_of(wf)
        ns = k8s.namespace_of(wf, "default")
        run_id = f"{ns}/{name}"
        status = wf.get("status", {}) or {}
        phase = status.get("phase", "Pending")
        finished = clock() if phase in TERMINAL else None
        with self._lock:
            existing = self._conn.execute(
                "SELECT created_at, finished_at FROM runs WHERE run_id=?",
                (run_id,)).fetchone()
            created = existing["created_at"] if existing else clock()
            if existing and existing["finished_at"] is not None:
                finished = existing["finished_at"]  # terminal time is sticky
            self._conn.execute(
                "INSERT INTO runs (run_id, name, namespace, schedule, phase,"
                " message, created_at, finished_at, nodes)"
                " VALUES (?,?,?,?,?,?,?,?,?)"
                " ON CONFLICT(run_id) DO UPDATE SET phase=excluded.phase,"
                " message=excluded.message, finished_at=excluded.finished_at,"
                " nodes=excluded.nodes",
                (run_id, name, ns,
                 k8s.labels_of(wf).get(SCHEDULE_LABEL),
                 phase, status.get("message", ""),
                 created, finished,
                 json.dumps(status.get("nodes", {}))))
            self._conn.commit()

    def get_run(self, run_id: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM runs WHERE run_id=?", (run_id,)).fetchone()
        return self._run_dict(row) if row else None

    def list_runs(self, namespace: Optional[str] = None,
                  schedule: Optional[str] = None,
                  phase: Optional[str] = None,
                  limit: int = 100) -> list[dict]:
        q = "SELECT * FROM runs WHERE 1=1"
        args: list = []
        for col, val in (("namespace", namespace), ("schedule", schedule),
                         ("phase", phase)):
            if val:
                q += f" AND {col}=?"
                args.append(val)
        q += " ORDER BY created_at DESC LIMIT ?"
        args.append(limit)
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [self._run_dict(r) for r in rows]

    @staticmethod
    def _run_dict(row: sqlite3.Row) -> dict:
        d = dict(row)
        d["nodes"] = json.loads(d.get("nodes") or "{}")
        return d

    # -- pipelines (uploaded templates) -------------------------------------

    def put_pipeline(self, name: str, workflow: dict,
                     description: str = "", clock=time.time) -> dict:
        with self._lock:
            self._conn.execute(
                "INSERT INTO pipelines (pipeline_id, description,"
                " created_at, workflow) VALUES (?,?,?,?)"
                " ON CONFLICT(pipeline_id) DO UPDATE SET"
                " description=excluded.description,"
                " workflow=excluded.workflow",
                (name, description, clock(), json.dumps(workflow)))
            self._conn.commit()
        return {"id": name, "description": description}

    def get_pipeline(self, name: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM pipelines WHERE pipeline_id=?",
                (name,)).fetchone()
        if row is None:
            return None
        d = dict(row)
        d["workflow"] = json.loads(d["workflow"])
        return d

    def list_pipelines(self) -> list[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT pipeline_id, description, created_at FROM pipelines"
                " ORDER BY pipeline_id").fetchall()
        return [dict(r) for r in rows]

    def delete_pipeline(self, name: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM pipelines WHERE pipeline_id=?", (name,))
            self._conn.commit()
            return cur.rowcount > 0

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class PersistenceAgent(Reconciler):
    """Watches Workflows, mirrors them into the RunStore — the
    pipeline-persistenceagent analog. Runs outlive their Workflows: a
    deleted Workflow keeps its last recorded state."""

    primary = (WORKFLOW_API_VERSION, WORKFLOW_KIND)
    owns: list = []

    def __init__(self, store: RunStore, clock=time.time):
        self.store = store
        self.clock = clock

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        ns, name = key
        try:
            wf = client.get(WORKFLOW_API_VERSION, WORKFLOW_KIND, ns, name)
        except NotFoundError:
            return Result()  # keep the last recorded state
        self.store.upsert_run(wf, clock=self.clock)
        return Result()
