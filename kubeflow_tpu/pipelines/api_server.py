"""The pipeline REST API: pipelines / runs / jobs.

The pipeline-apiserver analog (kubeflow/pipeline/pipeline-apiserver
.libsonnet; upstream ml-pipeline API shape, v1beta1 path prefix):

- ``POST/GET/DELETE /apis/v1beta1/pipelines`` — uploaded Workflow
  templates (stored in the RunStore).
- ``POST /apis/v1beta1/runs`` — create a run from a pipeline id or an
  inline workflow spec (instantiates a Workflow CR the engine executes);
  ``GET /apis/v1beta1/runs[?namespace=&phase=&schedule=]`` and
  ``GET /apis/v1beta1/runs/{ns}/{name}`` read the persisted history.
- ``POST/GET/DELETE /apis/v1beta1/jobs`` — ScheduledWorkflows ("jobs" in
  pipeline API vocabulary); ``POST /apis/v1beta1/jobs/{ns}/{name}:enable``
  / ``:disable`` flip the schedule.
- ``/healthz``.
"""

from __future__ import annotations

from typing import Optional

from ..api import k8s
from ..cluster.client import KubeClient, NotFoundError
from ..webapps._http import ApiError, JsonApp, JsonServer
from ..workflows.engine import WORKFLOW_API_VERSION, WORKFLOW_KIND
from .scheduled import SCHEDULED_WF_API_VERSION, SCHEDULED_WF_KIND
from .store import RunStore

PREFIX = "/apis/v1beta1"


def build_pipeline_app(client: KubeClient, store: RunStore,
                       namespace: str = "kubeflow",
                       prefix: str = "") -> JsonApp:
    """``prefix`` mounts the API under a URL base (e.g. "pipeline") so an
    ingress route /pipeline/ can front it, same as the jupyter app."""
    app = JsonApp(prefix=prefix)

    @app.route("GET", "/healthz")
    def healthz(params, query, body):
        return 200, {"ok": True}

    # -- pipelines ----------------------------------------------------------

    @app.route("POST", f"{PREFIX}/pipelines")
    def upload_pipeline(params, query, body):
        if not body or not body.get("name") or not body.get("workflow"):
            raise ApiError(400, "name and workflow are required")
        return 200, store.put_pipeline(body["name"], body["workflow"],
                                       body.get("description", ""))

    @app.route("GET", f"{PREFIX}/pipelines")
    def list_pipelines(params, query, body):
        return 200, {"pipelines": store.list_pipelines()}

    @app.route("GET", f"{PREFIX}/pipelines/{{name}}")
    def get_pipeline(params, query, body):
        p = store.get_pipeline(params["name"])
        if p is None:
            raise ApiError(404, f"pipeline {params['name']} not found")
        return 200, p

    @app.route("DELETE", f"{PREFIX}/pipelines/{{name}}")
    def delete_pipeline(params, query, body):
        if not store.delete_pipeline(params["name"]):
            raise ApiError(404, f"pipeline {params['name']} not found")
        return 200, {"deleted": params["name"]}

    # -- runs ---------------------------------------------------------------

    def _workflow_spec_from(body: dict) -> tuple[dict, Optional[str]]:
        if body.get("pipeline"):
            p = store.get_pipeline(body["pipeline"])
            if p is None:
                raise ApiError(404, f"pipeline {body['pipeline']} not found")
            return p["workflow"], body["pipeline"]
        if body.get("workflow"):
            return body["workflow"], None
        raise ApiError(400, "one of pipeline (id) or workflow (spec) "
                            "is required")

    @app.route("POST", f"{PREFIX}/runs")
    def create_run(params, query, body):
        if not body or not body.get("name"):
            raise ApiError(400, "name is required")
        wf_spec, pipeline_id = _workflow_spec_from(body)
        ns = body.get("namespace", namespace)
        params_list = body.get("parameters") or []
        spec = dict(wf_spec)
        if params_list:
            args = dict(spec.get("arguments") or {})
            args["parameters"] = params_list
            spec["arguments"] = args
        wf = {
            "apiVersion": WORKFLOW_API_VERSION, "kind": WORKFLOW_KIND,
            "metadata": {"name": body["name"], "namespace": ns,
                         "labels": ({"pipelines.kubeflow.org/pipeline":
                                     pipeline_id} if pipeline_id else {})},
            "spec": spec,
        }
        created = client.create(wf)
        store.upsert_run(created)
        return 200, {"run_id": f"{ns}/{body['name']}"}

    @app.route("GET", f"{PREFIX}/runs")
    def list_runs(params, query, body):
        return 200, {"runs": store.list_runs(
            namespace=query.get("namespace"),
            schedule=query.get("schedule"),
            phase=query.get("phase"),
            limit=int(query.get("limit", "100")))}

    @app.route("GET", f"{PREFIX}/runs/{{ns}}/{{name}}")
    def get_run(params, query, body):
        run = store.get_run(f"{params['ns']}/{params['name']}")
        if run is None:
            raise ApiError(404, f"run {params['ns']}/{params['name']} "
                                "not found")
        return 200, run

    # -- jobs (ScheduledWorkflows) ------------------------------------------

    @app.route("POST", f"{PREFIX}/jobs")
    def create_job(params, query, body):
        if not body or not body.get("name"):
            raise ApiError(400, "name is required")
        if not body.get("trigger"):
            raise ApiError(400, "trigger is required "
                                "(cronSchedule or periodicSchedule)")
        wf_spec, _ = _workflow_spec_from(body)
        ns = body.get("namespace", namespace)
        swf = {
            "apiVersion": SCHEDULED_WF_API_VERSION,
            "kind": SCHEDULED_WF_KIND,
            "metadata": {"name": body["name"], "namespace": ns},
            "spec": {
                "enabled": body.get("enabled", True),
                "maxConcurrency": body.get("maxConcurrency", 1),
                "maxHistory": body.get("maxHistory", 10),
                "trigger": body["trigger"],
                "workflow": {"spec": wf_spec},
            },
        }
        client.create(swf)
        return 200, {"job_id": f"{ns}/{body['name']}"}

    @app.route("GET", f"{PREFIX}/jobs")
    def list_jobs(params, query, body):
        jobs = client.list(SCHEDULED_WF_API_VERSION, SCHEDULED_WF_KIND,
                           namespace=query.get("namespace"))
        return 200, {"jobs": [{
            "name": k8s.name_of(j),
            "namespace": k8s.namespace_of(j, "default"),
            "enabled": j.get("spec", {}).get("enabled", True),
            "trigger": j.get("spec", {}).get("trigger"),
            "status": {k: v for k, v in (j.get("status") or {}).items()
                       if k in ("lastTriggeredTime", "nextTriggeredTime",
                                "runs")},
        } for j in jobs]}

    @app.route("DELETE", f"{PREFIX}/jobs/{{ns}}/{{name}}")
    def delete_job(params, query, body):
        try:
            client.delete(SCHEDULED_WF_API_VERSION, SCHEDULED_WF_KIND,
                          params["ns"], params["name"])
        except NotFoundError:
            raise ApiError(404, f"job {params['name']} not found")
        return 200, {"deleted": params["name"]}

    def _set_enabled(params, enabled: bool):
        try:
            client.patch(SCHEDULED_WF_API_VERSION, SCHEDULED_WF_KIND,
                         params["ns"], params["name"],
                         {"spec": {"enabled": enabled}})
        except NotFoundError:
            raise ApiError(404, f"job {params['name']} not found")
        return 200, {"name": params["name"], "enabled": enabled}

    # ':' is not a path separator; the {name} capture excludes '/', so the
    # verb routes need their own patterns
    @app.route("POST", f"{PREFIX}/jobs/{{ns}}/{{name}}:enable")
    def enable_job(params, query, body):
        return _set_enabled(params, True)

    @app.route("POST", f"{PREFIX}/jobs/{{ns}}/{{name}}:disable")
    def disable_job(params, query, body):
        return _set_enabled(params, False)

    return app


class PipelineAPIServer(JsonServer):
    """Deployable pipeline apiserver (pipeline-apiserver.libsonnet role)."""

    def __init__(self, client: KubeClient, store: Optional[RunStore] = None,
                 namespace: str = "kubeflow", prefix: str = "", **kw):
        self.store = store or RunStore()
        super().__init__(build_pipeline_app(client, self.store, namespace,
                                            prefix=prefix),
                         name="pipeline-api", **kw)
