"""ScheduledWorkflow: cron/periodic triggering of Workflows.

The pipeline-scheduledworkflow controller analog
(kubeflow/pipeline/pipeline-scheduledworkflow.libsonnet; upstream
ScheduledWorkflow CRD shape). Spec subset:

```yaml
apiVersion: kubeflow.org/v1beta1
kind: ScheduledWorkflow
spec:
  enabled: true
  maxConcurrency: 1          # running workflows triggered by this schedule
  maxHistory: 10             # completed run records kept in status
  trigger:
    cronSchedule: {cron: "0 * * * *"}        # OR
    periodicSchedule: {intervalSecond: 3600}
  workflow:
    spec: {...}              # Workflow spec to instantiate per run
status:
  conditions, lastTriggeredTime, nextTriggeredTime, runs: [...]
```

Triggered Workflows are owner-ref'd to the schedule (cascade delete) and
labeled for discovery. The reconciler is clock-injected and level-driven:
it fires every due tick since the last trigger (catch-up is capped to one
run per reconcile to avoid thundering herds), then requeues until the next
fire time.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..api import k8s
from ..cluster.client import KubeClient, NotFoundError
from ..controllers.runtime import Key, Reconciler, Result, status_snapshot
from ..workflows.engine import (PHASE_RUNNING, TERMINAL,
                                WORKFLOW_API_VERSION, WORKFLOW_KIND)

log = logging.getLogger(__name__)

SCHEDULED_WF_API_VERSION = "kubeflow.org/v1beta1"
SCHEDULED_WF_KIND = "ScheduledWorkflow"
SCHEDULE_LABEL = "scheduledworkflows.kubeflow.org/name"


# ---------------------------------------------------------------- cron


def _parse_field(field: str, lo: int, hi: int) -> frozenset[int]:
    """One cron field → allowed values. Supports * , - / and numbers."""
    out: set[int] = set()
    for part in field.split(","):
        part = part.strip()
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step < 1:
                raise ValueError(f"bad cron step in {field!r}")
        if part in ("*", ""):
            lo_p, hi_p = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            lo_p, hi_p = int(a), int(b)
        else:
            lo_p = hi_p = int(part)
        if not (lo <= lo_p <= hi and lo <= hi_p <= hi and lo_p <= hi_p):
            raise ValueError(f"cron field {field!r} out of range [{lo},{hi}]")
        out.update(range(lo_p, hi_p + 1, step))
    return frozenset(out)


def parse_cron(expr: str) -> tuple[frozenset, ...]:
    """5-field cron → (minutes, hours, days-of-month, months, days-of-week).
    Day-of-week: 0/7 = Sunday (both accepted)."""
    fields = expr.split()
    if len(fields) != 5:
        raise ValueError(f"cron needs 5 fields, got {expr!r}")
    minutes = _parse_field(fields[0], 0, 59)
    hours = _parse_field(fields[1], 0, 23)
    dom = _parse_field(fields[2], 1, 31)
    months = _parse_field(fields[3], 1, 12)
    dow = frozenset(d % 7 for d in _parse_field(fields[4], 0, 7))
    return minutes, hours, dom, months, dow


def next_fire_time(expr: str, after: float) -> float:
    """Next epoch second (UTC) strictly after ``after`` matching the cron.
    Kube-cron semantics: when both day-of-month and day-of-week are
    restricted, either may match."""
    minutes, hours, dom, months, dow = parse_cron(expr)
    fields = expr.split()
    dom_star = fields[2].strip() == "*"
    dow_star = fields[4].strip() == "*"
    # minute resolution: start at the next whole minute
    t = (int(after // 60) + 1) * 60
    for _ in range(366 * 24 * 60):  # bounded: at most one year of minutes
        tm = time.gmtime(t)
        if tm.tm_min in minutes and tm.tm_hour in hours and \
                tm.tm_mon in months:
            dom_ok = tm.tm_mday in dom
            dow_ok = (tm.tm_wday + 1) % 7 in dow  # gmtime: Mon=0 → Sun=0
            day_ok = (dom_ok or dow_ok) if not (dom_star or dow_star) else \
                (dom_ok and dow_ok)
            if day_ok:
                return float(t)
        t += 60
    raise ValueError(f"cron {expr!r} never fires")


# ------------------------------------------------------------ reconciler


class ScheduledWorkflowReconciler(Reconciler):
    primary = (SCHEDULED_WF_API_VERSION, SCHEDULED_WF_KIND)
    owns = [(WORKFLOW_API_VERSION, WORKFLOW_KIND)]

    def __init__(self, clock=time.time):
        self.clock = clock  # injected for deterministic tests

    # -- trigger math -------------------------------------------------------

    def _next_fire(self, spec: dict, after: float) -> Optional[float]:
        trigger = spec.get("trigger") or {}
        cron = (trigger.get("cronSchedule") or {}).get("cron")
        if cron:
            return next_fire_time(cron, after)
        interval = (trigger.get("periodicSchedule") or {}).get(
            "intervalSecond")
        if interval:
            return after + float(interval)
        return None

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, client: KubeClient, key: Key) -> Result:
        ns, name = key
        try:
            swf = client.get(SCHEDULED_WF_API_VERSION, SCHEDULED_WF_KIND,
                             ns, name)
        except NotFoundError:
            return Result()  # cascade GC reaps triggered workflows
        spec = swf.get("spec", {}) or {}
        status = swf.setdefault("status", {})
        before = status_snapshot(status)
        now = self.clock()

        runs = self._sync_runs(client, swf, status)
        active = [r for r in runs if r["phase"] not in TERMINAL]

        enabled = spec.get("enabled", True)
        max_concurrency = int(spec.get("maxConcurrency", 1))
        next_at = status.get("nextTriggeredTime")
        if next_at is None:
            # first reconcile: anchor the schedule at creation time
            next_at = self._next_fire(spec, now)
            status["nextTriggeredTime"] = next_at

        requeue_after = 0.0
        if enabled and next_at is not None:
            if now >= next_at:
                if len(active) < max_concurrency:
                    run = self._trigger(client, swf, spec, next_at)
                    if run is not None:
                        runs.append(run)
                    status["lastTriggeredTime"] = next_at
                    status["nextTriggeredTime"] = self._next_fire(
                        spec, max(now, next_at))
                # at concurrency limit: hold the fire time; re-check soon
                else:
                    requeue_after = 1.0
            if not requeue_after and status.get("nextTriggeredTime"):
                requeue_after = max(status["nextTriggeredTime"] - now, 0.05)

        max_history = int(spec.get("maxHistory", 10))
        status["runs"] = self._trim_history(client, swf, runs, max_history)
        k8s.set_condition(swf, k8s.Condition(
            "Enabled", "True" if enabled else "False",
            "Schedule", f"{len(active)} active run(s)"))
        status["conditions"] = swf["status"].get("conditions", [])
        if status_snapshot(status) != before:
            fresh = client.get(SCHEDULED_WF_API_VERSION, SCHEDULED_WF_KIND,
                               ns, name)
            fresh["status"] = status
            client.update_status(fresh)
        return Result(requeue_after=requeue_after) if requeue_after \
            else Result()

    # -- runs ---------------------------------------------------------------

    def _sync_runs(self, client: KubeClient, swf: dict,
                   status: dict) -> list[dict]:
        """Refresh the status.runs records from live Workflows."""
        ns = k8s.namespace_of(swf, "default")
        live = {k8s.name_of(w): w for w in client.list(
            WORKFLOW_API_VERSION, WORKFLOW_KIND, ns,
            selector={SCHEDULE_LABEL: k8s.name_of(swf)})}
        runs = []
        seen = set()
        for rec in status.get("runs", []) or []:
            wf = live.get(rec["name"])
            if wf is not None:
                rec = dict(rec,
                           phase=wf.get("status", {}).get("phase",
                                                          PHASE_RUNNING))
            seen.add(rec["name"])
            runs.append(rec)
        for wname, wf in live.items():
            if wname not in seen:  # adopted (e.g. controller restart)
                ann = (wf.get("metadata", {}).get("annotations") or {})
                at = ann.get(
                    "scheduledworkflows.kubeflow.org/scheduled-at")
                try:
                    at = float(at) if at is not None else None
                except ValueError:
                    at = None
                runs.append({
                    "name": wname,
                    "scheduledAt": at,
                    "phase": wf.get("status", {}).get("phase",
                                                      PHASE_RUNNING)})
        return runs

    def _trigger(self, client: KubeClient, swf: dict, spec: dict,
                 fire_time: float) -> Optional[dict]:
        ns = k8s.namespace_of(swf, "default")
        index = int(swf.get("status", {}).get("triggerCount", 0)) + 1
        swf.setdefault("status", {})["triggerCount"] = index
        wf_spec = (spec.get("workflow") or {}).get("spec")
        if not wf_spec:
            log.warning("ScheduledWorkflow %s/%s has no workflow.spec",
                        ns, k8s.name_of(swf))
            return None
        name = f"{k8s.name_of(swf)}-{index}"
        wf = {
            "apiVersion": WORKFLOW_API_VERSION, "kind": WORKFLOW_KIND,
            "metadata": {
                "name": name, "namespace": ns,
                "labels": {SCHEDULE_LABEL: k8s.name_of(swf)},
                "annotations": {
                    "scheduledworkflows.kubeflow.org/scheduled-at":
                        str(fire_time)},
            },
            "spec": wf_spec,
        }
        k8s.set_owner(wf, swf)
        try:
            client.create(wf)
        except Exception as e:  # noqa: BLE001 — record, try again next fire
            log.warning("trigger %s failed: %s", name, e)
            return None
        return {"name": name, "scheduledAt": fire_time,
                "phase": PHASE_RUNNING}

    @staticmethod
    def _trigger_index(swf: dict, run_name: str) -> int:
        """Trigger ordinal encoded in the generated run name, 0 if foreign."""
        prefix = k8s.name_of(swf) + "-"
        if run_name.startswith(prefix):
            try:
                return int(run_name[len(prefix):])
            except ValueError:
                pass
        return 0

    def _trim_history(self, client: KubeClient, swf: dict, runs: list[dict],
                      max_history: int) -> list[dict]:
        """Keep every active run + the most recent terminal ones; GC the
        trimmed runs' Workflow objects (upstream scheduledworkflow
        semantics — otherwise _sync_runs re-adopts them forever). Run
        history beyond this lives in the persistence store."""
        active = [r for r in runs if r["phase"] not in TERMINAL]
        done = [r for r in runs if r["phase"] in TERMINAL]
        # status.runs keeps active runs at the head, so a run's list position
        # says nothing about age once it completes.  Order terminal runs
        # chronologically (scheduledAt, falling back to the trigger index in
        # the generated name) so the slice below keeps the NEWEST runs.
        done.sort(key=lambda r: (r.get("scheduledAt") is None,
                                 r.get("scheduledAt") or 0.0,
                                 self._trigger_index(swf, r["name"])))
        ns = k8s.namespace_of(swf, "default")
        for rec in done[:-max_history] if max_history else done:
            try:
                client.delete(WORKFLOW_API_VERSION, WORKFLOW_KIND, ns,
                              rec["name"])
            except NotFoundError:
                pass
        return active + (done[-max_history:] if max_history else [])
