"""Kubeflow Pipelines equivalent over the in-repo workflow engine.

Reference: the ``kubeflow/pipeline`` package deploys four services
(SURVEY.md §2.3 argo/pipeline row; VERDICT r1 missing item 4):
``pipeline-apiserver.libsonnet`` (run/pipeline/job REST API),
``pipeline-scheduledworkflow.libsonnet`` (cron controller),
``pipeline-persistenceagent.libsonnet`` (workflow → run-history DB),
``pipeline-ui.libsonnet``. The TPU-native equivalents:

- ``scheduled``  — ScheduledWorkflow CR + reconciler (cron/periodic
  triggers, maxConcurrency, run history) over the Workflow engine.
- ``store``      — sqlite run persistence + the persistence-agent
  reconciler recording every Workflow's lifecycle.
- ``api_server`` — the REST surface (pipelines/runs/jobs) the UI and
  clients consume.
- ``dsl``        — pipeline authoring (Python DAG → Workflow manifest),
  the kfp.dsl/compiler role.
"""

from .dsl import Pipeline, Step
from .scheduled import (SCHEDULED_WF_API_VERSION, SCHEDULED_WF_KIND,
                        ScheduledWorkflowReconciler, next_fire_time,
                        parse_cron)
from .store import PersistenceAgent, RunStore

__all__ = ["Pipeline", "Step", "ScheduledWorkflowReconciler", "parse_cron", "next_fire_time",
           "RunStore", "PersistenceAgent", "SCHEDULED_WF_API_VERSION",
           "SCHEDULED_WF_KIND"]
