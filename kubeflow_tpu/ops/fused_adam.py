"""Fused shard-local Adam update as a Pallas kernel (ISSUE 16 rung 2).

The zero2-explicit path (runtime/trainstep.py) reduce-scatters gradients
and then runs the optimizer over the shard-local slab as a stock optax
chain: weight decay, moment update, bias correction and the parameter
step each materialize intermediates in HBM — five reads and three writes
per element where one read of (p, m, v, g) and one write of (Δp, m', v')
suffices. "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (PAPERS.md) makes the weight update a first-class
optimization target; this kernel is the compute half of that argument.

One Pallas kernel fuses, per element of the shard-local slab:

    g  ← g + wd·p                 (L2-into-gradient, recipe decay_mask)
    m' ← β₁·m + (1−β₁)·g
    v' ← β₂·v + (1−β₂)·g²
    Δp ← −lr · (m'/bc₁) / (√(v'/bc₂) + ε)

streaming (p, m, v, g) through VMEM in (rows × 128-lane) tiles with f32
accumulate, emitting (Δp, m', v') in one pass. Exposed as an optax
``GradientTransformation`` (``fused_adam``) so it drops into every
TrainStepBuilder weight-update mode unchanged — under zero2-explicit the
update runs under GSPMD sharding constraints, so the kernel operates on
exactly the shard-local shard; under replicated it fuses the full slab.

Numerics contract: parity ≤ 1e-5 against the stock optax reference
``chain(add_decayed_weights(wd, decay_mask), adam(sched))`` — enforced by
tests/test_kernels.py and re-measured by ``bench.py --mode kernels``.

TPU notes:
- each leaf is flattened, zero-padded to a whole number of (8, 128) f32
  tiles, and processed as a [rows, 128] slab; zero padding is a fixed
  point of the update (m'=v'=Δp=0), so the pad lanes never leak.
- lr / wd / bias corrections arrive as a (4,) SMEM operand — lr is a
  traced schedule value, so it cannot be a Python closure constant.
- off-TPU (tests, CPU smoke) the same kernel runs with ``interpret=True``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128       # TPU lane width: last dim of every tile
SUBLANES = 8      # f32 sublane alignment
# rows per grid step: 256×128 f32 ≈ 128 KiB per operand; 7 operands in
# flight ≈ 0.9 MiB of VMEM — comfortably under the ~16 MiB budget while
# long enough to amortize DMA issue
BLOCK_ROWS = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _adam_kernel(scal_ref, p_ref, m_ref, v_ref, g_ref,
                 dp_ref, m_out_ref, v_out_ref, *, b1: float, b2: float,
                 eps: float):
    """One (rows, 128) tile of the fused update. scal_ref (SMEM, f32[4])
    carries [lr, wd, bias_corr1, bias_corr2]; β/ε are compile-time."""
    lr = scal_ref[0]
    wd = scal_ref[1]
    bc1 = scal_ref[2]
    bc2 = scal_ref[3]
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) + wd * p
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * (g * g)
    m_hat = m / bc1
    v_hat = v / bc2
    dp_ref[:] = (-lr * m_hat / (jnp.sqrt(v_hat) + eps)).astype(dp_ref.dtype)
    m_out_ref[:] = m
    v_out_ref[:] = v


def _fused_leaf_update(p: jax.Array, m: jax.Array, v: jax.Array,
                       g: jax.Array, scalars: jax.Array, *, b1: float,
                       b2: float, eps: float):
    """Run the fused kernel over one (arbitrary-shape) leaf. Returns
    (Δp, m', v') with Δp in the leaf dtype and m'/v' in f32."""
    shape, dtype = p.shape, p.dtype
    n = int(p.size)
    if n == 0:
        z = jnp.zeros(shape, jnp.float32)
        return jnp.zeros(shape, dtype), z, z

    rows = max(-(-n // LANES), SUBLANES)
    rows += (-rows) % SUBLANES
    block_rows = min(rows, BLOCK_ROWS)
    rows += (-rows) % block_rows
    padded = rows * LANES

    def slab(x, dt):
        flat = x.reshape(-1).astype(dt)
        return jnp.pad(flat, (0, padded - n)).reshape(rows, LANES)

    kernel = functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps)
    grid = (rows // block_rows,)
    bspec = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0))
    dp, m2, v2 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  bspec, bspec, bspec, bspec],
        out_specs=[pl.BlockSpec((block_rows, LANES), lambda i: (i, 0)),
                   pl.BlockSpec((block_rows, LANES), lambda i: (i, 0)),
                   pl.BlockSpec((block_rows, LANES), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows, LANES), dtype),
                   jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, LANES), jnp.float32)],
        interpret=_interpret(),
    )(scalars, slab(p, jnp.float32), slab(m, jnp.float32),
      slab(v, jnp.float32), slab(g, jnp.float32))
    unpad = lambda x: x.reshape(-1)[:n].reshape(shape)  # noqa: E731
    return unpad(dp), unpad(m2), unpad(v2)


class FusedAdamState(NamedTuple):
    """Mirrors optax scale_by_adam's (count, mu, nu); mu/nu held in f32
    regardless of param dtype (the kernel accumulates in f32)."""
    count: jax.Array
    mu: Any
    nu: Any


def fused_adam(
    learning_rate: Union[float, optax.Schedule],
    *,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    mask: Optional[Union[Any, Callable[[Any], Any]]] = None,
) -> optax.GradientTransformation:
    """Drop-in for ``chain(add_decayed_weights(wd, mask), adam(lr))`` that
    executes the whole per-leaf update as ONE Pallas kernel. Matches
    optax semantics exactly: lr evaluated at the pre-increment count,
    bias correction at count+1, L2 folded into the gradient before the
    moment update, decay applied only where ``mask`` is True."""

    def init_fn(params):
        zeros = jax.tree.map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
        return FusedAdamState(count=jnp.zeros([], jnp.int32), mu=zeros,
                              nu=jax.tree.map(jnp.copy, zeros))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_adam needs params (weight decay + "
                             "parameter-relative update)")
        mask_tree = mask(params) if callable(mask) else mask
        if mask_tree is None:
            mask_tree = jax.tree.map(lambda _: True, params)
        count_inc = optax.safe_int32_increment(state.count)
        lr = (learning_rate(state.count) if callable(learning_rate)
              else learning_rate)
        lr = jnp.asarray(lr, jnp.float32)
        bc1 = 1.0 - jnp.power(jnp.asarray(b1, jnp.float32), count_inc)
        bc2 = 1.0 - jnp.power(jnp.asarray(b2, jnp.float32), count_inc)

        leaves_p, treedef = jax.tree_util.tree_flatten(params)
        leaves_g = treedef.flatten_up_to(updates)
        leaves_m = treedef.flatten_up_to(state.mu)
        leaves_v = treedef.flatten_up_to(state.nu)
        leaves_mask = treedef.flatten_up_to(mask_tree)

        out_dp, out_m, out_v = [], [], []
        for p, g, m, v, decay in zip(leaves_p, leaves_g, leaves_m,
                                     leaves_v, leaves_mask):
            wd = jnp.asarray(weight_decay if decay else 0.0, jnp.float32)
            scalars = jnp.stack([lr, wd, bc1, bc2])
            dp, m2, v2 = _fused_leaf_update(p, m, v, g, scalars, b1=b1,
                                            b2=b2, eps=eps)
            out_dp.append(dp)
            out_m.append(m2)
            out_v.append(v2)
        new_state = FusedAdamState(
            count=count_inc,
            mu=jax.tree_util.tree_unflatten(treedef, out_m),
            nu=jax.tree_util.tree_unflatten(treedef, out_v))
        return jax.tree_util.tree_unflatten(treedef, out_dp), new_state

    return optax.GradientTransformation(init_fn, update_fn)


def reference_adam(
    learning_rate: Union[float, optax.Schedule],
    *,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    mask: Optional[Union[Any, Callable[[Any], Any]]] = None,
) -> optax.GradientTransformation:
    """The stock optax chain the fused kernel must match to ≤1e-5 —
    the executable spec for tests and ``bench.py --mode kernels``."""
    txs = []
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay, mask=mask))
    txs.append(optax.adam(learning_rate, b1=b1, b2=b2, eps=eps))
    return optax.chain(*txs)
