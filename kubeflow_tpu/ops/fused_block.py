"""Fused ResNet bottleneck block (inference): one Pallas kernel per block.

The whole stride-1 block —

    conv1x1 → scale/shift → relu → conv3x3 → scale/shift → relu →
    conv1x1 → scale/shift → (+ residual/projection) → relu

— as one kernel that reads the block input once from HBM and writes the
output once; the interiors never leave VMEM. Batch-only tiling keeps the
full spatial extent resident, so the 3x3 conv needs no halo exchange; it
runs as 9 shifted matmuls on the MXU. At inference BatchNorm folds to an
exact affine, so the kernel is numerically identical to the standard
eval path (argmax agreement 1.0, max|Δ|=0 measured at 224px/bs128).

**Measured outcome (PERF.md): this does NOT beat XLA at inference** —
6.8k img/s fused vs 11.5k standard on the bench chip. At eval BN is
affine and XLA already fuses it into the conv epilogues, so there are no
extra HBM passes to remove; the kernel's shifted-matmul conv and
in-VMEM relayouts cost more than they save. The roofline's missing-byte
argument applies to TRAINING (batch-stat passes + autodiff stashes),
which needs a ghost-BN fwd+bwd kernel pair this module deliberately does
not model yet. Kept as the measured baseline for that future work and as
the repo's worked example of a multi-op conv-block kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["FusedBlockWeights", "fold_block", "fused_bottleneck_eval",
           "reference_bottleneck_eval"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@dataclass(frozen=True)
class FusedBlockWeights:
    """One bottleneck block with BN folded to affine (eval semantics).

    wN: conv kernels — w1 (Cin,Cmid), w2 (3,3,Cmid,Cmid), w3 (Cmid,Cout);
    sN/bN: the folded scale/shift, s = γ/sqrt(var+eps),
    b = β − mean·s (flax BatchNorm running stats). wp/sp/bp: the
    projection shortcut for Cin≠Cout blocks (1x1, stride 1)."""

    w1: jax.Array
    s1: jax.Array
    b1: jax.Array
    w2: jax.Array
    s2: jax.Array
    b2: jax.Array
    w3: jax.Array
    s3: jax.Array
    b3: jax.Array
    wp: Optional[jax.Array] = None
    sp: Optional[jax.Array] = None
    bp: Optional[jax.Array] = None


def _fold_bn(bn_params: dict, bn_stats: dict,
             eps: float = 1e-5) -> tuple[jax.Array, jax.Array]:
    scale = bn_params["scale"].astype(jnp.float32)
    bias = bn_params["bias"].astype(jnp.float32)
    mean = bn_stats["mean"].astype(jnp.float32)
    var = bn_stats["var"].astype(jnp.float32)
    s = scale * jax.lax.rsqrt(var + eps)
    return s, bias - mean * s


def fold_block(block_params: dict, block_stats: dict,
               eps: float = 1e-5) -> FusedBlockWeights:
    """Fold one flax BottleneckBlock's params+batch_stats (models/resnet
    naming: Conv_0..2 / BatchNorm_0..2 / conv_proj / norm_proj)."""
    s1, b1 = _fold_bn(block_params["BatchNorm_0"],
                      block_stats["BatchNorm_0"], eps)
    s2, b2 = _fold_bn(block_params["BatchNorm_1"],
                      block_stats["BatchNorm_1"], eps)
    s3, b3 = _fold_bn(block_params["BatchNorm_2"],
                      block_stats["BatchNorm_2"], eps)
    w1 = block_params["Conv_0"]["kernel"][0, 0]          # (Cin, Cmid)
    w2 = block_params["Conv_1"]["kernel"]                # (3,3,Cmid,Cmid)
    w3 = block_params["Conv_2"]["kernel"][0, 0]          # (Cmid, Cout)
    wp = sp = bp = None
    if "conv_proj" in block_params:
        wp = block_params["conv_proj"]["kernel"][0, 0]   # (Cin, Cout)
        sp, bp = _fold_bn(block_params["norm_proj"],
                          block_stats["norm_proj"], eps)
    return FusedBlockWeights(w1=w1, s1=s1, b1=b1, w2=w2, s2=s2, b2=b2,
                             w3=w3, s3=s3, b3=b3, wp=wp, sp=sp, bp=bp)


def reference_bottleneck_eval(x: jax.Array, w: FusedBlockWeights
                              ) -> jax.Array:
    """Pure-jnp executable spec the kernel is tested against."""
    f32 = jnp.float32
    n, h, ww, cin = x.shape
    xm = x.reshape(-1, cin)
    h1 = jax.nn.relu(xm.astype(f32) @ w.w1.astype(f32) * w.s1 + w.b1)
    cmid = h1.shape[-1]
    h1 = h1.reshape(n, h, ww, cmid).astype(x.dtype)
    pad = jnp.pad(h1, ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((n * h * ww, cmid), f32)
    for dy in range(3):
        for dx in range(3):
            shifted = pad[:, dy:dy + h, dx:dx + ww, :].reshape(-1, cmid)
            acc += shifted.astype(f32) @ w.w2[dy, dx].astype(f32)
    h2 = jax.nn.relu(acc * w.s2 + w.b2).astype(x.dtype)
    h3 = h2.astype(f32) @ w.w3.astype(f32) * w.s3 + w.b3
    if w.wp is not None:
        res = xm.astype(f32) @ w.wp.astype(f32) * w.sp + w.bp
    else:
        res = xm.astype(f32)
    out = jax.nn.relu(h3 + res).astype(x.dtype)
    return out.reshape(n, h, ww, -1)


def _kernel(x_ref, w1_ref, s1_ref, b1_ref, w2_ref, s2_ref, b2_ref,
            w3_ref, s3_ref, b3_ref, wp_ref, sp_ref, bp_ref, o_ref,
            *, has_proj: bool):
    f32 = jnp.float32
    x = x_ref[...]                              # (Bt, H, W, Cin)
    bt, h, w, cin = x.shape
    xm = x.reshape(-1, cin)

    h1 = jnp.dot(xm, w1_ref[...], preferred_element_type=f32)
    h1 = jax.nn.relu(h1 * s1_ref[...] + b1_ref[...])
    cmid = h1.shape[-1]
    h1 = h1.astype(x.dtype).reshape(bt, h, w, cmid)

    padded = jnp.pad(h1, ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((bt * h * w, cmid), f32)
    for dy in range(3):
        for dx in range(3):
            shifted = padded[:, dy:dy + h, dx:dx + w, :].reshape(-1, cmid)
            acc = acc + jnp.dot(shifted, w2_ref[dy, dx],
                                preferred_element_type=f32)
    h2 = jax.nn.relu(acc * s2_ref[...] + b2_ref[...]).astype(x.dtype)

    # keep the big Cout-wide tensors in bf16 (the f32 pair would blow the
    # ~16MB scoped-VMEM stack at 56²x256 tiles); the dots still accumulate
    # in f32 and only the final add runs at bf16 — the same precision the
    # standard eval path's residual add uses
    h3 = jnp.dot(h2, w3_ref[...], preferred_element_type=f32)
    h3 = (h3 * s3_ref[...] + b3_ref[...]).astype(x.dtype)

    if has_proj:
        res = jnp.dot(xm, wp_ref[...], preferred_element_type=f32)
        res = (res * sp_ref[...] + bp_ref[...]).astype(x.dtype)
    else:
        res = xm
    out = jax.nn.relu(h3 + res)
    o_ref[...] = out.reshape(bt, h, w, -1)


def fused_bottleneck_eval(x: jax.Array, w: FusedBlockWeights, *,
                          block_bt: Optional[int] = None) -> jax.Array:
    """The fused block. Tiles over batch only (full spatial in VMEM, no
    halo); stride-1 blocks only — callers route strided blocks to XLA."""
    n, h, ww, cin = x.shape
    cmid = w.w1.shape[-1]
    cout = w.w3.shape[-1]
    has_proj = w.wp is not None
    if not has_proj and cin != cout:
        raise ValueError(f"Cin {cin} != Cout {cout} needs a projection")

    if block_bt is None:
        # VMEM budget (~16MB/core): in+out tiles + interiors + f32 accs,
        # x2 for pipelining. Per image bytes ≈ hw*(cin+cout)*2 +
        # hw*cmid*(2*2 + 4*2)
        per_image = h * ww * ((cin + cout) * 2 + cmid * 12)
        block_bt = max(1, int((6 * 2 ** 20) // max(per_image, 1)))
        while n % block_bt:
            block_bt -= 1
    elif n % block_bt:
        raise ValueError(
            f"block_bt {block_bt} must divide batch {n} (a partial last "
            f"tile would leave output rows unwritten)")
    dtype = x.dtype

    weights = [w.w1.astype(dtype), w.s1, w.b1,
               w.w2.astype(dtype), w.s2, w.b2,
               w.w3.astype(dtype), w.s3, w.b3]
    if has_proj:
        weights += [w.wp.astype(dtype), w.sp, w.bp]
    else:
        # dead operands so the kernel signature is static
        weights += [jnp.zeros((1, 1), dtype), jnp.zeros((1,), jnp.float32),
                    jnp.zeros((1,), jnp.float32)]

    full = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    in_specs = [pl.BlockSpec((block_bt, h, ww, cin),
                             lambda i: (i, 0, 0, 0))]
    in_specs += [full(wi.shape) for wi in weights]

    return pl.pallas_call(
        partial(_kernel, has_proj=has_proj),
        grid=(n // block_bt,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_bt, h, ww, cout),
                               lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h, ww, cout), dtype),
        interpret=_interpret(),
    )(x, *weights)
