"""Fused ResNet bottleneck block (TRAINING): ghost-BN Pallas fwd + bwd.

The training-mode companion to ops/fused_block.py (which measured the
inference variant and showed the missing-byte argument only applies to
training: batch-stat passes + autodiff stashes are the redundant HBM
traffic — PERF.md "What would actually beat the roofline" item 1).

One stride-1 bottleneck block —

    conv1x1 → BN → relu → conv3x3 → BN → relu → conv1x1 → BN
    → (+ residual | BN(conv_proj)) → relu

— as ONE forward kernel and ONE backward kernel. Per batch tile the
forward reads the block input once from HBM and writes the output once;
the backward reads the input and the upstream gradient once, RECOMPUTES
the block interior in VMEM, and writes dx once plus the (tiny) weight
gradients. Interiors never touch HBM in either direction; the backward
trades ~⅓ extra MXU FLOPs for the eliminated traffic — the right trade
on a memory-bound chip (PERF.md roofline: MXU time ≈ 10.5 ms of a 47 ms
step).

**Ghost BatchNorm semantics (the opt-in departure).** Batch statistics
are computed per batch *tile* (the kernel grid unit), not over the full
per-chip batch: that is what makes the block tile-local and fusable.
Each ghost batch still averages over Bt·H·W samples per channel
(≥ 3136 even at Bt=1 on 56² feature maps), and per-subset BN is
standard practice in large-batch training (ghost BN; per-replica BN is
also what tf_cnn_benchmarks' data-parallel mode does — each GPU
normalizes over its own shard). Running statistics are updated with the
tile-averaged ghost moments. The semantics ship as an opt-in workload
variant (`--fused-blocks`), benchmarked and validated separately from
the exact-BN default path.

Backward derivation (per tile, per channel; M = Bt·H·W samples):
    BN: m = E[a], v = E[a²]−m², x̂ = (a−m)·rsqrt(v+eps), y = γx̂+β
    ∂γ = Σ dy·x̂ ; ∂β = Σ dy ; with dx̂ = dy·γ:
    ∂a = rsqrt(v+eps)·(dx̂ − mean(dx̂) − x̂·mean(dx̂·x̂))
    conv3x3 (stride 1, pad 1) as 9 shifted matmuls; its transpose uses
    the mirrored offsets (2−dy, 2−dx) on the padded gradient.

The pure-jnp `reference_bottleneck_train` is the executable spec both
kernels are tested against (values AND `jax.grad` gradients).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_bottleneck_train", "reference_bottleneck_train",
           "block_weights", "stats_to_tree", "default_tile_bt",
           "fits_vmem_budget", "VMEM_BUDGET_BYTES",
           "SCOPED_VMEM_LIMIT_BYTES"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# -----------------------------------------------------------------------------
# weight plumbing: the flax BottleneckBlock params subtree ↔ a flat tuple
# -----------------------------------------------------------------------------

def block_weights(params: dict) -> tuple:
    """Flatten one flax BottleneckBlock params subtree (models/resnet
    naming) into the kernel's positional weight tuple. Projection blocks
    (conv_proj/norm_proj present) append 3 more entries."""
    w = (params["Conv_0"]["kernel"][0, 0],
         params["BatchNorm_0"]["scale"], params["BatchNorm_0"]["bias"],
         params["Conv_1"]["kernel"],
         params["BatchNorm_1"]["scale"], params["BatchNorm_1"]["bias"],
         params["Conv_2"]["kernel"][0, 0],
         params["BatchNorm_2"]["scale"], params["BatchNorm_2"]["bias"])
    if "conv_proj" in params:
        w += (params["conv_proj"]["kernel"][0, 0],
              params["norm_proj"]["scale"], params["norm_proj"]["bias"])
    return w


def stats_to_tree(stats: tuple, has_proj: bool) -> dict:
    """Tile-averaged ghost moments as the flax batch_stats subtree shape
    (mean/var per BatchNorm) for the running-stat EMA update."""
    m1, v1, m2, v2, m3, v3, mp, vp = stats
    tree = {"BatchNorm_0": {"mean": m1, "var": v1},
            "BatchNorm_1": {"mean": m2, "var": v2},
            "BatchNorm_2": {"mean": m3, "var": v3}}
    if has_proj:
        tree["norm_proj"] = {"mean": mp, "var": vp}
    return tree


VMEM_BUDGET_BYTES = 7 * 2 ** 20

# Scoped-VMEM (kernel stack) ceiling for the fused kernels. The backward's
# weight-grad temporaries + accumulator refs are ~fixed per kernel instance
# — ~18.5 MB measured at stage-4 geometry (cmid=512) on first Mosaic
# compile — so the default 16 MiB stack cap fails regardless of batch
# tile. v5e has 128 MiB VMEM; granting 48 MiB of stack to these kernels
# leaves ample room for block buffers. Passed per-kernel via Pallas
# compiler_params (a process-wide XLA_FLAGS entry would fatal CPU-client
# processes that don't know TPU flags).
SCOPED_VMEM_LIMIT_BYTES = 48 * 1024 * 1024


def _compiler_params():
    return pltpu.CompilerParams(vmem_limit_bytes=SCOPED_VMEM_LIMIT_BYTES)


def _per_image_bytes(h: int, w: int, cin: int, cmid: int, cout: int) -> int:
    """Backward working-set estimate per image (the heavier direction):
    x + g + dx tiles, bf16 interiors (h1, h2, x̂3, gz, da3), f32 (M,Cmid)
    temporaries and one f32 (M,Cout) temporary."""
    return h * w * (cin * 2 * 2 + cout * 2 * 4 + cout * 4
                    + cmid * (2 * 2 + 4 * 4))


def fits_vmem_budget(h: int, w: int, cin: int, cmid: int,
                     cout: int) -> bool:
    """Whether even a one-image batch tile of this block's backward
    working set fits the VMEM budget. Blocks that fail (ResNet-50's
    56×56 stage-1/2 bottlenecks estimate ~14–17 MB/image) must route to
    the XLA path — the kernel grid tiles batch only, so bt=1 is the
    floor and a kernel launched past the budget VMEM-OOMs on silicon."""
    return _per_image_bytes(h, w, cin, cmid, cout) <= VMEM_BUDGET_BYTES


def default_tile_bt(n: int, h: int, w: int, cin: int, cmid: int,
                    cout: int) -> int:
    """Largest batch tile whose backward working set fits the VMEM
    budget (see _per_image_bytes). Callers must have checked
    fits_vmem_budget first: this clamps to bt=1 even when one image
    already busts the budget."""
    per_image = _per_image_bytes(h, w, cin, cmid, cout)
    bt = max(1, int(VMEM_BUDGET_BYTES // max(per_image, 1)))
    while n % bt:
        bt -= 1
    return bt


# -----------------------------------------------------------------------------
# executable spec (pure jnp, differentiable) — what the kernels must match
# -----------------------------------------------------------------------------

def reference_bottleneck_train(x: jax.Array, weights: tuple, *,
                               tile_bt: int, eps: float = 1e-5
                               ) -> tuple[jax.Array, tuple]:
    """Ghost-BN bottleneck forward in plain jnp, tiled exactly like the
    kernel grid ((n//tile_bt) ghost batches). Differentiable: jax.grad of
    this is the golden gradient for the Pallas backward."""
    has_proj = len(weights) == 12
    w1, g1, b1, w2, g2, b2, w3, g3, b3 = weights[:9]
    n, h, w_, cin = x.shape
    t = n // tile_bt
    f32 = jnp.float32
    dt = x.dtype

    def gbn(a, g, b):
        # a: (T, M, C) f32; ghost stats over axis 1
        m = jnp.mean(a, axis=1, keepdims=True)
        v = jnp.mean(a * a, axis=1, keepdims=True) - m * m
        xh = (a - m) * jax.lax.rsqrt(v + eps)
        return g * xh + b, m[:, 0], v[:, 0]

    xm = x.reshape(t, tile_bt * h * w_, cin)
    a1 = jnp.einsum("tmc,cd->tmd", xm, w1.astype(dt),
                    preferred_element_type=f32)
    y1, m1, v1 = gbn(a1, g1, b1)
    h1 = jax.nn.relu(y1).astype(dt).reshape(t * tile_bt, h, w_, -1)
    cmid = h1.shape[-1]
    pad = jnp.pad(h1, ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((t, tile_bt * h * w_, cmid), f32)
    for dy in range(3):
        for dx in range(3):
            sh = pad[:, dy:dy + h, dx:dx + w_, :].reshape(
                t, tile_bt * h * w_, cmid)
            acc = acc + jnp.einsum("tmc,cd->tmd", sh, w2[dy, dx].astype(dt),
                                   preferred_element_type=f32)
    y2, m2, v2 = gbn(acc, g2, b2)
    h2 = jax.nn.relu(y2).astype(dt)
    a3 = jnp.einsum("tmc,cd->tmd", h2, w3.astype(dt),
                    preferred_element_type=f32)
    y3, m3, v3 = gbn(a3, g3, b3)
    if has_proj:
        wp, gp, bp = weights[9:12]
        ap = jnp.einsum("tmc,cd->tmd", xm, wp.astype(dt),
                        preferred_element_type=f32)
        r, mp, vp = gbn(ap, gp, bp)
    else:
        r = xm.astype(f32)
        mp = vp = jnp.zeros((t, 1), f32)
    out = jax.nn.relu(y3 + r).astype(dt)
    cout = out.shape[-1]
    stats = tuple(jnp.mean(s, axis=0) for s in
                  (m1, v1, m2, v2, m3, v3, mp, vp))
    return out.reshape(n, h, w_, cout), stats


# -----------------------------------------------------------------------------
# forward kernel
# -----------------------------------------------------------------------------

def _fwd_kernel(x_ref, w1_ref, g1_ref, b1_ref, w2_ref, g2_ref, b2_ref,
                w3_ref, g3_ref, b3_ref, wp_ref, gp_ref, bp_ref,
                o_ref, m1_ref, v1_ref, m2_ref, v2_ref, m3_ref, v3_ref,
                mp_ref, vp_ref, *, has_proj: bool, eps: float,
                inv_tiles: float):
    f32 = jnp.float32
    x = x_ref[...]
    bt, h, w, cin = x.shape
    dt = x.dtype
    xm = x.reshape(-1, cin)

    def gbn(a, g, b):
        m = jnp.mean(a, axis=0)
        v = jnp.mean(a * a, axis=0) - m * m
        xh = (a - m) * jax.lax.rsqrt(v + eps)
        return g * xh + b, m, v

    i = pl.program_id(0)

    def acc_stat(ref, val):
        @pl.when(i == 0)
        def _():
            ref[...] = val * inv_tiles

        @pl.when(i > 0)
        def _():
            ref[...] += val * inv_tiles

    a1 = jnp.dot(xm, w1_ref[...], preferred_element_type=f32)
    y1, m1, v1 = gbn(a1, g1_ref[...], b1_ref[...])
    h1 = jax.nn.relu(y1).astype(dt)
    cmid = h1.shape[-1]
    pad = jnp.pad(h1.reshape(bt, h, w, cmid), ((0, 0), (1, 1), (1, 1),
                                               (0, 0)))
    acc = jnp.zeros((bt * h * w, cmid), f32)
    for dy in range(3):
        for dx in range(3):
            acc = acc + jnp.dot(
                pad[:, dy:dy + h, dx:dx + w, :].reshape(-1, cmid),
                w2_ref[dy, dx], preferred_element_type=f32)
    y2, m2, v2 = gbn(acc, g2_ref[...], b2_ref[...])
    h2 = jax.nn.relu(y2).astype(dt)
    a3 = jnp.dot(h2, w3_ref[...], preferred_element_type=f32)
    y3, m3, v3 = gbn(a3, g3_ref[...], b3_ref[...])
    if has_proj:
        ap = jnp.dot(xm, wp_ref[...], preferred_element_type=f32)
        r, mp, vp = gbn(ap, gp_ref[...], bp_ref[...])
        acc_stat(mp_ref, mp)
        acc_stat(vp_ref, vp)
    else:
        r = xm.astype(f32)

        @pl.when(i == 0)
        def _():
            mp_ref[...] = jnp.zeros_like(mp_ref)
            vp_ref[...] = jnp.zeros_like(vp_ref)
    out = jax.nn.relu(y3 + r).astype(dt)
    o_ref[...] = out.reshape(bt, h, w, -1)
    acc_stat(m1_ref, m1)
    acc_stat(v1_ref, v1)
    acc_stat(m2_ref, m2)
    acc_stat(v2_ref, v2)
    acc_stat(m3_ref, m3)
    acc_stat(v3_ref, v3)


# -----------------------------------------------------------------------------
# backward kernel: recompute the interior, then block-transpose it
# -----------------------------------------------------------------------------

def _bwd_kernel(x_ref, g_ref, w1_ref, g1_ref, b1_ref, w2_ref, g2_ref,
                b2_ref, w3_ref, g3_ref, b3_ref, wp_ref, gp_ref, bp_ref,
                dx_ref, dw1_ref, dg1_ref, db1_ref, dw2_ref, dg2_ref,
                db2_ref, dw3_ref, dg3_ref, db3_ref, dwp_ref, dgp_ref,
                dbp_ref, *, has_proj: bool, eps: float):
    f32 = jnp.float32
    x = x_ref[...]
    bt, h, w, cin = x.shape
    dt = x.dtype
    xm = x.reshape(-1, cin)
    gout = g_ref[...].reshape(bt * h * w, -1)
    mcount = f32(bt * h * w)

    i = pl.program_id(0)

    def acc_grad(ref, val):
        @pl.when(i == 0)
        def _():
            ref[...] = val

        @pl.when(i > 0)
        def _():
            ref[...] += val

    def gbn_fwd(a, g, b):
        # identical ops to the forward kernel → identical ghost stats
        m = jnp.mean(a, axis=0)
        v = jnp.mean(a * a, axis=0) - m * m
        s = jax.lax.rsqrt(v + eps)
        xh = (a - m) * s
        return g * xh + b, xh, s

    def gbn_bwd(dy, xh, g, s):
        dg = jnp.sum(dy * xh, axis=0)
        db = jnp.sum(dy, axis=0)
        dxh = dy * g
        da = s * (dxh - jnp.sum(dxh, axis=0) / mcount
                  - xh * (jnp.sum(dxh * xh, axis=0) / mcount))
        return da, dg, db

    # ---- recompute the forward interior (VMEM-resident, bf16 storage)
    a1 = jnp.dot(xm, w1_ref[...], preferred_element_type=f32)
    y1, xh1, s1 = gbn_fwd(a1, g1_ref[...], b1_ref[...])
    h1 = jax.nn.relu(y1).astype(dt)
    cmid = h1.shape[-1]
    pad1 = jnp.pad(h1.reshape(bt, h, w, cmid), ((0, 0), (1, 1), (1, 1),
                                                (0, 0)))
    acc2 = jnp.zeros((bt * h * w, cmid), f32)
    for dy in range(3):
        for dx in range(3):
            acc2 = acc2 + jnp.dot(
                pad1[:, dy:dy + h, dx:dx + w, :].reshape(-1, cmid),
                w2_ref[dy, dx], preferred_element_type=f32)
    y2, xh2, s2 = gbn_fwd(acc2, g2_ref[...], b2_ref[...])
    h2 = jax.nn.relu(y2).astype(dt)
    a3 = jnp.dot(h2, w3_ref[...], preferred_element_type=f32)
    y3, xh3, s3 = gbn_fwd(a3, g3_ref[...], b3_ref[...])
    if has_proj:
        ap = jnp.dot(xm, wp_ref[...], preferred_element_type=f32)
        r, xhp, sp = gbn_fwd(ap, gp_ref[...], bp_ref[...])
    else:
        r = xm.astype(f32)

    # ---- transpose the block, top down
    # final relu: sign of the recomputed pre-activation
    gz = jnp.where(y3 + r > 0, gout.astype(f32), 0.0)

    # BN3 + conv3 (1x1)
    da3, dg3, db3 = gbn_bwd(gz, xh3, g3_ref[...], s3)
    da3b = da3.astype(dt)
    acc_grad(dg3_ref, dg3)
    acc_grad(db3_ref, db3)
    acc_grad(dw3_ref, jnp.dot(h2.T, da3b, preferred_element_type=f32))
    dh2 = jnp.dot(da3b, w3_ref[...].T, preferred_element_type=f32)

    # relu2 + BN2
    dz2 = jnp.where(y2 > 0, dh2, 0.0)
    da2, dg2, db2 = gbn_bwd(dz2, xh2, g2_ref[...], s2)
    da2b = da2.astype(dt)
    acc_grad(dg2_ref, dg2)
    acc_grad(db2_ref, db2)

    # conv3x3 transpose: wgrad reuses the forward's shifted h1 views;
    # dgrad uses the mirrored offsets (2-dy, 2-dx) on padded da2
    # each dw2 tap accumulates straight into its (dy,dx) sub-ref: a
    # static-index .at[].set emits lax.scatter (unlowerable in Mosaic),
    # and stacking all 9 taps keeps ~3x the full (3,3,cmid,cmid) f32
    # live on the kernel stack — 28 MB at cmid=512, past the 16 MB
    # scoped-VMEM limit (measured on first Mosaic compile)
    pad2 = jnp.pad(da2b.reshape(bt, h, w, cmid), ((0, 0), (1, 1), (1, 1),
                                                  (0, 0)))
    dh1 = jnp.zeros((bt * h * w, cmid), f32)
    for dy in range(3):
        for dx in range(3):
            h1s = pad1[:, dy:dy + h, dx:dx + w, :].reshape(-1, cmid)
            acc_grad(dw2_ref.at[dy, dx],
                     jnp.dot(h1s.T, da2b, preferred_element_type=f32))
            g2s = pad2[:, 2 - dy:2 - dy + h, 2 - dx:2 - dx + w, :] \
                .reshape(-1, cmid)
            dh1 = dh1 + jnp.dot(g2s, w2_ref[dy, dx].T,
                                preferred_element_type=f32)

    # relu1 + BN1 + conv1 (1x1)
    dz1 = jnp.where(y1 > 0, dh1, 0.0)
    da1, dg1, db1 = gbn_bwd(dz1, xh1, g1_ref[...], s1)
    da1b = da1.astype(dt)
    acc_grad(dg1_ref, dg1)
    acc_grad(db1_ref, db1)
    acc_grad(dw1_ref, jnp.dot(xm.T, da1b, preferred_element_type=f32))
    dx = jnp.dot(da1b, w1_ref[...].T, preferred_element_type=f32)

    # residual path
    if has_proj:
        dap, dgp, dbp = gbn_bwd(gz, xhp, gp_ref[...], sp)
        dapb = dap.astype(dt)
        acc_grad(dgp_ref, dgp)
        acc_grad(dbp_ref, dbp)
        acc_grad(dwp_ref, jnp.dot(xm.T, dapb, preferred_element_type=f32))
        dx = dx + jnp.dot(dapb, wp_ref[...].T, preferred_element_type=f32)
    else:
        dx = dx + gz

        @pl.when(i == 0)
        def _():
            dwp_ref[...] = jnp.zeros_like(dwp_ref)
            dgp_ref[...] = jnp.zeros_like(dgp_ref)
            dbp_ref[...] = jnp.zeros_like(dbp_ref)
    dx_ref[...] = dx.astype(dt).reshape(bt, h, w, cin)


# -----------------------------------------------------------------------------
# pallas_call plumbing + custom_vjp
# -----------------------------------------------------------------------------

def _padded_weights(weights: tuple, dt) -> tuple[list, bool]:
    has_proj = len(weights) == 12
    w = list(weights)
    conv_idx = {0, 3, 6, 9}
    out = [wi.astype(dt) if k in conv_idx else wi.astype(jnp.float32)
           for k, wi in enumerate(w)]
    if not has_proj:
        # dead operands keep the kernel signature static (as the eval
        # kernel does)
        out += [jnp.zeros((1, 1), dt), jnp.zeros((1,), jnp.float32),
                jnp.zeros((1,), jnp.float32)]
    return out, has_proj


def _full_spec(shape):
    return pl.BlockSpec(shape, lambda i: (0,) * len(shape))


def _pallas_fwd(x, weights, tile_bt, eps):
    n, h, w_, cin = x.shape
    wlist, has_proj = _padded_weights(weights, x.dtype)
    cmid = wlist[0].shape[-1]
    cout = wlist[6].shape[-1]
    n_tiles = n // tile_bt
    cp = wlist[9].shape[-1] if has_proj else 1

    in_specs = [pl.BlockSpec((tile_bt, h, w_, cin), lambda i: (i, 0, 0, 0))]
    in_specs += [_full_spec(wi.shape) for wi in wlist]
    stat_shapes = [cmid, cmid, cmid, cmid, cout, cout, cp, cp]
    out_shapes = [jax.ShapeDtypeStruct((n, h, w_, cout), x.dtype)] + \
        [jax.ShapeDtypeStruct((c,), jnp.float32) for c in stat_shapes]
    out_specs = [pl.BlockSpec((tile_bt, h, w_, cout),
                              lambda i: (i, 0, 0, 0))] + \
        [_full_spec((c,)) for c in stat_shapes]

    res = pl.pallas_call(
        partial(_fwd_kernel, has_proj=has_proj, eps=eps,
                inv_tiles=1.0 / n_tiles),
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(x, *wlist)
    return res[0], tuple(res[1:])


def _pallas_bwd(x, g, weights, tile_bt, eps):
    n, h, w_, cin = x.shape
    wlist, has_proj = _padded_weights(weights, x.dtype)
    cmid = wlist[0].shape[-1]
    cout = wlist[6].shape[-1]
    n_tiles = n // tile_bt
    cp = wlist[9].shape[0] if has_proj else 1
    cpo = wlist[9].shape[-1] if has_proj else 1

    tile = lambda c: pl.BlockSpec((tile_bt, h, w_, c),  # noqa: E731
                                  lambda i: (i, 0, 0, 0))
    in_specs = [tile(cin), tile(cout)]
    in_specs += [_full_spec(wi.shape) for wi in wlist]
    f32 = jnp.float32
    grad_shapes = [(cin, cmid), (cmid,), (cmid,),          # w1, g1, b1
                   (3, 3, cmid, cmid), (cmid,), (cmid,),   # w2, g2, b2
                   (cmid, cout), (cout,), (cout,),         # w3, g3, b3
                   (cp, cpo), (cpo,), (cpo,)]              # wp, gp, bp
    out_shapes = [jax.ShapeDtypeStruct((n, h, w_, cin), x.dtype)] + \
        [jax.ShapeDtypeStruct(s, f32) for s in grad_shapes]
    out_specs = [tile(cin)] + [_full_spec(s) for s in grad_shapes]

    res = pl.pallas_call(
        partial(_bwd_kernel, has_proj=has_proj, eps=eps),
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(x, g, *wlist)
    dx, grads = res[0], tuple(res[1:])
    if not has_proj:
        grads = grads[:9]
    return dx, grads


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _fused(tile_bt, eps, x, *weights):
    out, stats = _pallas_fwd(x, weights, tile_bt, eps)
    return out, stats


def _fused_fwd(tile_bt, eps, x, *weights):
    out, stats = _pallas_fwd(x, weights, tile_bt, eps)
    return (out, stats), (x, weights)


def _fused_bwd(tile_bt, eps, residuals, cts):
    # cts[1] (the ghost-stats cotangent) is deliberately dropped: the
    # stats feed the running-average EMA only, which is stop-gradient in
    # flax's BatchNorm as well.
    x, weights = residuals
    ct_out = cts[0]
    dx, grads = _pallas_bwd(x, ct_out.astype(x.dtype), weights, tile_bt,
                            eps)
    dweights = tuple(gi.astype(wi.dtype) for gi, wi in zip(grads, weights))
    return (dx,) + dweights


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_bottleneck_train(x: jax.Array, params: dict, *,
                           tile_bt: Optional[int] = None,
                           eps: float = 1e-5) -> tuple[jax.Array, dict]:
    """The fused ghost-BN training block: (out, ghost_stats_tree).

    ``params`` is one flax BottleneckBlock subtree; stride-1 blocks only
    (callers route strided blocks to XLA). ghost_stats_tree holds the
    tile-averaged batch moments per BatchNorm, shaped for the running
    EMA update."""
    weights = block_weights(params)
    has_proj = len(weights) == 12
    n, h, w_, cin = x.shape
    cmid = weights[0].shape[-1]
    cout = weights[6].shape[-1]
    if not has_proj and cin != cout:
        raise ValueError(f"Cin {cin} != Cout {cout} needs a projection")
    if tile_bt is None:
        tile_bt = default_tile_bt(n, h, w_, cin, cmid, cout)
    elif n % tile_bt:
        raise ValueError(f"tile_bt {tile_bt} must divide batch {n}")
    out, stats = _fused(tile_bt, eps, x, *weights)
    return out, stats_to_tree(stats, has_proj)
