"""TPU kernel library (Pallas) + SPMD collective ops.

The compute-path hot ops the reference delegates to external frameworks
(SURVEY.md §2.5 rows 5-6 — absent upstream, required for the TPU build):

- :mod:`flash_attention` — fused causal attention, Pallas MXU kernel,
  online-softmax, custom VJP with Pallas backward kernels.
- :mod:`ring_attention` — sequence/context-parallel attention over the
  "sequence" mesh axis: K/V chunks rotate the ICI ring via ppermute while
  each step's block attention overlaps with the transfer (XLA schedules).
"""

from .flash_attention import flash_attention  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
