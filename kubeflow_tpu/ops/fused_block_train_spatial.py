"""Spatially-tiled fused ResNet bottleneck (TRAINING): ghost-BN over
batch x row-strip tiles — the variant that re-admits the stage-1/2
blocks whose ONE-IMAGE working set busts the VMEM budget of the
batch-tiled kernel (ops/fused_block_train.py; PERF.md round 5 "spatial
halo tiling is the path back to the 35% cut").

Tiling: the image's H rows split into strips of ``tile_h`` rows; each
kernel instance processes (tile_bt images x one strip) with a 1-row halo
on each side so the 3x3 conv is exact at strip seams (zero rows at image
edges — SAME-conv semantics). The halo is read WITHOUT any relayout
pass: x is passed three times with different BlockSpecs — a 1-row "top"
window at row ``max(s·th−1, 0)``, the ``th``-row body, and a 1-row
"bottom" window at ``min((s+1)·th, h−1)`` — giving overlapping reads
through non-overlapping block shapes (index maps address 1-row blocks).
At image edges the clamped windows read a REAL row, which is harmless:
the edge mask zeroes h1 there (forward) and the masked relu zeroes the
gradient flowing through it (backward), reproducing SAME-conv zero
padding exactly. The backward returns seam gradients as two THIN row
arrays (S rows each) that XLA scatter-adds into dx — total layout
overhead is a few rows, not whole-tensor passes. (``make_strips``
remains as the executable spec's layout helper.)

**Ghost-BN semantics (per batch x strip ghost):** statistics are
computed over the strip's INTERIOR samples (tile_bt*tile_h*W per
channel); halo rows are normalized with those interior stats (they only
feed the 3x3). In the backward, halo samples contribute to dgamma/dbeta
and to the stat-correction sums, but the 1/N divisor is the interior
count and the correction applies to interior rows only — exactly
``jax.grad`` of the executable spec below, which is the tested
definition of the semantics. Running stats are EMA-updated from the
ghost-averaged moments, same contract as the batch-tiled kernel.

The pure-jnp `reference_bottleneck_train_spatial` is the executable
spec both kernels are tested against (values AND `jax.grad` gradients).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .fused_block_train import (VMEM_BUDGET_BYTES, _compiler_params,
                                _interpret, _padded_weights,
                                _per_image_bytes, block_weights,
                                stats_to_tree)

__all__ = ["fused_bottleneck_train_spatial",
           "reference_bottleneck_train_spatial", "default_tile_h",
           "fits_vmem_budget_spatial", "make_strips"]


def _strip_bytes(tile_h: int, w: int, cin: int, cmid: int,
                 cout: int) -> int:
    """Working-set estimate per image for one haloed strip."""
    return _per_image_bytes(tile_h + 2, w, cin, cmid, cout)


def fits_vmem_budget_spatial(tile_h: int, w: int, cin: int, cmid: int,
                             cout: int) -> bool:
    return _strip_bytes(tile_h, w, cin, cmid, cout) <= VMEM_BUDGET_BYTES


def default_tile_h(h: int, w: int, cin: int, cmid: int,
                   cout: int) -> Optional[int]:
    """Largest strip height dividing h whose haloed working set fits the
    budget at tile_bt=1; None when even a 1-row strip cannot fit."""
    for th in range(h, 0, -1):
        if h % th == 0 and fits_vmem_budget_spatial(th, w, cin, cmid,
                                                    cout):
            return th
    return None


# -----------------------------------------------------------------------------
# strip layout (XLA side)
# -----------------------------------------------------------------------------

def make_strips(x: jax.Array, tile_h: int) -> jax.Array:
    """(n, h, w, c) -> (S, n, tile_h+2, w, c) haloed row strips; the
    halo is the neighbor strip's edge row, zeros at image edges."""
    n, h, w, c = x.shape
    s_count = h // tile_h
    xp = jnp.pad(x, ((0, 0), (1, 1), (0, 0), (0, 0)))
    return jnp.stack([xp[:, s * tile_h:s * tile_h + tile_h + 2]
                      for s in range(s_count)])


# -----------------------------------------------------------------------------
# executable spec (pure jnp, differentiable)
# -----------------------------------------------------------------------------

def _edge_mask(bt: int, th2: int, w: int, is_top, is_bottom):
    """1 everywhere except image-edge halo rows (those are SAME-conv
    ZERO padding of h1 — a BN with bias would otherwise turn the zero
    INPUT rows into nonzero h1 padding). Accepts python bools (spec) or
    traced predicates (kernel, from program_id)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (bt, th2, w, 1), 1)
    top = jnp.logical_and(rows == 0, is_top)
    bot = jnp.logical_and(rows == th2 - 1, is_bottom)
    return 1.0 - jnp.logical_or(top, bot).astype(jnp.float32)


def _strip_forward(xt: jax.Array, weights: tuple, eps: float,
                   is_top: bool, is_bottom: bool):
    """One (tile_bt, tile_h+2, w, cin) haloed strip through the block.
    Returns (out (tile_bt, tile_h, w, cout), ghost stats tuple). Pure
    jnp — the kernels mirror these ops exactly."""
    has_proj = len(weights) == 12
    w1, g1, b1, w2, g2, b2, w3, g3, b3 = weights[:9]
    f32 = jnp.float32
    dt = xt.dtype
    bt, th2, w_, cin = xt.shape
    th = th2 - 2
    cmid = w1.shape[-1]

    xm = xt.reshape(-1, cin)
    a1 = jnp.dot(xm, w1.astype(dt), preferred_element_type=f32)
    a1i = a1.reshape(bt, th2, w_, cmid)[:, 1:th + 1].reshape(-1, cmid)
    m1 = jnp.mean(a1i, axis=0)
    v1 = jnp.mean(a1i * a1i, axis=0) - m1 * m1
    h1 = jax.nn.relu(g1 * ((a1 - m1) * jax.lax.rsqrt(v1 + eps)) + b1) \
        .astype(dt).reshape(bt, th2, w_, cmid)
    h1 = (h1 * _edge_mask(bt, th2, w_, is_top, is_bottom)).astype(dt)

    pad = jnp.pad(h1, ((0, 0), (0, 0), (1, 1), (0, 0)))
    acc = jnp.zeros((bt * th * w_, cmid), f32)
    for dy in range(3):
        for dx in range(3):
            acc = acc + jnp.dot(
                pad[:, dy:dy + th, dx:dx + w_, :].reshape(-1, cmid),
                w2[dy, dx].astype(dt), preferred_element_type=f32)
    m2 = jnp.mean(acc, axis=0)
    v2 = jnp.mean(acc * acc, axis=0) - m2 * m2
    h2 = jax.nn.relu(g2 * ((acc - m2) * jax.lax.rsqrt(v2 + eps)) + b2) \
        .astype(dt)
    a3 = jnp.dot(h2, w3.astype(dt), preferred_element_type=f32)
    m3 = jnp.mean(a3, axis=0)
    v3 = jnp.mean(a3 * a3, axis=0) - m3 * m3
    y3 = g3 * ((a3 - m3) * jax.lax.rsqrt(v3 + eps)) + b3

    xi = xt[:, 1:th + 1].reshape(-1, cin)
    if has_proj:
        wp, gp, bp = weights[9:12]
        ap = jnp.dot(xi, wp.astype(dt), preferred_element_type=f32)
        mp = jnp.mean(ap, axis=0)
        vp = jnp.mean(ap * ap, axis=0) - mp * mp
        r = gp * ((ap - mp) * jax.lax.rsqrt(vp + eps)) + bp
    else:
        r = xi.astype(f32)
        mp = vp = jnp.zeros((1,), f32)
    out = jax.nn.relu(y3 + r).astype(dt).reshape(bt, th, w_, -1)
    return out, (m1, v1, m2, v2, m3, v3, mp, vp)


def reference_bottleneck_train_spatial(x: jax.Array, weights: tuple, *,
                                       tile_bt: int, tile_h: int,
                                       eps: float = 1e-5):
    """Ghost-BN bottleneck forward tiled exactly like the spatial kernel
    grid ((n//tile_bt) x (h//tile_h) ghosts). Differentiable: jax.grad
    of this is the golden gradient for the Pallas backward."""
    n, h, w_, cin = x.shape
    t_count, s_count = n // tile_bt, h // tile_h
    xs = make_strips(x, tile_h)
    out_rows = []
    stats = None
    for s in range(s_count):
        tiles = []
        for t in range(t_count):
            xt = xs[s, t * tile_bt:(t + 1) * tile_bt]
            o, st = _strip_forward(xt, weights, eps, is_top=(s == 0),
                                   is_bottom=(s == s_count - 1))
            tiles.append(o)
            stats = st if stats is None else \
                tuple(a + b for a, b in zip(stats, st))
        out_rows.append(jnp.concatenate(tiles, axis=0))
    out = jnp.concatenate(out_rows, axis=1)
    inv = 1.0 / (t_count * s_count)
    return out, tuple(s * inv for s in stats)


# -----------------------------------------------------------------------------
# forward kernel
# -----------------------------------------------------------------------------

def _fwd_kernel(xt_ref, xb_ref, xbot_ref, w1_ref, g1_ref, b1_ref,
                w2_ref, g2_ref, b2_ref, w3_ref, g3_ref, b3_ref,
                wp_ref, gp_ref, bp_ref,
                o_ref, m1_ref, v1_ref, m2_ref, v2_ref, m3_ref, v3_ref,
                mp_ref, vp_ref, *, has_proj: bool, eps: float,
                inv_ghosts: float, s_count: int):
    f32 = jnp.float32
    # haloed strip assembled from the three windows (top row, body,
    # bottom row — overlapping READS via per-row block indices)
    xt = jnp.concatenate([xt_ref[...], xb_ref[...], xbot_ref[...]],
                         axis=1)        # (bt, th+2, w, cin)
    bt, th2, w, cin = xt.shape
    th = th2 - 2
    dt = xt.dtype
    xm = xt.reshape(-1, cin)

    s_id = pl.program_id(1)
    first = (pl.program_id(0) == 0) & (s_id == 0)
    emask = _edge_mask(bt, th2, w, s_id == 0, s_id == s_count - 1)

    def acc_stat(ref, val):
        @pl.when(first)
        def _():
            ref[...] = val * inv_ghosts

        @pl.when(jnp.logical_not(first))
        def _():
            ref[...] += val * inv_ghosts

    def interior_stats(a):
        ai = a.reshape(bt, th2, w, -1)[:, 1:th + 1] \
            .reshape(-1, a.shape[-1])
        m = jnp.mean(ai, axis=0)
        v = jnp.mean(ai * ai, axis=0) - m * m
        return m, v

    a1 = jnp.dot(xm, w1_ref[...], preferred_element_type=f32)
    m1, v1 = interior_stats(a1)
    h1 = jax.nn.relu(g1_ref[...] * ((a1 - m1)
                                    * jax.lax.rsqrt(v1 + eps))
                     + b1_ref[...]).astype(dt)
    cmid = h1.shape[-1]
    h1 = (h1.reshape(bt, th2, w, cmid) * emask).astype(dt)
    pad = jnp.pad(h1, ((0, 0), (0, 0), (1, 1), (0, 0)))
    acc = jnp.zeros((bt * th * w, cmid), f32)
    for dy in range(3):
        for dx in range(3):
            acc = acc + jnp.dot(
                pad[:, dy:dy + th, dx:dx + w, :].reshape(-1, cmid),
                w2_ref[dy, dx], preferred_element_type=f32)
    m2 = jnp.mean(acc, axis=0)
    v2 = jnp.mean(acc * acc, axis=0) - m2 * m2
    h2 = jax.nn.relu(g2_ref[...] * ((acc - m2)
                                    * jax.lax.rsqrt(v2 + eps))
                     + b2_ref[...]).astype(dt)
    a3 = jnp.dot(h2, w3_ref[...], preferred_element_type=f32)
    m3 = jnp.mean(a3, axis=0)
    v3 = jnp.mean(a3 * a3, axis=0) - m3 * m3
    y3 = g3_ref[...] * ((a3 - m3) * jax.lax.rsqrt(v3 + eps)) + b3_ref[...]

    xi = xt[:, 1:th + 1].reshape(-1, cin)
    if has_proj:
        ap = jnp.dot(xi, wp_ref[...], preferred_element_type=f32)
        mp = jnp.mean(ap, axis=0)
        vp = jnp.mean(ap * ap, axis=0) - mp * mp
        r = gp_ref[...] * ((ap - mp) * jax.lax.rsqrt(vp + eps)) \
            + bp_ref[...]
        acc_stat(mp_ref, mp)
        acc_stat(vp_ref, vp)
    else:
        r = xi.astype(f32)

        @pl.when(first)
        def _():
            mp_ref[...] = jnp.zeros_like(mp_ref)
            vp_ref[...] = jnp.zeros_like(vp_ref)
    o_ref[...] = jax.nn.relu(y3 + r).astype(dt).reshape(bt, th, w, -1)
    acc_stat(m1_ref, m1)
    acc_stat(v1_ref, v1)
    acc_stat(m2_ref, m2)
    acc_stat(v2_ref, v2)
    acc_stat(m3_ref, m3)
    acc_stat(v3_ref, v3)


# -----------------------------------------------------------------------------
# backward kernel
# -----------------------------------------------------------------------------

def _bwd_kernel(xt_ref, xb_ref, xbot_ref, g_ref, w1_ref, g1_ref, b1_ref,
                w2_ref, g2_ref, b2_ref, w3_ref, g3_ref, b3_ref,
                wp_ref, gp_ref, bp_ref,
                dx_ref, dxt_ref, dxbot_ref, dw1_ref, dg1_ref, db1_ref,
                dw2_ref, dg2_ref, db2_ref, dw3_ref, dg3_ref, db3_ref,
                dwp_ref, dgp_ref, dbp_ref, *, has_proj: bool, eps: float,
                s_count: int):
    f32 = jnp.float32
    xt = jnp.concatenate([xt_ref[...], xb_ref[...], xbot_ref[...]],
                         axis=1)        # (bt, th+2, w, cin)
    bt, th2, w, cin = xt.shape
    th = th2 - 2
    dt = xt.dtype
    xm = xt.reshape(-1, cin)
    gout = g_ref[...].reshape(bt * th * w, -1)
    n_int = f32(bt * th * w)

    s_id = pl.program_id(1)
    first = (pl.program_id(0) == 0) & (s_id == 0)
    emask = _edge_mask(bt, th2, w, s_id == 0, s_id == s_count - 1) \
        .reshape(-1, 1)

    def acc_grad(ref, val):
        @pl.when(first)
        def _():
            ref[...] = val

        @pl.when(jnp.logical_not(first))
        def _():
            ref[...] += val

    # interior-row mask over the haloed sample axis, shape (M_halo, 1).
    # astype BEFORE reshape: Mosaic cannot reshape i1 (mask) vectors —
    # first TPU compile failed on tpu.reshape of vector<...xi1>
    rows = jax.lax.broadcasted_iota(jnp.int32, (bt, th2, w, 1), 1)
    imask = ((rows >= 1) & (rows <= th)).astype(f32).reshape(-1, 1)

    def gbn_bwd_int(dy_, xh, g, s):
        # all samples ARE interior (BN2/BN3/proj): standard ghost-BN bwd
        dg = jnp.sum(dy_ * xh, axis=0)
        db = jnp.sum(dy_, axis=0)
        dxh = dy_ * g
        da = s * (dxh - jnp.sum(dxh, axis=0) / n_int
                  - xh * (jnp.sum(dxh * xh, axis=0) / n_int))
        return da, dg, db

    # ---- recompute the forward interior (all haloed rows)
    a1 = jnp.dot(xm, w1_ref[...], preferred_element_type=f32)
    a1i = a1 * imask
    m1 = jnp.sum(a1i, axis=0) / n_int
    v1 = jnp.sum(a1i * a1, axis=0) / n_int - m1 * m1
    s1 = jax.lax.rsqrt(v1 + eps)
    xh1 = (a1 - m1) * s1
    y1 = g1_ref[...] * xh1 + b1_ref[...]
    h1 = (jax.nn.relu(y1) * emask).astype(dt)
    cmid = h1.shape[-1]
    pad1 = jnp.pad(h1.reshape(bt, th2, w, cmid),
                   ((0, 0), (0, 0), (1, 1), (0, 0)))
    acc2 = jnp.zeros((bt * th * w, cmid), f32)
    for dy in range(3):
        for dx in range(3):
            acc2 = acc2 + jnp.dot(
                pad1[:, dy:dy + th, dx:dx + w, :].reshape(-1, cmid),
                w2_ref[dy, dx], preferred_element_type=f32)
    m2 = jnp.mean(acc2, axis=0)
    v2 = jnp.mean(acc2 * acc2, axis=0) - m2 * m2
    s2 = jax.lax.rsqrt(v2 + eps)
    xh2 = (acc2 - m2) * s2
    y2 = g2_ref[...] * xh2 + b2_ref[...]
    h2 = jax.nn.relu(y2).astype(dt)
    a3 = jnp.dot(h2, w3_ref[...], preferred_element_type=f32)
    m3 = jnp.mean(a3, axis=0)
    v3 = jnp.mean(a3 * a3, axis=0) - m3 * m3
    s3 = jax.lax.rsqrt(v3 + eps)
    xh3 = (a3 - m3) * s3
    y3 = g3_ref[...] * xh3 + b3_ref[...]
    xi = xt[:, 1:th + 1].reshape(-1, cin)
    if has_proj:
        ap = jnp.dot(xi, wp_ref[...], preferred_element_type=f32)
        mp = jnp.mean(ap, axis=0)
        vp = jnp.mean(ap * ap, axis=0) - mp * mp
        sp = jax.lax.rsqrt(vp + eps)
        xhp = (ap - mp) * sp
        r = gp_ref[...] * xhp + bp_ref[...]
    else:
        r = xi.astype(f32)

    # ---- transpose the block, top down
    gz = jnp.where(y3 + r > 0, gout.astype(f32), 0.0)

    da3, dg3, db3 = gbn_bwd_int(gz, xh3, g3_ref[...], s3)
    da3b = da3.astype(dt)
    acc_grad(dg3_ref, dg3)
    acc_grad(db3_ref, db3)
    acc_grad(dw3_ref, jnp.dot(h2.T, da3b, preferred_element_type=f32))
    dh2 = jnp.dot(da3b, w3_ref[...].T, preferred_element_type=f32)

    dz2 = jnp.where(y2 > 0, dh2, 0.0)
    da2, dg2, db2 = gbn_bwd_int(dz2, xh2, g2_ref[...], s2)
    da2b = da2.astype(dt)
    acc_grad(dg2_ref, dg2)
    acc_grad(db2_ref, db2)

    # conv3x3 transpose: wgrad reuses the forward's shifted haloed-h1
    # views; dgrad scatters into the HALOED h1 rows via the mirrored
    # offsets. Rows pad (2,2): the forward used the halo (no row pad),
    # so output row q maps to haloed h1 row r = q + dy. Cols pad (1,1):
    # the forward zero-padded columns exactly like the batch-tiled
    # kernel.
    # each dw2 tap accumulates straight into its (dy,dx) sub-ref: a
    # static-index .at[].set emits lax.scatter (unlowerable in Mosaic),
    # and stacking all 9 taps keeps ~3x the full (3,3,cmid,cmid) f32
    # live on the kernel stack — past the 16 MB scoped-VMEM limit
    pad2 = jnp.pad(da2b.reshape(bt, th, w, cmid),
                   ((0, 0), (2, 2), (1, 1), (0, 0)))
    dh1 = jnp.zeros((bt * th2 * w, cmid), f32)
    for dy in range(3):
        for dx in range(3):
            h1s = pad1[:, dy:dy + th, dx:dx + w, :].reshape(-1, cmid)
            acc_grad(dw2_ref.at[dy, dx],
                     jnp.dot(h1s.T, da2b, preferred_element_type=f32))
            g2s = pad2[:, 2 - dy:2 - dy + th2, 2 - dx:2 - dx + w, :] \
                .reshape(-1, cmid)
            dh1 = dh1 + jnp.dot(g2s, w2_ref[dy, dx].T,
                                preferred_element_type=f32)

    # BN1 backward with halo: halo samples contribute to the sums and to
    # dgamma/dbeta, the 1/N divisor is the interior count, and the
    # stat-correction applies to interior rows only (jax.grad of the
    # spec — see module docstring)
    dz1 = jnp.where(y1 > 0, dh1 * emask, 0.0)
    dg1 = jnp.sum(dz1 * xh1, axis=0)
    db1 = jnp.sum(dz1, axis=0)
    dxh1 = dz1 * g1_ref[...]
    corr = (jnp.sum(dxh1, axis=0) / n_int
            + xh1 * (jnp.sum(dxh1 * xh1, axis=0) / n_int))
    da1 = s1 * (dxh1 - imask * corr)
    da1b = da1.astype(dt)
    acc_grad(dg1_ref, dg1)
    acc_grad(db1_ref, db1)
    acc_grad(dw1_ref, jnp.dot(xm.T, da1b, preferred_element_type=f32))
    dx = jnp.dot(da1b, w1_ref[...].T, preferred_element_type=f32)
    dx = dx.reshape(bt, th2, w, cin)

    # residual path lands on interior rows only
    if has_proj:
        dap, dgp, dbp = gbn_bwd_int(gz, xhp, gp_ref[...], sp)
        dapb = dap.astype(dt)
        acc_grad(dgp_ref, dgp)
        acc_grad(dbp_ref, dbp)
        acc_grad(dwp_ref, jnp.dot(xi.T, dapb, preferred_element_type=f32))
        dres = jnp.dot(dapb, wp_ref[...].T, preferred_element_type=f32)
    else:
        dres = gz

        @pl.when(first)
        def _():
            dwp_ref[...] = jnp.zeros_like(dwp_ref)
            dgp_ref[...] = jnp.zeros_like(dgp_ref)
            dbp_ref[...] = jnp.zeros_like(dbp_ref)
    # pad, don't .at[slice].add — scatter-add is unlowerable in Mosaic
    dx = dx + jnp.pad(dres.reshape(bt, th, w, cin),
                      ((0, 0), (1, 1), (0, 0), (0, 0)))
    dx = dx.astype(dt)
    # seam gradients go out as thin per-strip rows (XLA scatter-adds
    # them into the neighbor rows); the body writes straight into dx
    dxt_ref[...] = dx[:, :1]
    dx_ref[...] = dx[:, 1:th + 1]
    dxbot_ref[...] = dx[:, th + 1:]


# -----------------------------------------------------------------------------
# pallas_call plumbing + custom_vjp
# -----------------------------------------------------------------------------

def _full_spec(shape):
    return pl.BlockSpec(shape, lambda t, s: (0,) * len(shape))


def _x_window_specs(tile_bt, tile_h, w_, cin, h):
    """The three overlapping read windows of x: 1-row top halo at
    max(s·th−1, 0), th-row body at s·th, 1-row bottom halo at
    min((s+1)·th, h−1). Clamped indices read a real row at image edges;
    the kernels' edge masks make its content irrelevant."""
    top = pl.BlockSpec(
        (tile_bt, 1, w_, cin),
        lambda t, s: (t, jnp.maximum(s * tile_h - 1, 0), 0, 0))
    body = pl.BlockSpec((tile_bt, tile_h, w_, cin),
                        lambda t, s: (t, s, 0, 0))
    bot = pl.BlockSpec(
        (tile_bt, 1, w_, cin),
        lambda t, s: (t, jnp.minimum((s + 1) * tile_h, h - 1), 0, 0))
    return [top, body, bot]


def _pallas_fwd(x, weights, tile_bt, tile_h, eps):
    n, h, w_, cin = x.shape
    wlist, has_proj = _padded_weights(weights, x.dtype)
    cmid = wlist[0].shape[-1]
    cout = wlist[6].shape[-1]
    t_count, s_count = n // tile_bt, h // tile_h
    cp = wlist[9].shape[-1] if has_proj else 1

    in_specs = _x_window_specs(tile_bt, tile_h, w_, cin, h)
    in_specs += [_full_spec(wi.shape) for wi in wlist]
    stat_shapes = [cmid, cmid, cmid, cmid, cout, cout, cp, cp]
    out_shapes = [jax.ShapeDtypeStruct((n, h, w_, cout), x.dtype)] + \
        [jax.ShapeDtypeStruct((c,), jnp.float32) for c in stat_shapes]
    out_specs = [pl.BlockSpec((tile_bt, tile_h, w_, cout),
                              lambda t, s: (t, s, 0, 0))] + \
        [_full_spec((c,)) for c in stat_shapes]

    res = pl.pallas_call(
        partial(_fwd_kernel, has_proj=has_proj, eps=eps,
                inv_ghosts=1.0 / (t_count * s_count), s_count=s_count),
        grid=(t_count, s_count),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(x, x, x, *wlist)
    return res[0], tuple(res[1:])


def _pallas_bwd(x, g, weights, tile_bt, tile_h, eps):
    n, h, w_, cin = x.shape
    wlist, has_proj = _padded_weights(weights, x.dtype)
    cmid = wlist[0].shape[-1]
    cout = wlist[6].shape[-1]
    t_count, s_count = n // tile_bt, h // tile_h
    cp = wlist[9].shape[0] if has_proj else 1
    cpo = wlist[9].shape[-1] if has_proj else 1

    in_specs = _x_window_specs(tile_bt, tile_h, w_, cin, h)
    in_specs += [pl.BlockSpec((tile_bt, tile_h, w_, cout),
                              lambda t, s: (t, s, 0, 0))]
    in_specs += [_full_spec(wi.shape) for wi in wlist]
    f32 = jnp.float32
    grad_shapes = [(cin, cmid), (cmid,), (cmid,),
                   (3, 3, cmid, cmid), (cmid,), (cmid,),
                   (cmid, cout), (cout,), (cout,),
                   (cp, cpo), (cpo,), (cpo,)]
    # dx body writes straight into (n, h, w, cin); the two seam-row
    # contributions come back as thin (n, S, w, cin) arrays
    out_shapes = [jax.ShapeDtypeStruct((n, h, w_, cin), x.dtype),
                  jax.ShapeDtypeStruct((n, s_count, w_, cin), x.dtype),
                  jax.ShapeDtypeStruct((n, s_count, w_, cin), x.dtype)] + \
        [jax.ShapeDtypeStruct(s, f32) for s in grad_shapes]
    out_specs = [pl.BlockSpec((tile_bt, tile_h, w_, cin),
                              lambda t, s: (t, s, 0, 0)),
                 pl.BlockSpec((tile_bt, 1, w_, cin),
                              lambda t, s: (t, s, 0, 0)),
                 pl.BlockSpec((tile_bt, 1, w_, cin),
                              lambda t, s: (t, s, 0, 0))] + \
        [_full_spec(s) for s in grad_shapes]

    res = pl.pallas_call(
        partial(_bwd_kernel, has_proj=has_proj, eps=eps,
                s_count=s_count),
        grid=(t_count, s_count),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(x, x, x, g, *wlist)
    dx, dx_top, dx_bot = res[0], res[1], res[2]
    # scatter the seam rows into the neighbor strips: strip s's top halo
    # is global row s·th−1 (s ≥ 1), its bottom halo row (s+1)·th
    # (s ≤ S−2); the image-edge contributions are zero by the masks
    if s_count > 1:
        th = tile_h
        dx = dx.at[:, th - 1:h - 1:th].add(dx_top[:, 1:])
        dx = dx.at[:, th:h:th].add(dx_bot[:, :-1])
    grads = tuple(res[3:])
    if not has_proj:
        grads = grads[:9]
    return dx, grads


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _fused(tile_bt, tile_h, eps, x, *weights):
    out, stats = _pallas_fwd(x, weights, tile_bt, tile_h, eps)
    return out, stats


def _fused_fwd(tile_bt, tile_h, eps, x, *weights):
    out, stats = _pallas_fwd(x, weights, tile_bt, tile_h, eps)
    return (out, stats), (x, weights)


def _fused_bwd(tile_bt, tile_h, eps, residuals, cts):
    # the ghost-stats cotangent is deliberately dropped (EMA input is
    # stop-gradient in flax's BatchNorm as well)
    x, weights = residuals
    dx, grads = _pallas_bwd(x, cts[0].astype(x.dtype), weights, tile_bt,
                            tile_h, eps)
    dweights = tuple(gi.astype(wi.dtype) for gi, wi in zip(grads, weights))
    return (dx,) + dweights


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_bottleneck_train_spatial(x: jax.Array, params: dict, *,
                                   tile_bt: int = 1,
                                   tile_h: Optional[int] = None,
                                   eps: float = 1e-5
                                   ) -> tuple[jax.Array, dict]:
    """The spatially-tiled fused ghost-BN training block:
    (out, ghost_stats_tree). Stride-1 blocks only."""
    weights = block_weights(params)
    has_proj = len(weights) == 12
    n, h, w_, cin = x.shape
    cmid = weights[0].shape[-1]
    cout = weights[6].shape[-1]
    if not has_proj and cin != cout:
        raise ValueError(f"Cin {cin} != Cout {cout} needs a projection")
    if n % tile_bt:
        raise ValueError(f"tile_bt {tile_bt} must divide batch {n}")
    if tile_h is None:
        tile_h = default_tile_h(h, w_, cin, cmid, cout)
        if tile_h is None:
            raise ValueError("no strip height fits the VMEM budget")
    elif h % tile_h:
        raise ValueError(f"tile_h {tile_h} must divide height {h}")
    out, stats = _fused(tile_bt, tile_h, eps, x, *weights)
    return out, stats_to_tree(stats, has_proj)
