"""Ring attention: sequence/context-parallel attention over the ICI ring.

The sequence axis of the mesh shards Q, K and V along their sequence
dimension. Each device computes block attention of its local Q chunk
against the K/V chunk it currently holds, then rotates K/V one hop around
the ring with ``lax.ppermute`` — after ``ring_size`` steps every Q chunk
has seen every K/V chunk, with only O(S/n) live memory and the transfer of
the next chunk overlapping the current block's compute (XLA schedules the
collective-permute concurrently with the einsums).

Online-softmax accumulation (running max / sum / output in f32) merges the
per-chunk results exactly — bitwise-independent of ring order.

Reference parity: the reference has *no* long-context mechanism at all
(SURVEY.md §5 "Long-context: Absent", §2.5 row 5); this op is what the
TPUJob ``sharding.sequence`` / ``sharding.context`` axes lower to.
Public-technique citation: Ring Attention (Liu et al. 2023), blockwise
formulation per PAPERS.md.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _chunk_scores(q, k, scale, my_idx, src_idx, chunk_q, chunk_k, causal):
    """Masked f32 scores of local q against the chunk that originated at
    ring position src_idx. [B,Sq,H,D]x[B,Sk,H,D] -> [B,H,Sq,Sk]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if not causal:
        return s
    rows = my_idx * chunk_q + jax.lax.broadcasted_iota(
        jnp.int32, (chunk_q, chunk_k), 0)
    cols = src_idx * chunk_k + jax.lax.broadcasted_iota(
        jnp.int32, (chunk_q, chunk_k), 1)
    return jnp.where((cols <= rows)[None, None], s, NEG_INF)


def _ring_attention_local(q, k, v, my_idx, *, axis_name: str, causal: bool,
                          scale: float):
    """SPMD body (runs under shard_map): q,k,v are the local sequence
    chunks [B, S_local, H, D]; my_idx this shard's ring position (passed
    in as a sharded iota — lax.axis_index under a partial-manual
    shard_map lowers to a PartitionId op older SPMD pipelines reject)."""
    from ..parallel.compat import axis_size
    n = axis_size(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    qf = q.astype(jnp.float32)

    def step(carry, s_idx):
        acc, m, l, (k_c, v_c) = carry
        src_idx = (my_idx - s_idx) % n          # origin of the held chunk
        s = _chunk_scores(qf, k_c.astype(jnp.float32), scale,
                          my_idx, src_idx, sq, sk, causal)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = (acc * alpha.transpose(0, 2, 1, 3)
               + jnp.einsum("bhqk,bkhd->bqhd", p,
                            v_c.astype(jnp.float32),
                            preferred_element_type=jnp.float32))
        kv = jax.lax.ppermute((k_c, v_c), axis_name, perm)
        return (acc, m_new, l, kv), None

    acc0 = jnp.zeros((b, sq, h, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    # checkpoint each ring step: backward recomputes the chunk's scores
    # instead of storing per-step [B,H,Sq,Sk] probabilities — residual
    # memory stays O(S) (the rotating K/V chunks), not O(S^2/n)
    (acc, m, l, _), _ = jax.lax.scan(
        jax.checkpoint(step), (acc0, m0, l0, (k, v)), jnp.arange(n))
    l = jnp.maximum(l, 1e-30)
    out = acc / l.transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh, axis: str = "sequence",
                   causal: bool = True, scale: Optional[float] = None):
    """Sequence-parallel attention. q,k,v: [batch, seq, heads, head_dim]
    with the seq dim (to be) sharded over ``mesh`` axis ``axis``.

    Works inside jit: partial-manual shard_map over the sequence axis only;
    batch/tensor axes stay under automatic GSPMD sharding.
    """
    d = q.shape[-1]
    scale = float(scale if scale is not None else 1.0 / math.sqrt(d))
    if mesh.shape.get(axis, 1) <= 1:
        from .flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)

    return _ring_fn(mesh, axis, causal, scale)(q, k, v)


@functools.lru_cache(maxsize=64)
def _ring_fn(mesh: Mesh, axis: str, causal: bool, scale: float):
    """Cached jitted shard_map — eager callers (flax init runs once per
    layer) hit jax's jit cache instead of recompiling per call.

    partial-manual shard_map (axis_names ⊂ mesh axes) only composes
    inside jit; the jit wrapper also makes eager calls work."""
    body = functools.partial(
        _ring_attention_local, axis_name=axis, causal=causal, scale=scale)
    spec = P(None, axis, None, None)
    from ..parallel.compat import shard_map
    mapped = shard_map(
        lambda q, k, v, idx: body(q, k, v, idx[0]),
        mesh=mesh, in_specs=(spec, spec, spec, P(axis)), out_specs=spec,
        axis_names={axis}, check_vma=False)

    def run(q, k, v):
        ring_pos = jnp.arange(mesh.shape[axis], dtype=jnp.int32)
        return mapped(q, k, v, ring_pos)

    return jax.jit(run)
