"""Flash attention for TPU as Pallas kernels (forward + backward).

FlashAttention-2-style online softmax: the S x S score matrix is never
materialized; the grid streams (q-block, k-block) tiles through VMEM while
running (max, sum, accumulator) state lives in VMEM scratch that persists
across the innermost grid dimension — memory is O(block^2), not O(S).
The backward pass recomputes scores from the saved log-sum-exp (no O(S^2)
residuals).

The reference platform has no kernel layer at all (SURVEY.md §5
"long-context: absent") — this is the TPU-native mechanism behind the
TPUJob sharding-spec's sequence/context parallelism, used per-chunk by
:mod:`ring_attention` and directly by the transformer model.

TPU notes:
- block sizes default to 128 (MXU tile) and are kept 8-aligned (f32
  sublane); shapes with no 8-aligned divisor fall back to the reference
  implementation rather than feeding Mosaic unaligned tiles.
- grid order puts k-blocks innermost: XLA/Mosaic double-buffers the
  k/v-block DMAs against the MXU work automatically.
- causal tiles above the diagonal skip all compute via pl.when.
- f32 accumulation via ``preferred_element_type`` on every dot.
- off-TPU (tests, CPU smoke) the same kernels run with ``interpret=True``.
"""

from __future__ import annotations

import functools
import logging
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

log = logging.getLogger(__name__)

NEG_INF = -1e30  # big-but-finite: avoids NaN from (-inf) - (-inf)

# once-per-(kernel,reason) warning guard — a job that requested `flash`
# but silently ran einsum every step was invisible before ISSUE 16
_warned_fallbacks: set = set()


def count_fallback(kernel: str, reason: str, detail: str = "") -> None:
    """Record that an optimized kernel declined a shape and ran its
    reference path instead: once-per-process WARNING plus the
    ``kftpu_kernel_fallback_total{kernel,reason}`` counter (worker
    /metrics + dashboard). Called at trace time — block selection is
    static Python over shapes — so it fires once per compiled program,
    not once per step; the counter answers "did the tier I asked for
    actually run", not "how many steps"."""
    from ..obs import registry as obsreg
    obsreg.counter(
        "kftpu_kernel_fallback_total",
        "optimized-kernel requests that fell back to the reference path",
        labels=("kernel", "reason")).labels(
            kernel=kernel, reason=reason).inc()
    if (kernel, reason) not in _warned_fallbacks:
        _warned_fallbacks.add((kernel, reason))
        log.warning(
            "kernel %s fell back to its reference path (%s%s) — the "
            "requested tier is NOT running; see "
            "kftpu_kernel_fallback_total on /metrics", kernel, reason,
            f": {detail}" if detail else "")


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(seq: int, preferred: int = 128) -> Optional[int]:
    """Largest 8-aligned (f32 sublane) divisor of seq that is <= preferred;
    None if there is none (caller falls back to the reference impl). In
    interpret mode (no Mosaic tiling) any divisor is fine."""
    b = min(preferred, seq)
    if _interpret():
        while seq % b:
            b -= 1
        return b
    b -= b % 8
    while b >= 8:
        if seq % b == 0:
            return b
        b -= 8
    return None


def _causal_mask(i, j, block_q, block_k):
    rows = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return cols <= rows


def _when_relevant(i, j, block_q, block_k, causal):
    """Run the decorated block only if k-block j intersects the causal
    triangle of q-block i (always runs when not causal)."""
    if not causal:
        return lambda fn: fn()
    return pl.when(j * block_k <= i * block_q + block_q - 1)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k):
    i, j = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @_when_relevant(i, j, block_q, block_k, causal)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        if causal:
            s = jnp.where(_causal_mask(i, j, block_q, block_k), s, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)

    @pl.when(j == n_k - 1)
    def _finish():
        l = jnp.maximum(l_ref[:], 1e-30)               # fully-masked rows
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(l)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    """q,k,v: [BH, S, D] → (o [BH,S,D], lse [BH,S,1]).

    lse rides with a trailing singleton so its block shape is
    (block_q, 1) in the tiled dims — Mosaic requires the last two block
    dims be (8,128)-divisible or equal to the array dims; a (1, block_q)
    block on a [BH, S] array satisfies neither (first TPU compile)."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    grid = (bh, seq_q // block_q, seq_k // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),     # acc
            pltpu.VMEM((block_q, 1), jnp.float32),     # running max
            pltpu.VMEM((block_q, 1), jnp.float32),     # running sum
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * seq_q * seq_k * d // (2 if causal else 1),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k),
        interpret=_interpret(),
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale, causal, block_q, block_k):
    i, j = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @_when_relevant(i, j, block_q, block_k, causal)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                               # [bq, 1]
        delta = delta_ref[0]                           # [bq, 1]
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        if causal:
            p = jnp.where(_causal_mask(i, j, block_q, block_k), p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_ref[:] += jax.lax.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(j == n_k - 1)
    def _finish():
        dq_ref[0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k):
    # grid: (bh, j over k-blocks, i over q-blocks) — i innermost
    j, i = pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @_when_relevant(i, j, block_q, block_k, causal)
    def _compute():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                               # [bq, 1]
        delta = delta_ref[0]                           # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        p = jnp.exp(s - lse)
        if causal:
            p = jnp.where(_causal_mask(i, j, block_q, block_k), p, 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # p^T @ do
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # ds^T @ q

    @pl.when(i == n_q - 1)
    def _finish():
        dk_ref[0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k):
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)            # [BH, S, 1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, seq_q // block_q, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, seq_k // block_k, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, scale, causal,
                            block_q, block_k)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    with_lse: bool = False):
    """Fused attention. q,k,v: [batch, seq, heads, head_dim].

    Returns [batch, seq, heads, head_dim]. With ``with_lse`` also returns
    the per-row log-sum-exp [batch, heads, seq] (chunk-merge residual for
    ring attention) — NOTE: the with_lse path is forward-only (no custom
    VJP); do not differentiate through it.

    Sequence lengths with no 8-aligned block divisor fall back to the
    reference implementation (Mosaic tiling needs 8-aligned sublanes).
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = float(scale if scale is not None else 1.0 / math.sqrt(d))
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    if bq is None or bk is None:
        if with_lse:
            raise ValueError(
                f"with_lse needs block-divisible seq lens, got {sq},{sk}")
        # fixed-vocabulary reason (metric label cardinality stays bounded);
        # the offending shape goes to the log line via the warning
        count_fallback("flash_attention", "unaligned-seq", f"seq {sq}x{sk}")
        return reference_attention(q, k, v, causal=causal, scale=scale)

    def fold(x):  # [B,S,H,D] -> [B*H, S, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    def unfold(x):
        return x.reshape(b, h, sq, d).transpose(0, 2, 1, 3)

    if with_lse:
        o, lse = _flash_fwd(fold(q), fold(k), fold(v), scale, causal, bq, bk)
        return unfold(o), lse.reshape(b, h, sq)
    return unfold(_flash(fold(q), fold(k), fold(v), scale, causal, bq, bk))


def reference_attention(q, k, v, *, causal=True, scale=None):
    """Naive O(S^2)-memory attention — the correctness oracle for tests
    and the fallback for shapes the Pallas kernels can't tile."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), jnp.bool_))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)
