"""Flash attention for TPU as Pallas kernels (forward + backward).

FlashAttention-2-style online softmax: the S x S score matrix is never
materialized in HBM; each q-block streams k/v-blocks through VMEM, keeping a
running (max, sum, accumulator) in f32. The backward pass recomputes scores
from the saved log-sum-exp (no O(S^2) residuals).

The reference platform has no kernel layer at all (SURVEY.md §5
"long-context: absent") — this is the TPU-native mechanism behind the
TPUJob sharding-spec's sequence/context parallelism, used per-chunk by
:mod:`ring_attention` and directly by the transformer model.

TPU notes:
- block sizes default to 128 (MXU tile); f32 accumulation via
  ``preferred_element_type`` on every dot.
- causal kernels bound the k-loop at the diagonal (no wasted blocks).
- off-TPU (tests, CPU smoke) the same kernels run with ``interpret=True``.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # big-but-finite: avoids NaN from (-inf) - (-inf)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(seq: int, preferred: int = 128) -> int:
    """Largest divisor of seq that is <= preferred (TPU-friendly)."""
    b = min(preferred, seq)
    while seq % b:
        b -= 1
    return b


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_k):
    i = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
    d = q.shape[-1]

    if causal:
        # number of k-blocks overlapping [0, (i+1)*bq) — diagonal included
        num_kv = jax.lax.div((i + 1) * block_q + block_k - 1, block_k)
    else:
        num_kv = seq_k // block_k

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        return acc, m_new, l

    acc = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv, body, (acc, m, l))

    l = jnp.maximum(l, 1e-30)                          # fully-masked rows
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = (m[:, 0] + jnp.log(l[:, 0])).astype(jnp.float32)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    """q,k,v: [BH, S, D] → (o [BH,S,D], lse [BH,S])."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    grid = (bh, seq_q // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=seq_k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_q, block_k, seq_k):
    i = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, None]
    delta = delta_ref[0][:, None]
    d = q.shape[-1]

    if causal:
        num_kv = jax.lax.div((i + 1) * block_q + block_k - 1, block_k)
    else:
        num_kv = seq_k // block_k

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(cols <= rows, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, num_kv, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                    seq_q):
    j = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                   # [bk, d]
    v = v_ref[0].astype(jnp.float32)
    d = k.shape[-1]
    num_q = seq_q // block_q
    # causal: q-blocks before the diagonal see nothing of this k-block
    start_i = jax.lax.div(j * block_k, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[0, pl.ds(i * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        p = jnp.exp(s - lse)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(cols <= rows, p, 0.0)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # p^T @ do
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # ds^T @ q
        return dk, dv

    dk = jnp.zeros((block_k, d), jnp.float32)
    dv = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_i, num_q, body, (dk, dv))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k):
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=seq_k),
        grid=(bh, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_q=seq_q),
        grid=(bh, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((1, seq_q, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, seq_q, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, seq_q), lambda b, j: (b, 0)),
            pl.BlockSpec((1, seq_q), lambda b, j: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, scale, causal,
                            block_q, block_k)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    with_lse: bool = False):
    """Fused attention. q,k,v: [batch, seq, heads, head_dim].

    Returns [batch, seq, heads, head_dim] (and the per-row log-sum-exp
    [batch, heads, seq] when ``with_lse`` — the residual ring_attention
    needs to merge chunks).
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = float(scale if scale is not None else 1.0 / math.sqrt(d))
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)

    def fold(x):  # [B,S,H,D] -> [B*H, S, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    def unfold(x):
        return x.reshape(b, h, sq, d).transpose(0, 2, 1, 3)

    if with_lse:
        o, lse = _flash_fwd(fold(q), fold(k), fold(v), scale, causal,
                            block_q, block_k)
        return unfold(o), lse.reshape(b, h, sq)
    return unfold(_flash(fold(q), fold(k), fold(v), scale, causal,
                         block_q, block_k))


def reference_attention(q, k, v, *, causal=True, scale=None):
    """Naive O(S^2)-memory attention — the correctness oracle for tests."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), jnp.bool_))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)
