"""AOT train-step executable export: rebinds skip XLA entirely.

The persistent compilation cache (compile_cache.py) cuts a warm start's
first-step cost to trace + lower + cache-load; this module removes even
that. At first-bind time the worker AOT-compiles the train step
(``TrainStepBuilder.build_compiled`` — ``jit(...).lower().compile()``)
and serializes the compiled executable to the checkpoint/cache volume
(``jax.experimental.serialize_executable``), keyed on everything that
shapes the program: topology, slice count, model+recipe fingerprint,
weight-update mode, sharding, global batch, and the jax/jaxlib versions.
A rebind, elastic resize back to a known shape, preemption re-bind, or
warm-pod adoption loads the keyed executable — no tracing, no lowering,
no XLA — and falls back to the persistent cache, then to a fresh
compile: a stale or mismatched key must never kill a gang.

Wire contract: the operator renders ``spec.warmStart`` (aot, aotDir) as
``KFTPU_AOT`` / ``KFTPU_AOT_DIR`` (api/trainingjob.py WarmStartSpec);
runtime/worker.py consumes both. The executable file is written
atomically (tmp + rename) and carries the key plus the abstract
(treedef + shape/dtype) signature of its example args, so a collision
or drift is detected at load, not at execution.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
from typing import Any, Optional

log = logging.getLogger(__name__)

AOT_ENABLE_ENV = "KFTPU_AOT"
AOT_DIR_ENV = "KFTPU_AOT_DIR"
# executables live beside the compile cache on the same volume — the one
# place this name is defined (worker + operator + docs import it)
AOT_SUBDIR = ".jax-aot-executables"

# bumped when the on-disk record layout changes (old files read as
# corrupt and fall back — never crash)
_FORMAT = 1


def default_aot_dir(volume_dir: str) -> str:
    """``<volume>/.jax-aot-executables`` with normalized slashes (same
    convention as compile_cache.default_cache_dir)."""
    return volume_dir.rstrip("/") + "/" + AOT_SUBDIR


def step_key(*, topology: str, num_slices: int, model_fingerprint: str,
             weight_update: str, sharding: dict, global_batch: int,
             kernels: Optional[dict] = None,
             extra: Optional[dict] = None) -> str:
    """Stable key of one compiled train step. Everything that changes
    the compiled program must feed it: the slice geometry, the model +
    recipe fingerprint (recipe.recipe_fingerprint), the weight-update
    layout, the resolved sharding axes, the global batch, the kernel
    tier (ISSUE 16 — the tier is ALSO inside the recipe fingerprint,
    but it rides here explicitly so a caller composing its own
    fingerprint cannot alias a flash/fused executable with a stock
    one), and — added here so no caller can forget — the jax/jaxlib
    versions and backend platform (a jaxlib upgrade silently
    invalidates serialized executables; the key must rotate with it)."""
    import jax
    import jaxlib
    parts = {
        "topology": topology,
        "numSlices": int(num_slices),
        "model": model_fingerprint,
        "weightUpdate": weight_update,
        "sharding": {k: int(v) for k, v in sorted((sharding or {}).items())},
        "globalBatch": int(global_batch),
        "kernels": {k: str(v)
                    for k, v in sorted((kernels or {}).items())},
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": jax.devices()[0].platform,
        "deviceKind": getattr(jax.devices()[0], "device_kind", ""),
        "format": _FORMAT,
        **(extra or {}),
    }
    blob = json.dumps(parts, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:24]


def abstract_signature(*example_args: Any) -> dict:
    """Treedef + per-leaf (shape, dtype) of the executable's example
    arguments — the load-time guard against a key collision or a pytree
    registration drift feeding mismatched buffers into a donating
    executable."""
    import jax
    sig = []
    for arg in example_args:
        leaves, treedef = jax.tree_util.tree_flatten(arg)
        sig.append({
            "treedef": str(treedef),
            "leaves": [[list(getattr(leaf, "shape", ())),
                        str(getattr(leaf, "dtype", type(leaf).__name__))]
                       for leaf in leaves],
        })
    return {"args": sig}


def _path(aot_dir: str, key: str) -> str:
    return aot_dir.rstrip("/") + f"/step-{key}.aotx"


def export_step(aot_dir: str, key: str, compiled,
                signature: dict) -> Optional[str]:
    """Serialize a ``jax.stages.Compiled`` train step under ``key``.
    Returns the written path, or None — export is an optimization, so
    every failure (unserializable backend, read-only volume) downgrades
    to a warning. The write is atomic (tmp + rename): a pod killed
    mid-export must never leave a truncated file a rebind would trip
    over."""
    try:
        from jax.experimental import serialize_executable
        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        record = {
            "format": _FORMAT,
            "key": key,
            "signature": signature,
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
        }
        os.makedirs(aot_dir, exist_ok=True)
        path = _path(aot_dir, key)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(record, f)
        os.replace(tmp, path)
        log.info("AOT step executable exported to %s (%d bytes)", path,
                 len(payload))
        _count("export")
        return path
    except Exception as e:  # noqa: BLE001 — export must never kill a gang
        log.warning("AOT export to %s failed: %s", aot_dir, e)
        _count("export-failed")
        return None


def load_step(aot_dir: str, key: str, signature: dict):
    """Load the serialized executable for ``key``, or None. EVERY
    failure mode falls back to None — absent file, truncated/corrupt
    pickle, a record written under a different key (hash collision on
    the filename is impossible, but a hand-copied file is not), an
    abstract-signature mismatch, and a deserialization error — so the
    caller's ladder (persistent cache, then fresh compile) always has a
    next rung. The gang must never die for a stale artifact."""
    path = _path(aot_dir, key)
    try:
        with open(path, "rb") as f:
            record = pickle.load(f)
    except FileNotFoundError:
        _count("miss")
        return None
    except Exception as e:  # noqa: BLE001 — corrupt file = miss
        log.warning("AOT executable %s unreadable (%s); falling back to "
                    "compile", path, e)
        _count("corrupt")
        return None
    try:
        if record.get("format") != _FORMAT or record.get("key") != key:
            log.warning("AOT executable %s key/format mismatch "
                        "(have %s/%s, want %s/%s); falling back",
                        path, record.get("key"), record.get("format"),
                        key, _FORMAT)
            _count("key-mismatch")
            return None
        if record.get("signature") != signature:
            log.warning("AOT executable %s argument-signature mismatch; "
                        "falling back to compile", path)
            _count("signature-mismatch")
            return None
        from jax.experimental import serialize_executable
        compiled = serialize_executable.deserialize_and_load(
            record["payload"], record["in_tree"], record["out_tree"])
        _count("hit")
        return compiled
    except Exception as e:  # noqa: BLE001 — a bad record = miss
        log.warning("AOT executable %s failed to deserialize (%s); "
                    "falling back to compile", path, e)
        _count("deserialize-failed")
        return None


def _count(outcome: str) -> None:
    """Obs-registry counter for the AOT path's outcomes (hit / miss /
    corrupt / mismatch / export) — the fleet-dashboard side of 'are
    rebinds actually skipping XLA'."""
    from ..obs import registry as obsreg
    obsreg.counter(
        "kftpu_aot_executable_total",
        "AOT serialized-executable loads/exports by outcome",
        labels=("outcome",)).labels(outcome=outcome).inc()
