"""The in-pod JAX worker runtime (new relative to the reference, which
delegated compute to the launched frameworks — SURVEY.md §7 phase 4).

- ``bootstrap``: consume the operator-rendered topology contract env,
  jax.distributed.initialize, build the mesh.
- ``trainstep``: pjit-compiled train-step engine over sharded state.
- ``checkpoint``: orbax-backed checkpoint/resume (core component; the
  reference only passed storage paths through to workloads).
- ``metrics``: per-step timing, throughput, JSONL metrics, profiler hooks.
- ``worker``: the in-pod main loop gluing the above (tf-cnn launcher analog).
"""
