"""The pjit train-step engine.

Everything inside one XLA computation: forward, backward, gradient
all-reduce (inserted by XLA over ICI/DCN from the sharding annotations),
optimizer update. No user-space communication — the TPU-native replacement
for the reference's PS gRPC / Horovod-NCCL step loops (SURVEY.md §2.5).

Design points for the MXU/HBM:
- params live in float32, compute in bfloat16 (models cast), optimizer
  update in float32;
- the whole state is donated so the update is in-place in HBM;
- optional jax.checkpoint (remat) policy for memory-bound models.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import data_axes
from ..parallel.sharding_rules import LogicalRules

PyTree = Any
# loss_fn(params, variables, batch, rng) -> (loss, aux_dict)
LossFn = Callable[[PyTree, PyTree, PyTree, jax.Array], tuple[jax.Array, dict]]


@dataclass
class TrainState:
    step: jax.Array
    params: PyTree
    opt_state: PyTree
    variables: PyTree = field(default_factory=dict)  # e.g. batch_stats
    rng: Optional[jax.Array] = None


def tree_logical_shardings(mesh: Mesh, rules: LogicalRules,
                           logical_axes: PyTree) -> PyTree:
    return rules.tree_shardings(mesh, logical_axes)


def replicated_like(mesh: Mesh, tree: PyTree) -> PyTree:
    rep = NamedSharding(mesh, P())
    return jax.tree.map(lambda _: rep, tree)


@dataclass
class TrainStepBuilder:
    """Builds the jitted init and step functions for one training setup."""

    mesh: Mesh
    loss_fn: LossFn
    optimizer: optax.GradientTransformation
    rules: Optional[LogicalRules] = None
    # pytree (matching params) of logical-axis tuples; None = replicate all
    param_logical_axes: Optional[PyTree] = None
    donate: bool = True

    # -- shardings ----------------------------------------------------------

    def param_shardings(self, params: PyTree) -> PyTree:
        if self.rules is None or self.param_logical_axes is None:
            return replicated_like(self.mesh, params)
        return self.rules.tree_shardings(self.mesh, self.param_logical_axes)

    def batch_shardings(self, rank: int = 2) -> NamedSharding:
        """Batch dim over data axes; dim 1 (sequence, for token arrays) over
        the sequence axis when sequence parallelism is on."""
        if rank >= 2 and self.mesh.shape.get("sequence", 1) > 1:
            return NamedSharding(self.mesh, P(data_axes(self.mesh), "sequence"))
        return NamedSharding(self.mesh, P(data_axes(self.mesh)))

    def state_shardings(self, state: TrainState) -> TrainState:
        ps = self.param_shardings(state.params)
        rep = NamedSharding(self.mesh, P())
        # optimizer state mirrors param sharding where shapes match (adam
        # moments), else replicated (scalars, counts)
        opt_sh = _optimizer_shardings(state.opt_state, state.params, ps, rep)
        return TrainState(
            step=rep, params=ps, opt_state=opt_sh,
            variables=replicated_like(self.mesh, state.variables),
            rng=rep if state.rng is not None else None,
        )

    # -- init ---------------------------------------------------------------

    def init(self, init_fn: Callable[[jax.Array], tuple[PyTree, PyTree]],
             rng: jax.Array) -> TrainState:
        """Initialize params sharded (never materialized replicated when the
        rules shard them): init under jit with out_shardings."""

        def _init(rng):
            params, variables = init_fn(rng)
            opt_state = self.optimizer.init(params)
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=opt_state, variables=variables,
                              rng=rng)

        abstract = jax.eval_shape(_init, rng)
        shardings = self.state_shardings(abstract)
        with self.mesh:
            return jax.jit(_init, out_shardings=shardings)(rng)

    # -- step ---------------------------------------------------------------

    def build(self) -> Callable[[TrainState, PyTree], tuple[TrainState, dict]]:
        def step_fn(state: TrainState, batch: PyTree) -> tuple[TrainState, dict]:
            rng = state.rng
            if rng is not None:
                rng, step_rng = jax.random.split(rng)
            else:
                step_rng = jax.random.PRNGKey(0)

            def loss_wrapper(params):
                return self.loss_fn(params, state.variables, batch, step_rng)

            (loss, aux), grads = jax.value_and_grad(
                loss_wrapper, has_aux=True)(state.params)
            updates, new_opt = self.optimizer.update(
                grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_vars = aux.pop("variables", state.variables)
            metrics = {"loss": loss,
                       "grad_norm": optax.global_norm(grads), **aux}
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   opt_state=new_opt, variables=new_vars,
                                   rng=rng)
            return new_state, metrics

        with self.mesh:
            fn = jax.jit(
                step_fn,
                donate_argnums=(0,) if self.donate else (),
            )
        return fn

    def build_eval(self, eval_fn: Callable[[PyTree, PyTree, PyTree], dict]
                   ) -> Callable[["TrainState", PyTree], dict]:
        """Jitted eval step: (state, batch) → metrics. No donation (the
        state lives on), same mesh/shardings as the train step — metrics
        come back replicated scalars."""

        def step(state: "TrainState", batch: PyTree) -> dict:
            return eval_fn(state.params, state.variables, batch)

        with self.mesh:
            return jax.jit(step)

    def place_batch(self, batch: PyTree) -> PyTree:
        """Shard a host batch onto the mesh (batch dim over data axes;
        sequence dim over the sequence axis for rank-2 token arrays)."""
        return jax.tree.map(
            lambda x: jax.device_put(
                x, self.batch_shardings(rank=getattr(x, "ndim", 1))
                if getattr(x, "ndim", 1) == 2 else
                NamedSharding(self.mesh, P(data_axes(self.mesh)))),
            batch)


def _optimizer_shardings(opt_state, params, param_shardings, rep):
    """Walk opt_state structurally: any subtree that mirrors the param tree
    (same treedef AND same leaf shapes — adam mu/nu do) takes the params'
    shardings wholesale; everything else (counts, scalars) replicates.

    Structural, not shape-keyed: two same-shape params with different
    shardings each keep their own sharding in the moments."""
    pdef = jax.tree.structure(params)
    pshapes = [getattr(l, "shape", None) for l in jax.tree.leaves(params)]

    def mirrors(node):
        try:
            if jax.tree.structure(node) != pdef:
                return False
        except TypeError:
            return False
        return [getattr(l, "shape", None)
                for l in jax.tree.leaves(node)] == pshapes

    def rec(node):
        if mirrors(node):
            return param_shardings
        if isinstance(node, (list, tuple)):
            new = [rec(c) for c in node]
            if hasattr(node, "_fields"):  # namedtuple (optax states)
                return type(node)(*new)
            return type(node)(new)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return rep

    return rec(opt_state)


jax.tree_util.register_dataclass(
    TrainState,
    data_fields=["step", "params", "opt_state", "variables", "rng"],
    meta_fields=[],
)
