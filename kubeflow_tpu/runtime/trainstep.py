"""The pjit train-step engine.

Everything inside one XLA computation: forward, backward, gradient
all-reduce (inserted by XLA over ICI/DCN from the sharding annotations),
optimizer update. No user-space communication — the TPU-native replacement
for the reference's PS gRPC / Horovod-NCCL step loops (SURVEY.md §2.5).

Design points for the MXU/HBM:
- params live in float32, compute in bfloat16 (models cast), optimizer
  update in float32;
- the whole state is donated so the update is in-place in HBM;
- optional jax.checkpoint (remat) policy for memory-bound models.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.compat import shard_map
from ..parallel.mesh import (data_axes, num_slices_of, replica_axes,
                             replica_degree)
from ..parallel.sharding_rules import LogicalRules, weight_update_spec
from .recipe import validate_weight_update

PyTree = Any
# loss_fn(params, variables, batch, rng) -> (loss, aux_dict)
LossFn = Callable[[PyTree, PyTree, PyTree, jax.Array], tuple[jax.Array, dict]]


@dataclass
class TrainState:
    step: jax.Array
    params: PyTree
    opt_state: PyTree
    variables: PyTree = field(default_factory=dict)  # e.g. batch_stats
    rng: Optional[jax.Array] = None


def tree_logical_shardings(mesh: Mesh, rules: LogicalRules,
                           logical_axes: PyTree) -> PyTree:
    return rules.tree_shardings(mesh, logical_axes)


def replicated_like(mesh: Mesh, tree: PyTree) -> PyTree:
    rep = NamedSharding(mesh, P())
    return jax.tree.map(lambda _: rep, tree)


@dataclass
class TrainStepBuilder:
    """Builds the jitted init and step functions for one training setup."""

    mesh: Mesh
    loss_fn: LossFn
    optimizer: optax.GradientTransformation
    rules: Optional[LogicalRules] = None
    # pytree (matching params) of logical-axis tuples; None = replicate all
    param_logical_axes: Optional[PyTree] = None
    donate: bool = True
    # Cross-replica weight-update layout (ZeRO-2, Xu et al.): "sharded"
    # distributes the optimizer state (adam mu/nu, f32 master copies) over
    # the data/fsdp axes even when the params themselves are replicated,
    # and constrains gradients so XLA emits reduce-scatter → shard-local
    # update → all-gather instead of all-reduce + a full replicated
    # update. Numerics match the replicated path; per-chip optimizer HBM
    # traffic drops to ~1/N (PERF.md "Weight-update sharding").
    # operator_knob metadata: tests/test_lint.py enforces that every such
    # knob is plumbed through recipe.py, worker.py, the TPUJob spec, the
    # controller env, and manifests/training.py.
    weight_update: str = field(default="replicated", metadata={
        "operator_knob": True, "spec_field": "weightUpdate",
        "modes": "WEIGHT_UPDATE_MODES"})
    # Slices the mesh spans (the DCN geometry): None = auto-detect from
    # the devices' slice_index (real multi-slice TPU backends stamp it;
    # single-host and CPU meshes read 1). The worker passes the
    # contract's count explicitly. When > 1, the sharding rules resolve
    # DCN-AWARE (LogicalRules.dcn_aware): dcn-unsafe logical axes (the
    # gather-indexed tok_embed vocab dim) replicate instead of forcing
    # the partitioner's involuntary full rematerialization across the
    # slow link — rung 1 of the multi-slice ISSUE, measured in PERF.md
    # "Multi-slice DCN training". dcn_aware=False keeps the legacy
    # layout (the bench's known-bad positive control).
    num_slices: Optional[int] = None
    dcn_aware: bool = True

    def __post_init__(self):
        validate_weight_update(self.weight_update)
        if self.num_slices is None:
            self.num_slices = num_slices_of(self.mesh)
        if self.dcn_aware and self.rules is not None and \
                self.num_slices > 1 and hasattr(self.rules, "dcn_aware"):
            self.rules = self.rules.dcn_aware(self.num_slices)
        # Sharding-invariant RNG: with the legacy (non-partitionable)
        # threefry, jit-with-sharded-out_shardings generates DIFFERENT
        # random bits per layout — init(rng) under TP rules diverged ~12%
        # from the same seed under pure DP
        # (tests/test_runtime.py::test_tp_matches_dp_numerics).
        # Partitionable threefry makes random generation a function of the
        # key alone regardless of output sharding (the default in newer
        # JAX). Set here, not at import: the flag is process-global, and
        # only code that actually builds train steps should flip it.
        jax.config.update("jax_threefry_partitionable", True)

    # -- shardings ----------------------------------------------------------

    def param_shardings(self, params: PyTree) -> PyTree:
        if self.rules is None or self.param_logical_axes is None:
            return replicated_like(self.mesh, params)
        return self.rules.tree_shardings(self.mesh, self.param_logical_axes)

    def batch_shardings(self, rank: int = 2) -> NamedSharding:
        """Batch dim over data axes; dim 1 (sequence, for token arrays) over
        the sequence axis when sequence parallelism is on."""
        if rank >= 2 and self.mesh.shape.get("sequence", 1) > 1:
            return NamedSharding(self.mesh, P(data_axes(self.mesh), "sequence"))
        return NamedSharding(self.mesh, P(data_axes(self.mesh)))

    def update_shardings(self, params: PyTree) -> PyTree:
        """Per-leaf shardings of the weight-update domain: where gradients
        land after reduction, where the optimizer state lives, and where
        updated params exist before the all-gather. Equal to the param
        shardings in replicated mode; in sharded mode each leaf gains one
        dimension sharded over the replica (data/fsdp) axes — leaves with
        no dividable dimension keep their param sharding (per-leaf
        fallback, bit-identical either way)."""
        ps = self.param_shardings(params)
        if self.weight_update != "sharded":
            return ps
        axes = replica_axes(self.mesh)
        if not axes:
            return ps

        def shard_leaf(leaf, sh):
            spec = weight_update_spec(sh.spec, getattr(leaf, "shape", ()),
                                      self.mesh, axes)
            return NamedSharding(self.mesh, spec) if spec is not None else sh

        return jax.tree.map(shard_leaf, params, ps)

    def state_shardings(self, state: TrainState) -> TrainState:
        ps = self.param_shardings(state.params)
        rep = NamedSharding(self.mesh, P())
        # optimizer state mirrors the weight-update sharding where shapes
        # match (adam moments — the param shardings themselves unless the
        # sharded update distributes them), else replicated (scalars,
        # counts). Params stay in their own sharding: fwd/bwd need them.
        opt_sh = _optimizer_shardings(state.opt_state, state.params,
                                      self.update_shardings(state.params),
                                      rep)
        return TrainState(
            step=rep, params=ps, opt_state=opt_sh,
            variables=replicated_like(self.mesh, state.variables),
            rng=rep if state.rng is not None else None,
        )

    # -- init ---------------------------------------------------------------

    def init(self, init_fn: Callable[[jax.Array], tuple[PyTree, PyTree]],
             rng: jax.Array) -> TrainState:
        """Initialize params sharded (never materialized replicated when the
        rules shard them): init under jit with out_shardings."""

        def _init(rng):
            params, variables = init_fn(rng)
            opt_state = self.optimizer.init(params)
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=opt_state, variables=variables,
                              rng=rng)

        abstract = jax.eval_shape(_init, rng)
        shardings = self.state_shardings(abstract)
        with self.mesh:
            return jax.jit(_init, out_shardings=shardings)(rng)

    # -- step ---------------------------------------------------------------

    def update_strategy(self, variables: Optional[PyTree] = None) -> str:
        """How this builder executes the weight update:
        "replicated" — full optimizer state on every chip;
        "zero2-explicit" — the gradient reduce-scatter emitted as an
        explicit collective (pure-DP meshes, replicated params, and no
        mutable model variables — see below);
        "zero2-gspmd" — the same dataflow requested from XLA with
        with_sharding_constraint (mixed meshes, rules-sharded params —
        and the Xu et al. mechanism verbatim: the TPU partitioner
        rewrites the annotated update into reduce-scatter + all-gather).

        Pass the workload's ``variables`` tree when you have it: a model
        with mutable batch statistics (BatchNorm) must take the GSPMD
        strategy — under shard_map the loss_fn would compute PER-REPLICA
        batch stats where the replicated path computes global-batch
        stats, a semantics change, not just a layout change. build()
        makes the same choice from the traced state, so this parameter
        only matters for reporting."""
        if self.weight_update != "sharded" or not replica_axes(self.mesh):
            return "replicated"
        nontrivial = {a for a, n in self.mesh.shape.items() if n > 1}
        pure_dp = nontrivial <= set(replica_axes(self.mesh))
        params_replicated = self.rules is None or \
            self.param_logical_axes is None
        stateless = variables is None or not jax.tree.leaves(variables)
        return "zero2-explicit" if pure_dp and params_replicated \
            and stateless else "zero2-gspmd"

    def build(self) -> Callable[[TrainState, PyTree], tuple[TrainState, dict]]:
        strategy = self.update_strategy()
        explicit_step = self._zero2_explicit_step_fn() \
            if strategy == "zero2-explicit" else None

        def generic_step(state: TrainState, batch: PyTree, strategy: str
                         ) -> tuple[TrainState, dict]:
            rng = state.rng
            if rng is not None:
                rng, step_rng = jax.random.split(rng)
            else:
                step_rng = jax.random.PRNGKey(0)

            def loss_wrapper(params):
                return self.loss_fn(params, state.variables, batch, step_rng)

            (loss, aux), grads = jax.value_and_grad(
                loss_wrapper, has_aux=True)(state.params)
            if strategy == "zero2-gspmd":
                # ZeRO-2 via GSPMD: constrain gradients into the sharded
                # update domain (the partitioner reduces into shards
                # instead of all-reducing the full gradient), slice
                # params into the same domain (local — params are
                # replicated over those axes), update the 1/N shard,
                # then constrain the new params back out (one
                # all-gather). Shard-local math is elementwise, so
                # values are identical to the replicated path.
                us = self.update_shardings(state.params)
                grads = jax.lax.with_sharding_constraint(grads, us)
                params_upd = jax.lax.with_sharding_constraint(
                    state.params, us)
            else:
                params_upd = state.params
            updates, new_opt = self.optimizer.update(
                grads, state.opt_state, params_upd)
            new_params = optax.apply_updates(params_upd, updates)
            new_vars = aux.pop("variables", state.variables)
            if strategy == "zero2-gspmd":
                new_params = jax.lax.with_sharding_constraint(
                    new_params, self.param_shardings(state.params))
                # pin the rest of the state to its init-time layout so the
                # step is a sharding fixed point (state out ≡ state in):
                # without this XLA drifts e.g. BN stats to a data-sharded
                # output, forcing an all-gather at the NEXT step's entry
                # and breaking AOT executable reuse (bench)
                new_opt = jax.lax.with_sharding_constraint(
                    new_opt, _optimizer_shardings(
                        new_opt, state.params, us,
                        NamedSharding(self.mesh, P())))
                new_vars = jax.lax.with_sharding_constraint(
                    new_vars, replicated_like(self.mesh, new_vars))
            metrics = {"loss": loss,
                       "grad_norm": optax.global_norm(grads), **aux}
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   opt_state=new_opt, variables=new_vars,
                                   rng=rng)
            return new_state, metrics

        def step_fn(state: TrainState, batch: PyTree) -> tuple[TrainState, dict]:
            # trace-time dispatch: the variables treedef is static under
            # jit, so a stateless model takes the explicit reduce-scatter
            # path and a BatchNorm-style model falls back to GSPMD (its
            # batch statistics must stay global-batch — update_strategy)
            if explicit_step is not None and \
                    not jax.tree.leaves(state.variables):
                return explicit_step(state, batch)
            return generic_step(
                state, batch,
                "zero2-gspmd" if strategy != "replicated" else "replicated")

        with self.mesh:
            fn = jax.jit(
                step_fn,
                donate_argnums=(0,) if self.donate else (),
            )
        return fn

    def build_compiled(self, state: "TrainState", batch: PyTree):
        """The AOT path: lower + compile the step against concrete
        example args NOW (instead of at the first loop iteration) and
        return the ``jax.stages.Compiled``. The compiled executable is
        what runtime/aot.py serializes to the cache volume so a rebind /
        resize / warm-pod adoption skips XLA entirely; it is also
        directly callable, so the exporting worker runs the very
        executable it persisted (compile once, not twice).

        Compiled WITHOUT buffer donation, deliberately: a DESERIALIZED
        executable's donation is unsafe against concurrent readers —
        donating the train state while orbax's async checkpoint save
        still references it corrupts the heap (observed: glibc
        "corrupted double-linked list" on the jit path's equivalent the
        runtime copy-protects). The cost is one extra live copy of the
        state during the step; the exporting worker runs the same
        non-donating executable so exported and first-bind numerics are
        the identical program."""
        from dataclasses import replace
        nondonating = replace(self, donate=False)
        with self.mesh:
            return nondonating.build().lower(state, batch).compile()

    def _zero2_explicit_step_fn(self):
        """The sharded weight update with its gradient reduction emitted
        explicitly (returns the UNjitted step fn — build() wraps it): a
        shard_map over the replica axes runs fwd/bwd on the replica-local
        batch and reduce-scatters the gradients (psum_scatter — the
        partitioner cannot decline to emit it, unlike the all-reduce +
        dynamic-slice rewrite TPU performs but CPU does not), returning
        the gradient as ONE logical full-shape array physically laid out
        in the update sharding. The optimizer update then runs OUTSIDE
        the manual region under GSPMD: every optax transform sees global
        values, so cross-leaf norms (grad clip, LARS trust ratios) are
        exact — running the optimizer shard-locally inside shard_map
        would compute shard-local norms and silently diverge from the
        replicated path. The final constraint of the new params back to
        their replicated sharding is the one all-gather. Only used for
        pure-DP meshes with replicated params and NO mutable model
        variables: under shard_map a BatchNorm model would compute
        per-replica batch statistics where the replicated path computes
        global-batch ones (update_strategy sends those to GSPMD).

        Parity fine print: losses/params/grad_norm are bit-identical to
        the replicated path for rng-FREE loss functions (all current
        workloads). A loss that consumes its rng (dropout) draws
        per-replica independent streams here (step_rng fold_in below) —
        statistically equivalent DP, not bitwise equal to the replicated
        path's single global-batch draw. And aux metrics leave the body
        as the cross-replica MEAN of per-replica values, so a nonlinear
        metric (e.g. perplexity = exp(loss)) carries a Jensen gap vs
        computing it over the global batch; loss itself is exact."""
        axes = replica_axes(self.mesh)
        n_rep = replica_degree(self.mesh)
        mesh = self.mesh
        P0 = P()
        rep = NamedSharding(mesh, P0)
        model_axes = tuple(a for a in mesh.axis_names if a not in set(axes))

        def spec_dim(spec) -> Optional[int]:
            for i, entry in enumerate(spec):
                if entry is None:
                    continue
                names = (entry,) if isinstance(entry, str) else tuple(entry)
                if set(names) & set(axes):
                    return i
            return None

        def step_fn(state: TrainState, batch: PyTree) -> tuple[TrainState, dict]:
            rng = state.rng
            if rng is not None:
                rng, step_rng = jax.random.split(rng)
            else:
                step_rng = jax.random.PRNGKey(0)

            is_ns = lambda x: isinstance(x, NamedSharding)  # noqa: E731
            ushard = self.update_shardings(state.params)
            uspecs = jax.tree.map(lambda s: s.spec, ushard, is_leaf=is_ns)
            opt_sh = _optimizer_shardings(state.opt_state, state.params,
                                          ushard, rep)

            def body(params, variables, batch, step_rng, ridx):
                # per-replica rng stream: the local batch is a different
                # slice of the global batch, so a loss_fn that draws
                # randomness (dropout, augmentation) must NOT draw the
                # same pattern on every replica. fold_in of the ring
                # position (passed as a sharded iota — lax.axis_index
                # under shard_map lowers to a PartitionId op older SPMD
                # pipelines reject, see ops/ring_attention.py) gives
                # independent per-replica draws; rng-FREE losses are
                # untouched and stay bit-identical to the replicated path.
                step_rng = jax.random.fold_in(step_rng, ridx[0])

                def loss_wrapper(p):
                    return self.loss_fn(p, variables, batch, step_rng)

                (loss, aux), grads = jax.value_and_grad(
                    loss_wrapper, has_aux=True)(params)

                # cross-replica gradient mean, scattered into the update
                # domain: grads of the replica-local mean loss divided by
                # the replica count sum to the global-mean gradient
                def scatter(g, spec):
                    d = spec_dim(spec)
                    g = g / n_rep
                    if d is None:    # no dividable dim: plain all-reduce
                        return jax.lax.psum(g, axes)
                    return jax.lax.psum_scatter(
                        g, axes, scatter_dimension=d, tiled=True)

                grads = jax.tree.map(scatter, grads, uspecs)
                new_vars = aux.pop("variables", variables)
                # per-replica aux metrics and updated model variables
                # (e.g. BN stats over the local batch) leave as the
                # cross-replica mean
                pmean = lambda t: jax.tree.map(  # noqa: E731
                    lambda x: jax.lax.psum(x / n_rep, axes), t)
                return (grads, jax.lax.psum(loss / n_rep, axes),
                        pmean(aux), pmean(new_vars))

            grads, loss, aux, new_vars = shard_map(
                body, mesh=mesh,
                in_specs=(P0, P0, P(axes), P0, P(axes)),
                out_specs=(uspecs, P0, P0, P0),
                check_vma=False,
            )(state.params, state.variables, batch, step_rng,
              jnp.arange(n_rep, dtype=jnp.int32))

            # shard-local update under GSPMD: grads arrive in the update
            # sharding (the reduce-scatter result), params are sliced into
            # it (local — they are replicated over the replica axes), and
            # all elementwise optimizer math stays sharded; cross-shard
            # norms lower to partial reductions + a scalar all-reduce
            grads = jax.lax.with_sharding_constraint(grads, ushard)
            params_upd = jax.lax.with_sharding_constraint(
                state.params, ushard)
            updates, new_opt = self.optimizer.update(
                grads, state.opt_state, params_upd)
            new_opt = jax.lax.with_sharding_constraint(new_opt, opt_sh)
            new_params = optax.apply_updates(params_upd, updates)
            # ... and the new params all-gather back out (their fwd/bwd
            # sharding — replicated over the replica axes)
            new_params = jax.lax.with_sharding_constraint(
                new_params, self.param_shardings(state.params))
            metrics = {"loss": loss,
                       "grad_norm": optax.global_norm(grads), **aux}
            # Replicated-math integrity probe (runtime/sentinel.py): every
            # replica recomputes the SAME scalar — the global param sqnorm
            # after the update's all-gather — and the per-replica vector
            # leaves for the host. Absent corruption the entries agree up
            # to reduce-order noise; a replica that disagrees is silent-
            # data-corruption evidence NAMING a host. Cost: one vdot
            # chain + a scalar all-gather per step. Only emitted when the
            # params are genuinely replicated over the replica axes (an
            # fsdp-style layout would make the entries differ
            # legitimately).
            psh = self.param_shardings(state.params)
            if n_rep > 1 and not any(
                    spec_dim(s.spec) is not None
                    for s in jax.tree.leaves(psh)):
                pspecs = jax.tree.map(lambda s: s.spec, psh,
                                      is_leaf=is_ns)

                def integrity_probe(params):
                    p2 = jnp.zeros((), jnp.float32)
                    for leaf in jax.tree.leaves(params):
                        x = leaf.astype(jnp.float32)
                        p2 = p2 + jnp.vdot(x, x)
                    if model_axes:
                        p2 = jax.lax.psum(p2, model_axes)
                    return jax.lax.all_gather(p2, axes)

                metrics["param_sqnorm_replicas"] = shard_map(
                    integrity_probe, mesh=mesh, in_specs=(pspecs,),
                    out_specs=P0, check_vma=False)(new_params)
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   opt_state=new_opt, variables=new_vars,
                                   rng=rng)
            return new_state, metrics

        return step_fn

    def build_eval(self, eval_fn: Callable[[PyTree, PyTree, PyTree], dict]
                   ) -> Callable[["TrainState", PyTree], dict]:
        """Jitted eval step: (state, batch) → metrics. No donation (the
        state lives on), same mesh/shardings as the train step — metrics
        come back replicated scalars."""

        def step(state: "TrainState", batch: PyTree) -> dict:
            return eval_fn(state.params, state.variables, batch)

        with self.mesh:
            return jax.jit(step)

    def place_batch(self, batch: PyTree) -> PyTree:
        """Shard a host batch onto the mesh (batch dim over data axes;
        sequence dim over the sequence axis for rank-2 token arrays)."""
        return jax.tree.map(
            lambda x: jax.device_put(
                x, self.batch_shardings(rank=getattr(x, "ndim", 1))
                if getattr(x, "ndim", 1) == 2 else
                NamedSharding(self.mesh, P(data_axes(self.mesh)))),
            batch)


@dataclass
class MultisliceTrainStepBuilder:
    """The MPMD pipeline-over-DCN path (parallel/multislice.py) behind
    the TrainStepBuilder surface the worker loop drives: ``init`` /
    ``build`` / ``place_batch``. One program per slice — stage s's
    params, optimizer shard, and compiled programs live entirely on
    slice s's own mesh; activations/grads cross the DCN boundary as
    explicit transfers under the 1F1B microbatch schedule, and
    ``last_report`` carries the measured bubble/DCN accounting the
    goodput ledger's ``pipeline_bubble`` category and bench --mode
    multislice consume. Supports the pipelined transformer workload
    (models/transformer.py multislice_stage_fns)."""

    cfg: Any                       # transformer.TransformerConfig
    num_slices: int
    num_microbatches: int
    optimizer: optax.GradientTransformation   # per-leaf transform
    grad_clip_norm: Optional[float] = None    # cross-stage global clip
    devices: Optional[list] = None

    def __post_init__(self):
        from ..models.transformer import multislice_stage_fns
        from ..parallel.multislice import MPMDPipeline, stage_meshes
        if self.num_slices < 2:
            raise ValueError(
                "the MPMD multislice path needs numSlices >= 2 (one "
                "program per slice); single-slice jobs take the "
                "TrainStepBuilder path")
        devices = list(self.devices if self.devices is not None
                       else jax.devices())
        init_fn, embed_fn, block_fn, head_loss_fn = \
            multislice_stage_fns(self.cfg)
        self._full_init = init_fn
        self.engine = MPMDPipeline(
            meshes=stage_meshes(devices, self.num_slices),
            embed_fn=embed_fn, block_fn=block_fn,
            head_loss_fn=head_loss_fn, optimizer=self.optimizer,
            num_microbatches=self.num_microbatches,
            grad_clip_norm=self.grad_clip_norm)

    @property
    def mesh(self):
        """Stage 0's mesh (logging / batch-geometry callers)."""
        return self.engine.meshes[0]

    @property
    def last_report(self):
        return self.engine.last_report

    def init(self, init_fn, rng: jax.Array):
        """Same surface as TrainStepBuilder.init: ``init_fn(rng) ->
        (params, variables)`` — the pipelined workload's init returns
        the full {"embed", "blocks", "head"} tree, which the engine
        partitions per stage (bit-identical to the single-program arm's
        init under the same rng)."""

        def full(rng):
            out = init_fn(rng)
            params = out[0] if isinstance(out, tuple) else out
            return params

        return self.engine.init(full, rng)

    def build(self):
        return self.engine.step

    def place_batch(self, batch):
        return self.engine.place_batch(batch)

    def build_eval(self, eval_fn):
        raise NotImplementedError(
            "eval is not supported on the MPMD multislice path yet; "
            "run eval on a single-program mesh")


def _optimizer_shardings(opt_state, params, param_shardings, rep):
    """Walk opt_state structurally: any subtree that mirrors the param tree
    (same treedef AND same leaf shapes — adam mu/nu do) takes the params'
    shardings wholesale; everything else (counts, scalars) replicates.

    Structural, not shape-keyed: two same-shape params with different
    shardings each keep their own sharding in the moments."""
    pdef = jax.tree.structure(params)
    pshapes = [getattr(l, "shape", None) for l in jax.tree.leaves(params)]

    def mirrors(node):
        try:
            if jax.tree.structure(node) != pdef:
                return False
        except TypeError:
            return False
        return [getattr(l, "shape", None)
                for l in jax.tree.leaves(node)] == pshapes

    def rec(node):
        if mirrors(node):
            return param_shardings
        if isinstance(node, (list, tuple)):
            new = [rec(c) for c in node]
            if hasattr(node, "_fields"):  # namedtuple (optax states)
                return type(node)(*new)
            return type(node)(new)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return rep

    return rec(opt_state)


jax.tree_util.register_dataclass(
    TrainState,
    data_fields=["step", "params", "opt_state", "variables", "rng"],
    meta_fields=[],
)
