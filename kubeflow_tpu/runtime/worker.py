"""The in-pod worker main: bootstrap → train loop → checkpoint → metrics.

The TPU-native launcher (the analog of tf-controller-examples/tf-cnn/
launcher.py, which parsed TF_CONFIG into tf_cnn_benchmarks flags). Run as:

    python -m kubeflow_tpu.runtime.worker --workload resnet50 --steps 100 ...

inside a TPUJob pod (the operator injects KFTPU_* env), or standalone on a
dev machine (no env → local mesh over visible devices). Unlike the
reference's launcher, workers EXIT on completion — the operator's
cleanPodPolicy handles pod reaping, so no sleep-forever hack
(launcher.py:91-93).
"""

from __future__ import annotations

import argparse
import logging
import os
import time
from functools import partial
from dataclasses import dataclass, replace
from typing import Callable, Optional

import jax

from ..models import RESNET_DEPTHS
from .bootstrap import WorkerContext, initialize
from .recipe import make_optimizer, scale_lr, validate_weight_update
from .checkpoint import CheckpointManager, HAVE_ORBAX
from .metrics import (FLIGHT_WINDOWS_ENV, METRICS_PATH_ENV,
                      AsyncWindowFetch, FlightRecorder, HeartbeatReporter,
                      MetricsLogger, ProfileArm, profile_trace)
from .trainstep import TrainStepBuilder

log = logging.getLogger(__name__)


@dataclass
class WorkloadSpec:
    """Everything the loop needs, supplied per-model by the registry."""

    name: str
    init_fn: Callable                      # rng -> (params, variables)
    loss_fn: Callable                      # (params, vars, batch, rng) -> (loss, aux)
    batch_fn: Callable                     # (rng, batch_size) -> batch pytree
    rules: Optional[object] = None         # LogicalRules
    param_logical_axes: Optional[object] = None
    eval_fn: Optional[Callable] = None     # (params, vars, batch) -> metrics


def _resnet_spec(image_size: int = 224, num_classes: int = 1000,
                 depth: int = 50,
                 label_smoothing: float = 0.0,
                 fused: bool = False,
                 fused_tile_bt: Optional[int] = None,
                 mesh=None) -> WorkloadSpec:
    from ..models import resnet as R
    model = R.make_resnet(depth, num_classes=num_classes)
    if fused:
        # opt-in ghost-BN fused-block variant (ops/fused_block_train.py):
        # per-tile/per-shard BN statistics, one Pallas kernel per
        # stride-1 bottleneck in each direction
        loss_fn = R.make_fused_loss_fn(model,
                                       label_smoothing=label_smoothing,
                                       tile_bt=fused_tile_bt, mesh=mesh)
    else:
        loss_fn = R.make_loss_fn(model, label_smoothing=label_smoothing)
    return WorkloadSpec(
        name=f"resnet{depth}" + ("-fused" if fused else ""),
        init_fn=R.init_fn(model, image_size=image_size),
        loss_fn=loss_fn,
        batch_fn=lambda rng, bs: R.synthetic_batch(
            rng, bs, image_size, num_classes),
        eval_fn=R.make_eval_fn(model),
    )


def _transformer_spec(**kw) -> WorkloadSpec:
    from ..models import transformer as T
    return T.workload_spec(**kw)


def _transformer_pipelined_spec(**kw) -> WorkloadSpec:
    from ..models import transformer as T
    return T.pipelined_workload_spec(**kw)


WORKLOADS: dict[str, Callable[..., WorkloadSpec]] = {
    # the tf_cnn_benchmarks --model family
    **{f"resnet{d}": partial(_resnet_spec, depth=d)
       for d in RESNET_DEPTHS},
    "transformer": _transformer_spec,
    # stacked-layer LM routed through the GPipe engine when the mesh has a
    # pipeline axis (factory takes mesh=, injected by train())
    "transformer-pipelined": _transformer_pipelined_spec,
}

# workloads whose spec factory needs the live mesh (pipeline scheduling;
# resnets shard_map the fused ghost-BN path over the data axes)
_MESH_AWARE_WORKLOADS = {"transformer-pipelined"} | \
    {f"resnet{d}" for d in RESNET_DEPTHS}
# workloads that consume --num-microbatches (GPipe scheduling)
_PIPELINED_WORKLOADS = {"transformer-pipelined"}

# workloads whose spec factory takes a TransformerConfig (cfg=) — the
# kernels.attention tier rewrites cfg.attention for these
_TRANSFORMER_WORKLOADS = {"transformer", "transformer-pipelined"}

# workloads that consume --data-dir (ImageNet-style record shards)
_IMAGE_WORKLOADS = {f"resnet{d}" for d in RESNET_DEPTHS}


def _env_int(name: str, default: int) -> int:
    """Integer knob from the operator-rendered env, with a loud failure
    on garbage (a typo'd spec value must not silently become a default)."""
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {v!r}") from None


def _env_float(name: str, default: float) -> float:
    """Float knob from the operator-rendered env (same loud-failure
    policy as _env_int)."""
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {v!r}") from None


def _emit_ckpt_spans(ckpt, tracer) -> None:
    """Drain the checkpoint manager's wall-clock op log into
    ckpt-save/ckpt-restore trace spans — the goodput ledger's
    checkpoint-badput evidence (obs/goodput.py)."""
    if ckpt is None or tracer is None:
        return
    for op, t0, t1, step in ckpt.drain_op_log():
        tracer.emit(op, start=t0, end=t1, step=step)


def _comm_profile_hlo(step_fn, state, batch) -> Optional[str]:
    """The compiled train step's optimized HLO for the comm profiler
    (obs/collectives.py), or None when profiling is off or not free.

    KFTPU_COMM_PROFILE: "0" disables; "auto" (default) profiles only
    when the HLO is FREE — the step is a ``jax.stages.Compiled`` (the
    PR 9 build_compiled / AOT-load path exposes ``as_text``); "1"
    forces the jit path to lower+compile a second executable for the
    text — a persistent-cache hit when the cache is live, but never
    free, so it is opt-in."""
    from ..obs.collectives import COMM_PROFILE_ENV
    mode = (os.environ.get(COMM_PROFILE_ENV) or "auto").strip().lower()
    if mode in ("0", "off", "false"):
        return None
    as_text = getattr(step_fn, "as_text", None)
    if as_text is not None:
        return as_text()
    if mode not in ("1", "force", "true"):
        return None
    sds = lambda x: jax.ShapeDtypeStruct(  # noqa: E731
        x.shape, x.dtype, sharding=x.sharding)
    a_state, a_batch = jax.tree.map(sds, (state, batch))
    return step_fn.lower(a_state, a_batch).compile().as_text()


# worker exit status after a SIGTERM-forced checkpoint: non-zero so the
# pod lands in Failed and the operator gang-restarts with resume
# (restart-ELIGIBLE, unlike exit 0 = Succeeded which completes the job),
# but a recognizable code (EX_TEMPFAIL) so logs distinguish "preempted,
# checkpointed, please restart me" from a crash
PREEMPTED_EXIT_CODE = 75


@dataclass
class TrainResult:
    steps: int
    examples_per_sec: float
    mean_step_time_s: float
    final_metrics: dict
    preempted: bool = False
    first_window_s: float = 0.0   # compile + warmup window (startup cost)
    # startup→first-completed-step seconds from train() entry, and how
    # the step executable came to exist: "aot" (serialized executable
    # loaded, no XLA), "warm" (persistent compile cache had entries), or
    # "cold" (fresh compile) — the warm-start evidence bench.py --mode
    # warmstart and the kftpu_time_to_first_step_seconds histogram read
    time_to_first_step_s: float = 0.0
    start_kind: str = "cold"
    # tripped-detector evidence (AnomalyEvidence.to_dict()) when the
    # numeric-integrity sentinel ended the run; None on a clean run.
    # main() maps truthiness to ANOMALY_EXIT_CODE (runtime/sentinel.py).
    anomaly: Optional[dict] = None


class PreemptionGuard:
    """SIGTERM-aware stop flag: TPU slices get preempted (maintenance,
    spot reclaim) with a grace period; Kubernetes delivers SIGTERM first.
    The loop checks ``stop`` at step boundaries, forces a final checkpoint,
    and exits cleanly so the gang restart resumes instead of replaying.
    The reference leaned on restartPolicy alone (SURVEY §5 failure
    handling) — losing up to checkpoint_every steps of work per restart."""

    def __init__(self, install: bool = True, on_term=None):
        self.stop = False
        self._prev = None
        # evidence hook: the flight recorder dumps from INSIDE the
        # signal handler — a worker wedged in a collective never reaches
        # the next step boundary, so the handler is the only place its
        # ring can still leave the sink (ISSUE 10)
        self._on_term_cb = on_term
        if install:
            import signal
            import threading
            if threading.current_thread() is threading.main_thread():
                self._prev = signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        log.warning("SIGTERM: finishing step, checkpointing, exiting")
        self.stop = True
        if self._on_term_cb is not None:
            try:
                self._on_term_cb()
            except Exception:  # noqa: BLE001 — evidence must not break
                pass           # the graceful-preemption path

    def uninstall(self) -> None:
        if self._prev is not None:
            import signal
            signal.signal(signal.SIGTERM, self._prev)
            self._prev = None


def train(
    workload: str = "resnet50",
    steps: int = 20,
    global_batch: int = 64,
    learning_rate: float = 0.1,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 100,
    resume: bool = True,
    resume_from: Optional[str] = None,
    metrics_path: Optional[str] = None,
    profile_dir: Optional[str] = None,
    ctx: Optional[WorkerContext] = None,
    workload_kwargs: Optional[dict] = None,
    seed: int = 0,
    sync_every: int = 10,
    data_dir: Optional[str] = None,
    optimizer: str = "momentum",
    lr_schedule: str = "constant",
    warmup_steps: int = 0,
    weight_decay: float = 0.0,
    momentum: float = 0.9,
    label_smoothing: float = 0.0,
    scale_lr_by_batch: bool = False,
    eval_every: int = 0,
    eval_batches: int = 8,
    eval_data_dir: Optional[str] = None,
    handle_sigterm: bool = True,
    tensorboard_dir: Optional[str] = None,
    weight_update: Optional[str] = None,
    input_workers: Optional[int] = None,
    device_prefetch: Optional[int] = None,
    span_path: Optional[str] = None,
    obs_metrics_port: Optional[int] = None,
    aot: Optional[bool] = None,
    aot_dir: Optional[str] = None,
    multislice_pipeline: Optional[bool] = None,
    multislice_microbatches: Optional[int] = None,
    kernel_attention: Optional[str] = None,
    kernel_optimizer: Optional[str] = None,
    kernel_serving: Optional[str] = None,
    integrity: Optional[bool] = None,
    integrity_spike_z: Optional[float] = None,
    integrity_window: Optional[int] = None,
    integrity_check_every: Optional[int] = None,
    runtime_schedule: Optional[bool] = None,
) -> TrainResult:
    # before any jit: warm restarts must hit the persistent cache for the
    # very first compile (the startup→first-step dominator, PERF.md) —
    # and the compile/cache listeners must be live so the first step's
    # cold-vs-warm evidence is counted, not guessed
    t_train_start = time.perf_counter()
    from .compile_cache import (compile_stats, enable_compilation_cache,
                                install_compile_metrics)
    install_compile_metrics()
    enable_compilation_cache()
    # snapshot BEFORE any jit: the first step's cold-vs-warm verdict is
    # the hit/compile delta from here (evidence, not a directory check
    # — a shared namespace cache is non-empty with OTHER jobs' entries)
    compile_stats_at_entry = compile_stats()
    ctx = ctx or initialize()
    workload_kwargs = dict(workload_kwargs or {})
    if workload in _MESH_AWARE_WORKLOADS:
        workload_kwargs.setdefault("mesh", ctx.mesh)

    # real-data path: shard dirs are self-describing, so the dataset's
    # geometry configures the model (launcher.py --data_dir analog)
    data_dir = data_dir or os.environ.get("KFTPU_DATA_DIR")
    eval_explicit = eval_data_dir is not None
    eval_data_dir = eval_data_dir or os.environ.get("KFTPU_EVAL_DATA_DIR")
    if eval_data_dir and workload not in _IMAGE_WORKLOADS:
        if eval_explicit or eval_every > 0:
            # mirror the data_dir check below: a transformer job pointed
            # at image shards must fail at startup, not at the first eval
            raise ValueError(
                f"workload {workload!r} does not consume --eval-data-dir")
        # gang-wide KFTPU_EVAL_DATA_DIR with eval disabled: the env var
        # is set for the image workers in the gang, not this one — warn,
        # don't crash the whole job
        log.warning("ignoring KFTPU_EVAL_DATA_DIR for workload %r "
                    "(eval disabled)", workload)
        eval_data_dir = None
    # input-pipeline knobs: CLI flag wins, then the operator-rendered env
    # (controllers/tpujob.py renders spec.input.workers/devicePrefetch as
    # KFTPU_INPUT_WORKERS / KFTPU_DEVICE_PREFETCH), then the defaults —
    # in-process augment, double-buffered device staging
    if input_workers is None:
        input_workers = _env_int("KFTPU_INPUT_WORKERS", 0)
    if device_prefetch is None:
        device_prefetch = _env_int("KFTPU_DEVICE_PREFETCH", 2)
    if input_workers < 0 or device_prefetch < 0:
        raise ValueError(
            f"input_workers ({input_workers}) and device_prefetch "
            f"({device_prefetch}) must be >= 0")
    data_source = None
    if data_dir:
        if workload not in _IMAGE_WORKLOADS:
            raise ValueError(
                f"workload {workload!r} does not consume --data-dir")
        from ..data.imagenet import ImageNetSource
        # ship uint8 records host→device (1/4 the bytes of f32);
        # normalization folds into the train step below so XLA fuses it
        # into the first conv's prologue — transfers are the real-data
        # bottleneck (PERF.md "Real-data input path"); input_workers > 0
        # fans decode+augment out over spawned processes through the
        # shared-memory ring (data/mp_augment.py)
        data_source = ImageNetSource(data_dir, batch_size=global_batch,
                                     output="uint8",
                                     workers=input_workers)
        workload_kwargs.setdefault("image_size", data_source.image_size)
        workload_kwargs.setdefault("num_classes", data_source.num_classes)

    if label_smoothing and workload in _IMAGE_WORKLOADS:
        workload_kwargs.setdefault("label_smoothing", label_smoothing)

    # kernel tier (ISSUE 16): CLI flag wins, then the operator-rendered
    # env (controllers/tpujob.py renders spec.kernels.* as
    # KFTPU_KERNEL_*), then stock. Every resolved knob is baked into the
    # recipe fingerprint + AOT step key below — a tier flip can never
    # alias a cached executable.
    from ..api.trainingjob import (ATTENTION_KERNELS, OPTIMIZER_KERNELS,
                                   SERVING_KERNELS)
    ka_set = kernel_attention or os.environ.get("KFTPU_KERNEL_ATTENTION")
    kernel_attention = ka_set or "einsum"
    kernel_optimizer = kernel_optimizer or \
        os.environ.get("KFTPU_KERNEL_OPTIMIZER") or "stock"
    kernel_serving = kernel_serving or \
        os.environ.get("KFTPU_KERNEL_SERVING") or "stock"
    for _seg, _val, _vocab in (
            ("attention", kernel_attention, ATTENTION_KERNELS),
            ("optimizer", kernel_optimizer, OPTIMIZER_KERNELS),
            ("serving", kernel_serving, SERVING_KERNELS)):
        if _val not in _vocab:
            raise ValueError(
                f"kernels.{_seg} {_val!r} not one of {_vocab}")
    if ka_set:
        # the attention tier configures the transformer's attention
        # implementation; on any other workload it would be a silent
        # no-op the user mistakes for a speedup — reject at startup
        if workload not in _TRANSFORMER_WORKLOADS:
            raise ValueError(
                f"kernels.attention applies to transformer workloads, "
                f"not {workload!r}")
        from ..models import transformer as _TK
        _cfg = workload_kwargs.get("cfg") or _TK.TransformerConfig.tiny()
        workload_kwargs["cfg"] = replace(_cfg, attention=kernel_attention)
    # active tier on /metrics (labels, value 1): the dashboard's runs
    # panel and a flight-recorder dump both read it; pairs with
    # kftpu_kernel_fallback_total to answer "did the tier actually run"
    from ..obs import registry as obsreg
    obsreg.gauge(
        "kftpu_kernel_tier_info",
        "active kernel tier of this worker (info-style: value is 1)",
        labels=("attention", "optimizer", "serving")).labels(
            attention=kernel_attention, optimizer=kernel_optimizer,
            serving=kernel_serving).set(1)

    spec = WORKLOADS[workload](**workload_kwargs)
    if data_source is not None:
        from ..data.imagenet import device_normalize
        inner_loss = spec.loss_fn

        def loss_fn_u8(params, variables, batch, rng,
                       _inner=inner_loss):
            batch = dict(batch, images=device_normalize(batch["images"]))
            return _inner(params, variables, batch, rng)

        spec = replace(spec, loss_fn=loss_fn_u8)
    log.info("worker %d/%d mesh=%s workload=%s", ctx.process_id,
             ctx.num_processes, dict(ctx.mesh.shape), spec.name)

    base_lr = scale_lr(learning_rate, global_batch) if scale_lr_by_batch \
        else learning_rate
    # runtime LR schedule (ISSUE 19): lr/warmup/total_steps become
    # optimizer-STATE scalars instead of traced constants, so every
    # hyperparameter-sweep trial after the first shares one cached /
    # AOT'd executable (the fingerprint below switches to
    # compile_shape_fingerprint). CLI flag wins, then the
    # experiment-injected env, then off — the baked path stays the
    # byte-for-byte default. fused_adam + runtime_schedule is rejected
    # inside make_optimizer (the kernel bakes the schedule).
    if runtime_schedule is None:
        runtime_schedule = bool(_env_int("KFTPU_RUNTIME_SCHEDULE", 0))
    opt, lr_fn = make_optimizer(
        optimizer, base_lr, schedule=lr_schedule, total_steps=steps,
        warmup_steps=warmup_steps, weight_decay=weight_decay,
        momentum=momentum, kernels=kernel_optimizer,
        runtime_schedule=runtime_schedule)
    # weight-update layout (ZeRO-2 sharded vs replicated): CLI flag wins,
    # then the operator-rendered env (controllers/tpujob.py renders
    # spec.weightUpdate as KFTPU_WEIGHT_UPDATE), then replicated
    weight_update = validate_weight_update(
        weight_update or os.environ.get("KFTPU_WEIGHT_UPDATE")
        or "replicated")
    # DCN geometry: the contract's slice count makes the step engine's
    # sharding-rule resolution (and the comm profile below) DCN-aware —
    # a multi-slice mesh must not shard dcn-unsafe axes across the
    # boundary (parallel/sharding_rules.py dcn_aware)
    n_slices = ctx.contract.num_slices if ctx.contract else \
        _env_int("KFTPU_NUM_SLICES", 1)
    # spec.multislice → KFTPU_MULTISLICE_PIPELINE/_MICROBATCHES: the
    # MPMD pipeline-over-DCN path — one program per slice with explicit
    # activation/grad transfers instead of one SPMD program resharding
    # across the slow link (docs/training.md "Multi-slice training")
    if multislice_pipeline is None:
        multislice_pipeline = bool(
            _env_int("KFTPU_MULTISLICE_PIPELINE", 0))
    if multislice_pipeline:
        if workload != "transformer-pipelined":
            raise ValueError(
                f"multislice.pipeline supports the transformer-"
                f"pipelined workload (stacked stages), not {workload!r}")
        if eval_every:
            raise ValueError(
                "eval is not supported on the MPMD multislice path yet")
        if weight_update != "replicated":
            # reject, don't silently downgrade: the MPMD engine runs
            # per-stage replicated updates (stage params already live
            # only on their slice), so a requested ZeRO-2 layout would
            # quietly not happen
            raise ValueError(
                f"weightUpdate={weight_update!r} is not supported on "
                f"the MPMD multislice path (per-stage updates are "
                f"replicated within each slice)")
        from .trainstep import MultisliceTrainStepBuilder
        from ..models import transformer as _T
        # default 4 x slices (bubble (S-1)/(M+S-1) <= ~20%). NOT the
        # single-program --num-microbatches knob: main() always fills
        # that with its own default, so consulting it here would
        # silently pin M=4 at every slice count
        if multislice_microbatches is None:
            multislice_microbatches = _env_int(
                "KFTPU_MULTISLICE_MICROBATCHES", 0) or \
                4 * max(2, n_slices)
        # the engine owns cross-stage global-norm clipping (the same
        # clip the single-program chain applies); its inner optimizer
        # must stay per-leaf
        opt_ms, lr_fn = make_optimizer(
            optimizer, base_lr, schedule=lr_schedule, total_steps=steps,
            warmup_steps=warmup_steps, weight_decay=weight_decay,
            momentum=momentum, grad_clip=None, kernels=kernel_optimizer,
            runtime_schedule=runtime_schedule)
        builder = MultisliceTrainStepBuilder(
            cfg=workload_kwargs.get("cfg") or _T.TransformerConfig.tiny(),
            num_slices=n_slices,
            num_microbatches=int(multislice_microbatches),
            optimizer=opt_ms, grad_clip_norm=1.0)
    else:
        builder = TrainStepBuilder(
            mesh=ctx.mesh, loss_fn=spec.loss_fn, optimizer=opt,
            rules=spec.rules, param_logical_axes=spec.param_logical_axes,
            weight_update=weight_update, num_slices=n_slices)

    rng = jax.random.PRNGKey(seed)
    state = builder.init(spec.init_fn, rng)

    # numeric-integrity sentinel (runtime/sentinel.py): CLI flag wins,
    # then the operator-rendered env (controllers/tpujob.py renders
    # spec.integrity.* as KFTPU_INTEGRITY*), then off. Deliberately NOT
    # in the recipe fingerprint — the sentinel changes no math.
    from . import sentinel as sentinel_mod
    if integrity is None:
        integrity = bool(_env_int("KFTPU_INTEGRITY", 0))
    if integrity_spike_z is None:
        integrity_spike_z = _env_float("KFTPU_INTEGRITY_SPIKE_Z",
                                       sentinel_mod.DEFAULT_SPIKE_Z)
    if integrity_window is None:
        integrity_window = _env_int("KFTPU_INTEGRITY_WINDOW",
                                    sentinel_mod.DEFAULT_WINDOW_STEPS)
    if integrity_check_every is None:
        integrity_check_every = _env_int("KFTPU_INTEGRITY_CHECK_EVERY",
                                         sentinel_mod.DEFAULT_CHECK_EVERY)
    sentinel = sentinel_mod.NumericSentinel(
        spike_z=float(integrity_spike_z),
        window_steps=int(integrity_window)) if integrity else None
    # operator anomaly-rollback contract (NOT spec knobs — rendered from
    # the job's anomaly-rollback annotation): resume from the newest
    # intact step <= the LKG, never the newest (tainted) one; the replay
    # range arms bisection over the suspect steps (the deterministic
    # input pipeline replays byte-identical batches per (seed, index))
    resume_step = _env_int(sentinel_mod.RESUME_STEP_ENV, 0) or None
    replay = sentinel_mod.parse_replay_range(
        os.environ.get(sentinel_mod.REPLAY_RANGE_ENV))
    # chaos numeric-fault hook (cluster/chaos.py injectors): poisons the
    # state at an armed step so the detectors have something to catch
    fault_hook = sentinel_mod.NumericFaultHook.from_env()
    anomaly = None       # AnomalyEvidence once a detector trips
    replay_done = False  # bisection verdict emitted

    # operator-rendered checkpoint/resume contract (controllers/tpujob.py
    # renders spec.checkpointDir/resumeFrom as these env vars; gang restart
    # sets resumeFrom automatically)
    checkpoint_dir = checkpoint_dir or os.environ.get("KFTPU_CHECKPOINT_DIR")
    resume_from = resume_from or os.environ.get("KFTPU_RESUME_FROM")

    # Elastic-resize restore contract: every save stamps the writer's
    # replica degree + global batch; a restore at a DIFFERENT degree
    # (the scheduler shrank/grew the gang between restarts) validates
    # the fixed-global-batch invariant, then the template's shardings
    # reshape the state — incl. the ZeRO-2-distributed optimizer
    # moments — onto the new mesh (runtime/checkpoint.py).
    from ..parallel.mesh import replica_degree
    degree = replica_degree(ctx.mesh) or 1
    run_meta = {"replicaDegree": degree, "globalBatch": global_batch}

    ckpt = None
    early_ckpt_ops: list = []
    if checkpoint_dir and HAVE_ORBAX:
        ckpt = CheckpointManager(checkpoint_dir,
                                 save_interval_steps=checkpoint_every,
                                 run_meta=run_meta)
        if resume and ckpt.latest_step() is not None:
            # expect_run: the elastic contract is checked against the
            # step the fallback walk ACTUALLY restores. max_step caps
            # the fallback walk for anomaly rollback (resume the LKG,
            # not the newest tainted step; a corrupt LKG falls back to
            # the next-oldest intact step).
            state = ckpt.restore(state,
                                 expect_run=(degree, global_batch),
                                 max_step=resume_step)
            log.info("resumed from step %d", int(state.step))
            if resume_step is not None:
                # the steps after the LKG are tainted by the trip:
                # delete them so they can't shadow the rollback on the
                # next restore (and so orbax doesn't refuse re-saving
                # them as training replays through)
                ckpt.discard_steps_after(int(state.step))
    if resume_from and int(state.step) == 0 and HAVE_ORBAX:
        # warm start / gang-restart restore: only when the local
        # checkpoint_dir had nothing newer
        src = ckpt if resume_from == checkpoint_dir else \
            CheckpointManager(resume_from, run_meta=run_meta)
        if src.latest_step() is not None:
            state = src.restore(state,
                                expect_run=(degree, global_batch))
            log.info("resumed from %s at step %d", resume_from,
                     int(state.step))
        if src is not ckpt:
            # keep the restore's op-log entry: the tracer that will emit
            # it as a ckpt-restore span does not exist yet (it is created
            # after every failure-prone setup stage), and src closes here
            early_ckpt_ops = src.drain_op_log()
            src.close()

    # LKG promotion bookkeeping: steps with a checkpoint on disk,
    # promoted to last-known-good once a LATER window drains clean
    # through the sentinel (ckpt.tag_lkg below)
    saved_steps: list = []
    if ckpt is not None and ckpt.latest_step() is not None:
        saved_steps.append(int(ckpt.latest_step()))

    step_fn = builder.build()

    # -- eval pass (running-stats forward, top-1/top-5) ---------------------
    eval_step = None
    eval_source = None
    if eval_every and spec.eval_fn is not None:
        eval_step = builder.build_eval(spec.eval_fn)
        if eval_data_dir:
            from ..data.imagenet import (ImageNetSource,  # noqa: F811
                                         read_meta)
            from ..parallel.mesh import data_axes
            # validation reads: no augmentation, normalized on host (eval
            # is off the hot path, simplicity over transfer bytes). A
            # holdout smaller than the (possibly huge) train batch must
            # not kill the run — clamp the eval batch to the holdout,
            # rounded down to a data-axis multiple (place_batch shards
            # dim 0 over the data axes; a non-divisible batch won't place)
            dp = 1
            for ax in data_axes(ctx.mesh):
                dp *= ctx.mesh.shape[ax]
            n_rec = int(read_meta(eval_data_dir)["num_records"])
            eval_bs = (min(global_batch, n_rec) // dp) * dp
            if eval_bs == 0:
                log.warning(
                    "eval disabled: holdout %s has %d records, fewer than "
                    "the %d-way data-parallel mesh", eval_data_dir, n_rec,
                    dp)
                eval_step = None
            else:
                # drop_remainder=False: the final partial batch comes
                # through short and run_eval pads+masks it, so a full
                # pass counts every holdout record exactly once
                eval_source = ImageNetSource(eval_data_dir,
                                             batch_size=eval_bs,
                                             augment=False,
                                             drop_remainder=False)

    def _pad_mask(batch) -> tuple[dict, float]:
        """Pad a (possibly short) holdout batch to the compiled eval
        shape, 0/1-weighting the rows so eval_fn masks the padding out
        of every metric. Returns (batch, real-record count)."""
        import numpy as np
        n = int(batch["labels"].shape[0])
        w = np.ones((n,), np.float32)
        if n < eval_bs:
            pad = eval_bs - n
            batch = {
                "images": np.concatenate(
                    [batch["images"],
                     np.zeros((pad,) + batch["images"].shape[1:],
                              batch["images"].dtype)]),
                "labels": np.concatenate(
                    [batch["labels"], np.zeros((pad,), np.int32)]),
            }
            w = np.concatenate([w, np.zeros((pad,), np.float32)])
        return dict(batch, weight=w), float(n)

    def run_eval(state) -> dict:
        """Average spec.eval_fn over at most ONE pass of the held-out
        shards (never resampled). eval_batches caps the pass for cheap
        mid-run checks; eval_batches=0 means the FULL holdout — every
        record counted exactly once (the tail batch is padded + masked)
        — what the final acceptance number must be measured on (a
        subsample's sampling error can flip a 76%-top-1 verdict)."""
        if eval_source is not None:
            eval_iter = eval_source.epoch(0, seed + 2)
            n_batches = eval_source.num_batches if eval_batches <= 0 \
                else min(eval_batches, eval_source.num_batches)
            next_batch = lambda i: next(eval_iter)  # noqa: E731
        else:
            n_batches = eval_batches if eval_batches > 0 else 8
            next_batch = lambda i: spec.batch_fn(  # noqa: E731
                jax.random.fold_in(jax.random.PRNGKey(seed + 2), i),
                global_batch)
        totals: dict = {}
        denom = 0.0
        for i in range(n_batches):
            b = next_batch(i)
            if eval_source is not None:
                b, bw = _pad_mask(b)
            else:
                bw = 1.0
            eb = builder.place_batch(b)
            em = eval_step(state, eb)
            for k, v in em.items():
                totals[k] = totals.get(k, 0.0) + float(v) * bw
            denom += bw
        if not denom:
            return {}
        out = {k: v / denom for k, v in totals.items()}
        if "eval_perplexity" in out and "eval_loss" in out:
            # perplexity = exp(MEAN loss); a mean of per-batch exp(loss)
            # is biased high (Jensen), so rederive from the averaged loss
            import math
            out["eval_perplexity"] = math.exp(out["eval_loss"])
        return out

    # kubebench injects KFTPU_METRICS_PATH so the reporter can aggregate
    # this run's per-step stream (workflows/kubebench.py report_from_metrics)
    metrics_path = metrics_path or os.environ.get(METRICS_PATH_ENV)
    if metrics_path:
        os.makedirs(os.path.dirname(metrics_path) or ".", exist_ok=True)
    tensorboard_dir = tensorboard_dir or os.environ.get("KFTPU_TB_DIR")
    # TB events come from process 0 only — one curve per run, not per host
    mlog = MetricsLogger(metrics_path, batch_size=global_batch,
                         tensorboard_dir=(tensorboard_dir
                                          if ctx.process_id == 0 else None))
    # liveness heartbeat for the stall watchdog (controllers/tpujob.py):
    # None outside a pod (no KFTPU_POD_NAME) — bare-metal runs and tests
    # carry no annotation to patch. The initial forced beat establishes
    # the baseline, so a worker that wedges inside its FIRST window (the
    # compile, the first collective) is still caught.
    heartbeat = HeartbeatReporter.from_env()
    if heartbeat is not None:
        heartbeat.beat(int(state.step), force=True)
    data_rng = jax.random.PRNGKey(seed + 1)
    # host batches come from the (possibly multi-process) augment
    # pipeline; the device prefetcher then stages them onto the mesh
    # `device_prefetch` batches ahead of the running step so host→device
    # copies overlap compute (data/device_prefetch.py). Resume picks the
    # stream up at the restored step so restarts never replay
    # already-consumed batches.
    data_iter = data_source.batches(seed, start_batch=int(state.step)) \
        if data_source is not None else None
    dev_iter = None
    if data_iter is not None and device_prefetch > 0:
        from ..data.device_prefetch import DevicePrefetcher
        dev_iter = DevicePrefetcher(data_iter, builder.place_batch,
                                    depth=device_prefetch)

    # synthetic mode rotates a small pre-placed batch pool instead of
    # generating on-device every step: generation shares the chip with the
    # train step and was measured costing ~30% throughput; the reference's
    # vehicle (tf_cnn_benchmarks --data_name synthetic) reuses a static
    # batch the same way
    batch_pool: list = []
    if data_iter is None:
        for _ in range(4):
            data_rng, brng = jax.random.split(data_rng)
            batch_pool.append(
                builder.place_batch(spec.batch_fn(brng, global_batch)))

    # -- warm start: AOT executable load/export (runtime/aot.py) ----------
    # The fallback ladder the whole warm-start stack rests on: a keyed
    # serialized executable (no trace, no lower, no XLA) → the
    # persistent compile cache (trace+lower, executable loaded) → a
    # fresh compile. Every rung downgrades to the next with a warning —
    # a stale key, corrupt file, or missing volume must never kill a
    # gang. start_kind records which rung actually ran the first step
    # (resolved from the compile/cache-hit evidence at the first step).
    start_kind = "cold"
    aot_used = False
    if aot is None:
        from .aot import AOT_ENABLE_ENV
        aot = bool(_env_int(AOT_ENABLE_ENV, 0))  # rendered "1"/"0"
    if aot:
        from . import aot as aot_mod
        from .recipe import compile_shape_fingerprint, recipe_fingerprint

        def _fingerprint(**knobs):
            # With the runtime schedule active, lr/warmup/steps are
            # executable INPUTS, not constants — drop them from the key
            # so lr-variant trials share one AOT executable; the flag
            # itself is a program change, so it joins the key (a
            # runtime-schedule step can never alias a baked one).
            if runtime_schedule:
                return compile_shape_fingerprint(
                    runtime_schedule=True, **knobs)
            return recipe_fingerprint(**knobs)

        aot_dir = aot_dir or os.environ.get(aot_mod.AOT_DIR_ENV) or (
            aot_mod.default_aot_dir(checkpoint_dir) if checkpoint_dir
            else None)
        if not aot_dir:
            log.warning("AOT warm start requested but no --aot-dir / "
                        "%s / checkpoint volume to keep executables on; "
                        "continuing without it", aot_mod.AOT_DIR_ENV)
        elif multislice_pipeline:
            # per-stage AOT (the MPMD path): one serialized executable
            # per (stage, program) — stage index + program kind ride
            # step_key's ``extra`` beside topology x numSlices, so an
            # N-program job warms N executables and cold start stays
            # flat in N. Load-all = aot start; anything less exports
            # the missing programs on this (already-paid) compile.
            try:
                fp = _fingerprint(
                    workload=spec.name, optimizer=optimizer,
                    lr_schedule=lr_schedule, learning_rate=base_lr,
                    warmup_steps=warmup_steps, weight_decay=weight_decay,
                    momentum=momentum, label_smoothing=label_smoothing,
                    steps=steps, real_data=False,
                    kernels={"attention": kernel_attention,
                             "optimizer": kernel_optimizer},
                    workload_kwargs=workload_kwargs)
                engine = builder.engine
                stage_sharding = {
                    "data": int(engine.meshes[0].shape["data"])}

                def ms_key(s, kind):
                    return aot_mod.step_key(
                        topology=os.environ.get("KFTPU_TOPOLOGY", "")
                        or f"local-{ctx.num_processes}p",
                        num_slices=n_slices, model_fingerprint=fp,
                        weight_update="mpmd", sharding=stage_sharding,
                        global_batch=global_batch,
                        kernels={"attention": kernel_attention,
                                 "optimizer": kernel_optimizer},
                        extra={"stage": s, "program": kind,
                               "microbatches":
                                   engine.num_microbatches})

                n_loaded = engine.load_stages(aot_dir, state,
                                              batch_pool[0], ms_key)
                if n_loaded == engine.num_programs:
                    aot_used = True
                    start_kind = "aot"
                    log.info("AOT: %d/%d stage programs loaded — "
                             "skipping XLA for every stage", n_loaded,
                             engine.num_programs)
                else:
                    engine.export_stages(aot_dir, state, batch_pool[0],
                                         ms_key)
            except Exception as e:  # noqa: BLE001 — optimization only
                log.warning("multislice AOT setup failed (%s); using "
                            "the jit path", e)
        else:
            try:
                if data_source is not None:
                    import numpy as np
                    s = data_source.image_size
                    example = builder.place_batch({
                        "images": np.zeros((global_batch, s, s, 3),
                                           np.uint8),
                        "labels": np.zeros((global_batch,), np.int32)})
                else:
                    example = batch_pool[0]
                fp = _fingerprint(
                    workload=spec.name, optimizer=optimizer,
                    lr_schedule=lr_schedule, learning_rate=base_lr,
                    warmup_steps=warmup_steps, weight_decay=weight_decay,
                    momentum=momentum, label_smoothing=label_smoothing,
                    steps=steps, real_data=data_source is not None,
                    kernels={"attention": kernel_attention,
                             "optimizer": kernel_optimizer},
                    workload_kwargs=workload_kwargs)
                sig = aot_mod.abstract_signature(state, example)
                key = aot_mod.step_key(
                    topology=os.environ.get("KFTPU_TOPOLOGY", "")
                    or f"local-{ctx.num_processes}p",
                    num_slices=int(os.environ.get("KFTPU_NUM_SLICES",
                                                  "1") or 1),
                    model_fingerprint=fp, weight_update=weight_update,
                    sharding={a: int(n)
                              for a, n in ctx.mesh.shape.items()},
                    global_batch=global_batch,
                    kernels={"attention": kernel_attention,
                             "optimizer": kernel_optimizer})
                loaded = aot_mod.load_step(aot_dir, key, sig)
                if loaded is not None:
                    step_fn = loaded
                    aot_used = True
                    start_kind = "aot"
                    log.info("AOT step executable loaded (key %s): "
                             "skipping XLA for the train step", key)
                else:
                    # first bind: compile ahead of time, persist the
                    # executable, and RUN the compiled object (compile
                    # once — the export is on the already-paid path)
                    compiled = builder.build_compiled(state, example)
                    aot_mod.export_step(aot_dir, key, compiled, sig)
                    step_fn = compiled
            except Exception as e:  # noqa: BLE001 — optimization only
                log.warning("AOT warm-start setup failed (%s); using "
                            "the jit path", e)

    start_step = int(state.step)
    # trace spans (obs/trace.py): the worker end of the job's end-to-end
    # timeline. The operator renders KFTPU_TRACE_ID (minted at admission)
    # and KFTPU_SPAN_PATH / spec.observability.spanPath into the pod; a
    # bare-metal run with --span-path mints its own trace id. None = no
    # sink configured, spans off at zero cost. Created HERE, after every
    # failure-prone setup stage (data pipeline, device placement): the
    # only cleanup path is the loop's finally, so nothing that can raise
    # may sit between creation and the try below — an earlier creation
    # would leak the bound port and span fd on a setup failure.
    from ..obs.trace import SPAN_PATH_ENV, TRACE_ID_ENV, SpanWriter, \
        mint_trace_id
    span_path = span_path or os.environ.get(SPAN_PATH_ENV)
    tracer = None
    dump_tracer = None
    if span_path:
        trace_id = os.environ.get(TRACE_ID_ENV) or mint_trace_id()
        tracer = SpanWriter(span_path, "worker", trace_id=trace_id)
        # the flight recorder dumps from the SIGTERM handler, which can
        # interrupt the main thread INSIDE tracer's emit lock — a
        # dedicated writer (own lock, same sink) makes the dump path
        # deadlock-free by construction
        dump_tracer = SpanWriter(span_path, "worker", trace_id=trace_id)
    # step-time flight recorder + on-demand profiler trigger (ISSUE 10):
    # the ring records per-window host-stage breakdowns; the arm lets
    # POST /profile?steps=N capture a jax.profiler trace around the next
    # N steps without a restart
    recorder = FlightRecorder(windows=_env_int(FLIGHT_WINDOWS_ENV, 64))
    import tempfile
    # profile artifacts beside the checkpoints ONLY for local volumes:
    # a gs://-style checkpoint URI joined with os.path would make
    # on_step_start os.makedirs a literal ./gs:/bucket/... tree (the
    # bug class the compile-cache gs:// guard exists for) — bucket
    # checkpoint dirs fall through to the local tempdir
    profile_arm = ProfileArm(
        base_dir=profile_dir or os.environ.get("KFTPU_PROFILE_DIR")
        or (os.path.join(checkpoint_dir, "profiles")
            if checkpoint_dir and "://" not in checkpoint_dir
            else os.path.join(tempfile.gettempdir(), "kftpu-profiles")),
        tracer=tracer)
    # the worker's own scrape surface (spec.observability.metricsPort →
    # KFTPU_OBS_METRICS_PORT → --obs-metrics-port): /metrics over the
    # process default registry — step/window timings, input-stage rates,
    # checkpoint durations, heartbeat freshness — plus the on-demand
    # profiler trigger and the flight-recorder peek
    if obs_metrics_port is None:
        obs_metrics_port = _env_int("KFTPU_OBS_METRICS_PORT", 0)
    obs_server = None
    if obs_metrics_port:
        from ..obs.http import ObsServer
        try:
            obs_server = ObsServer(port=obs_metrics_port, handlers={
                ("POST", "/profile"):
                    lambda q: profile_arm.request(q.get("steps", 0)),
                ("GET", "/flightrecorder"):
                    lambda q: (200, recorder.snapshot()),
            })
            obs_server.start()
        except (OSError, OverflowError) as e:
            # observability must never kill training: a taken port
            # (second in-process train(), hostNetwork clash) or an
            # out-of-range one from the raw env/CLI path costs the
            # scrape surface, nothing else
            log.warning("obs metrics server on :%d failed: %s",
                        obs_metrics_port, e)
            obs_server = None
    if tracer is not None:
        tracer.event("train-start", workload=spec.name,
                     start_step=start_step, steps=steps,
                     process=ctx.process_id)
        # the pre-tracer restores' op-log entries become spans now, so
        # restore time lands in the ledger's checkpoint badput
        for op, t0w, t1w, st in early_ckpt_ops:
            tracer.emit(op, start=t0w, end=t1w, step=st)
        _emit_ckpt_spans(ckpt, tracer)
    last_metrics: dict = {}
    first_step_s = 0.0
    guard = PreemptionGuard(
        install=handle_sigterm,
        on_term=lambda: recorder.dump(dump_tracer, "sigterm"))
    preempted = False
    # Sync to the host only every `sync_every` steps: a per-step float()
    # fetch is a full device→host round trip that defeats async dispatch
    # (r2 verdict item). Even at the window edge the fetch is ASYNC now:
    # the device→host copy for window N's metrics starts at N's edge and
    # resolves a window later (AsyncWindowFetch), so the dispatch queue
    # never empties — the blocking edge fetch cost ~160 ms of queue
    # refill per window on tunneled hosts (PERF.md).
    sync_every = max(1, int(sync_every))
    if sentinel is not None:
        # the sentinel reads window-drained floats, so the window edge
        # bounds detection latency: cap the sync interval at the check
        # cadence (spec.integrity.checkEverySteps)
        sync_every = min(sync_every, max(1, int(integrity_check_every)))
    afetch = AsyncWindowFetch(lag=1)
    comm_series = None   # kftpu_comm_* handle, pruned at teardown
    # MPMD schedule-idle accumulator: the engine reports modeled bubble
    # seconds per step (host floats); each closed window emits ONE
    # pipeline-bubble span sized to its accumulated bubble so the
    # goodput ledger's pipeline_bubble category is fed from measured
    # schedule evidence (obs/goodput.py)
    win_bubble = 0.0
    loop_error: Optional[BaseException] = None
    try:
        with profile_trace(profile_dir, enabled=profile_dir is not None,
                           tracer=tracer):
            window = 0
            win_t0 = time.perf_counter()
            for step in range(start_step, steps):
                profile_arm.on_step_start()
                recorder.mark("data", step)
                t_a = time.perf_counter()
                if dev_iter is not None:
                    batch = next(dev_iter)
                    t_h = t_b = time.perf_counter()
                elif data_iter is not None:
                    host_batch = next(data_iter)
                    t_h = time.perf_counter()
                    batch = builder.place_batch(host_batch)
                    t_b = time.perf_counter()
                else:
                    batch = batch_pool[step % len(batch_pool)]
                    t_h = t_b = time.perf_counter()
                recorder.mark("first-step" if step == start_step
                              else "step", step)
                if step == start_step:
                    try:
                        state, metrics = step_fn(state, batch)
                    except Exception as e:  # noqa: BLE001 — see below
                        if not aot_used:
                            raise
                        # last rung of the AOT fallback ladder: an
                        # executable that passed the key+signature check
                        # but still cannot execute (backend drift a
                        # version string did not capture) falls back to
                        # a fresh compile — a stale artifact must never
                        # kill the gang. Donation is consummated only on
                        # successful dispatch, so state is still alive.
                        log.warning("AOT executable failed at first "
                                    "step (%s); recompiling", e)
                        aot_used = False
                        if multislice_pipeline:
                            builder.engine.reset_programs()
                        step_fn = builder.build()
                        state, metrics = step_fn(state, batch)
                    # one hard sync, once: the time-to-first-step metric
                    # IS the startup cost this measures — never on the
                    # steady-state path
                    jax.block_until_ready(metrics)
                    t_first = time.perf_counter() - t_train_start
                    stats_now = compile_stats()
                    d_compiles = stats_now["xla_backend_compiles"] - \
                        compile_stats_at_entry["xla_backend_compiles"]
                    d_hits = stats_now["cache_hits"] - \
                        compile_stats_at_entry["cache_hits"]
                    if not aot_used:
                        # warm = EVERY compile so far came from the
                        # persistent cache; any real XLA compile (or no
                        # cache at all) is a cold start — evidence, so
                        # a shared cache warmed by OTHER jobs' programs
                        # (or the AOT subdir beside it) can't
                        # masquerade as warmth. Conservative on
                        # purpose: with the default persistence
                        # threshold, tiny sub-threshold jits recompile
                        # and read as cold — under-reporting warmth
                        # beats hiding real cold starts.
                        start_kind = "warm" if d_compiles == 0 \
                            and d_hits > 0 else "cold"
                    from ..obs import registry as obsreg
                    obsreg.histogram(
                        "kftpu_time_to_first_step_seconds",
                        "train()-entry to first completed step, by "
                        "start kind (cold/warm/aot)",
                        labels=("start",)).labels(
                            start=start_kind).observe(t_first)
                    if tracer is not None:
                        tracer.event("first-step",
                                     start_kind=start_kind,
                                     seconds=round(t_first, 3),
                                     backend_compiles=d_compiles,
                                     cache_hits=d_hits, step=step + 1)
                    first_step_s = t_first
                    # communication observability (ISSUE 13): profile
                    # the compiled step's collectives ONCE, after the
                    # start-kind evidence above (a forced second
                    # compile must not pollute the cold/warm verdict).
                    # Best-effort — observability never kills training.
                    try:
                        hlo = _comm_profile_hlo(step_fn, state, batch)
                        if hlo is not None:
                            from ..obs.collectives import (
                                COMM_PROFILE_SPAN, analyze_hlo,
                                export_comm_metrics, slice_assignment)
                            comm_prof = analyze_hlo(
                                hlo,
                                slice_assignment(ctx.mesh, n_slices),
                                mesh_axes=[(a, int(ctx.mesh.shape[a]))
                                           for a in ctx.mesh.axis_names])
                            comm_series = export_comm_metrics(comm_prof)
                            recorder.set_comm_model(
                                comm_prof.modeled_ici_seconds,
                                comm_prof.modeled_dcn_seconds)
                            if tracer is not None and \
                                    ctx.process_id == 0:
                                tracer.event(COMM_PROFILE_SPAN,
                                             step=step + 1,
                                             profile=comm_prof.to_dict())
                    except Exception as e:  # noqa: BLE001
                        log.warning("comm profile failed: %s", e)
                    if multislice_pipeline and tracer is not None and \
                            ctx.process_id == 0 and \
                            builder.last_report is not None:
                        # the MPMD analog of the comm-profile span: the
                        # schedule model's makespan / per-stage busy /
                        # bubble / explicit-DCN accounting of the first
                        # step
                        tracer.event("multislice-profile", step=step + 1,
                                     report=builder.last_report.to_dict())
                else:
                    state, metrics = step_fn(state, batch)
                # the first step's compile + blocking sync is recorded
                # under its OWN key: charging it to dispatch would make
                # the first window's record lie about where time went
                step_cost = time.perf_counter() - t_b
                recorder.note_step(
                    data_s=t_h - t_a, h2d_s=t_b - t_h,
                    dispatch_s=0.0 if step == start_step else step_cost,
                    first_step_s=step_cost if step == start_step
                    else 0.0)
                profile_arm.on_step_end(step + 1)
                if fault_hook is not None and \
                        fault_hook.should_fire(step + 1):
                    # chaos numeric fault (cluster/chaos.py): corrupt
                    # the state AFTER the step completes, so the damage
                    # surfaces in the NEXT window's metrics — the way
                    # real SDC would
                    state = fault_hook.poison(state, step + 1)
                if multislice_pipeline:
                    win_bubble += float(
                        metrics.get("pipeline_bubble_s", 0.0) or 0.0)
                window += 1
                # checkpoint saves are their own sync point (orbax fetches
                # the state), so close the timing window first
                # snapshot ONCE per iteration: SIGTERM between the save's
                # force= evaluation and the break check must not exit
                # without the forced checkpoint
                stopping = guard.stop
                final = step + 1 == steps
                will_ckpt = ckpt is not None and ckpt.should_save(step + 1)
                will_eval = eval_step is not None and (
                    (step + 1) % eval_every == 0 or final)
                closed = window >= sync_every or final \
                    or will_ckpt or will_eval or stopping
                if closed:
                    t_now = time.perf_counter()
                    # start the copy for THIS window; resolve the window
                    # submitted one edge ago (its copy has completed, so
                    # the float() below costs nothing). Hard sync points
                    # — checkpoint/eval/preemption/final — force the
                    # drain: their reported metrics must be complete.
                    afetch.submit(step + 1, window, t_now - win_t0,
                                  {**metrics, "learning_rate": lr_fn(step)})
                    if tracer is not None:
                        # one span per closed window, timed by the loop
                        # itself (no device fetch): the per-window beat
                        # of the job's end-to-end timeline
                        now_w = time.time()
                        tracer.emit("window",
                                    start=now_w - (t_now - win_t0),
                                    end=now_w, step=step + 1, steps=window)
                        if win_bubble > 0:
                            # the window's MPMD schedule-idle seconds,
                            # anchored at its tail (a modeled
                            # attribution inside the real interval —
                            # obs/goodput.py SPAN_PIPELINE_BUBBLE)
                            from ..obs.goodput import \
                                SPAN_PIPELINE_BUBBLE
                            b = min(win_bubble, t_now - win_t0)
                            tracer.emit(SPAN_PIPELINE_BUBBLE,
                                        start=now_w - b, end=now_w,
                                        step=step + 1)
                    win_bubble = 0.0
                    t_drain0 = time.perf_counter()
                    for s, w, wall, vals in afetch.drain(
                            force=final or will_ckpt or will_eval
                            or stopping):
                        # the zero2 integrity probe's per-replica VECTOR
                        # must not reach the scalar metric stream
                        rep_sq = vals.pop("param_sqnorm_replicas", None)
                        last_metrics = vals
                        mlog.record_window(s, w, wall, vals)
                        if tracer is not None:
                            # per-window objective event for the
                            # experiment reconciler's median-stopping
                            # read (api/experiment.py SPAN_OBJECTIVE):
                            # drained values are complete, one window
                            # behind the live edge by design
                            from ..api.experiment import SPAN_OBJECTIVE
                            obj_vals = {}
                            for k, v in vals.items():
                                try:
                                    obj_vals[k] = float(v)
                                except (TypeError, ValueError):
                                    pass  # non-scalar diagnostic
                            tracer.event(SPAN_OBJECTIVE, step=s,
                                         window=w, **obj_vals)
                        if sentinel is not None and anomaly is None:
                            anomaly = sentinel.observe(
                                s, loss=vals.get("loss"),
                                grad_norm=vals.get("grad_norm"),
                                replica_sqnorms=None if rep_sq is None
                                else [float(v) for v in rep_sq],
                                lkg=ckpt.lkg_step()
                                if ckpt is not None else None)
                            if anomaly is None and ckpt is not None:
                                # window ending at s drained clean:
                                # every saved step < s now has a
                                # sentinel-cleared window after it —
                                # promote the newest to last-known-good
                                cleared = [n for n in saved_steps
                                           if n < s]
                                if cleared:
                                    ckpt.tag_lkg(cleared[-1])
                            if anomaly is None and replay is not None \
                                    and not replay_done \
                                    and s >= replay[1]:
                                # the suspect range replayed CLEAN with
                                # the suspect host evacuated: the
                                # bisection verdict that converts "the
                                # job is cursed" into "host N is bad"
                                replay_done = True
                                if tracer is not None:
                                    tracer.event(
                                        "anomaly-bisection",
                                        lo=replay[0], hi=replay[1],
                                        verdict="clean", step=s)
                    recorder.close_window(
                        step + 1, window, t_now - win_t0,
                        drain_s=time.perf_counter() - t_drain0)
                    if heartbeat is not None:
                        # advertise progress at EVERY window close, not
                        # per drained window: the step number needs no
                        # device fetch, and a beat gated on the lagged
                        # drain would double the beat-free interval the
                        # stall watchdog sees right after a forced
                        # drain. A loop that stops closing windows
                        # stops beating — exactly the watchdog's signal.
                        # lastLoss/lastGradNorm ride along so the
                        # operator can flag a NaN-emitting worker even
                        # with the worker's own sentinel disabled.
                        heartbeat.beat(
                            step + 1,
                            loss=last_metrics.get("loss"),
                            grad_norm=last_metrics.get("grad_norm"))
                    window = 0
                if anomaly is not None:
                    # tripped detector: dump the flight record, post the
                    # evidence, and exit WITHOUT checkpointing — the
                    # state is tainted; the operator rolls the job back
                    # to the LKG (controllers/tpujob.py _handle_anomaly)
                    log.error("numeric anomaly %s at step %d (value %s, "
                              "lkg %s): exiting for LKG rollback",
                              anomaly.kind, anomaly.step,
                              anomaly.to_dict()["value"], anomaly.lkg)
                    from ..obs.goodput import SPAN_ANOMALY
                    recorder.dump(dump_tracer, SPAN_ANOMALY,
                                  error=f"{anomaly.kind}@{anomaly.step}")
                    if tracer is not None:
                        tracer.event(SPAN_ANOMALY, step=anomaly.step,
                                     kind=anomaly.kind,
                                     value=anomaly.to_dict()["value"],
                                     lkg=anomaly.lkg,
                                     **({"replay": list(replay)}
                                        if replay is not None else {}))
                    if heartbeat is not None:
                        from ..api.trainingjob import ANOMALY_ANNOTATION
                        heartbeat.annotate(ANOMALY_ANNOTATION,
                                           anomaly.to_json())
                    break
                if ckpt is not None:
                    # preemption and normal completion force the save
                    # regardless of cadence: the final state must be
                    # persisted (resume/serving read it), and under
                    # preemption the grace period is the budget — resume
                    # must lose 0 steps
                    recorder.mark("ckpt-save", step + 1)
                    if ckpt.save(step + 1, state,
                                 force=stopping or final):
                        saved_steps.append(step + 1)
                    _emit_ckpt_spans(ckpt, tracer)
                if stopping:
                    preempted = True
                    break
                if will_eval:
                    # the window closed above, so eval wall-time is never
                    # charged to throughput; forward-only pass, results
                    # ride the metric stream
                    recorder.mark("eval", step + 1)
                    em = run_eval(state)
                    if em:
                        last_metrics.update(em)
                        mlog.event(step + 1, em)
                        log.info("eval @%d: %s", step + 1,
                                 {k: round(v, 4) for k, v in em.items()})
                if closed:
                    # restart the timer only after the save: orbax fetches
                    # the device state synchronously, and that must not be
                    # charged to the next window
                    win_t0 = time.perf_counter()
    except BaseException as e:
        loop_error = e   # frame-scoped, unlike sys.exc_info() — a caller
        raise            # invoking train() inside an except must not
        # make the success path look like the error path
    finally:
        # failures must not leak the prefetch threads / augment worker
        # processes / shard fds / metric and TB event file handles (train
        # is called repeatedly in-process by katib studies and benchmarks)
        if dev_iter is not None:
            dev_iter.close()    # release the staged device batches first
        if data_source is not None:
            data_source.close()
        if eval_source is not None:
            eval_source.close()
        guard.uninstall()
        if loop_error is not None:
            # the crash dump: the ring's last records + the in-progress
            # stage say WHERE the loop died (the SIGTERM dump rides the
            # signal handler; this is its non-signal sibling)
            recorder.dump(dump_tracer, "crash",
                          error=f"{type(loop_error).__name__}: "
                                f"{loop_error}")
        if tracer is not None:
            _emit_ckpt_spans(ckpt, tracer)
            attrs = {"preempted": preempted}
            if anomaly is not None:
                attrs["anomaly"] = anomaly.kind
            if loop_error is not None:
                attrs["error"] = f"{type(loop_error).__name__}: {loop_error}"
            try:
                attrs["step"] = int(state.step)
            except Exception:  # noqa: BLE001 — a dead backend mid-error
                pass           # handling must not mask the loop error
            tracer.event("train-done", **attrs)
            tracer.close()
        if dump_tracer is not None:
            dump_tracer.close()
        if obs_server is not None:
            obs_server.stop()
        if comm_series is not None:
            # job teardown prunes the comm series (the kftpu_job_phase
            # rule): a later train() in this process must not inherit
            # this step's comm profile on its /metrics
            comm_series.prune()
        save_error: Optional[Exception] = None
        if ckpt is not None:
            try:
                ckpt.wait()   # surfaces async background-save failures
            except Exception as e:  # noqa: BLE001
                if loop_error is None:
                    save_error = e
                else:   # a loop error is already propagating; don't mask
                    log.warning("checkpoint wait failed during error "
                                "handling: %s", e)
            try:
                ckpt.close()
            except Exception as e:  # noqa: BLE001 — close is best-effort
                log.warning("checkpoint close failed: %s", e)
        mlog.close()
        if save_error is not None:
            # on the success path a failed (possibly forced final) save
            # MUST fail the run — "success" with a missing checkpoint
            # breaks the zero-lost-steps resume guarantee. Every handle
            # above is already closed.
            raise save_error
    summary = mlog.summary(warmup=1)
    # Under a katib study the operator injects KFTPU_STUDY/KFTPU_TRIAL (+
    # vizier URL); report the final metrics as the trial observation — the
    # TPU-native metrics-collector contract (katib/vizier.py). No-op
    # otherwise.
    if ctx.process_id == 0 and os.environ.get("KFTPU_STUDY"):
        try:
            from ..katib.vizier import report_observation
            for mname, mval in {**last_metrics,
                                "examples_per_sec":
                                    summary["examples_per_sec"]}.items():
                report_observation(mname, float(mval),
                                   step=summary["steps"])
        except Exception as e:  # noqa: BLE001 - reporting must not fail runs
            log.warning("observation report failed: %s", e)
    if preempted:
        log.warning("preempted at step %d; checkpoint saved, exiting "
                    "cleanly for gang-restart resume", int(state.step))
    return TrainResult(
        steps=summary["steps"],
        examples_per_sec=summary["examples_per_sec"],
        mean_step_time_s=summary["mean_step_time_s"],
        final_metrics=last_metrics,
        preempted=preempted,
        first_window_s=summary.get("first_window_s", 0.0),
        time_to_first_step_s=first_step_s,
        start_kind=start_kind,
        anomaly=anomaly.to_dict() if anomaly is not None else None,
    )


def main(argv=None) -> int:
    # force: importing jax/orbax can install a root handler first, which
    # would turn this into a no-op and silence the worker entirely
    logging.basicConfig(level=logging.INFO, force=True)
    p = argparse.ArgumentParser(description="kubeflow-tpu training worker")
    p.add_argument("--workload", default="resnet50", choices=sorted(WORKLOADS))
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--global-batch", type=int, default=64)
    p.add_argument("--learning-rate", type=float, default=0.1)
    p.add_argument("--checkpoint-dir")
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument("--no-resume", action="store_true")
    p.add_argument("--resume-from",
                   help="checkpoint dir to restore from before the loop "
                        "(defaults to $KFTPU_RESUME_FROM)")
    p.add_argument("--metrics-path")
    p.add_argument("--tensorboard-dir",
                   help="write TB scalar events here (defaults to "
                        "$KFTPU_TB_DIR; the tensorboard component's "
                        "--logdir)")
    p.add_argument("--profile-dir")
    p.add_argument("--span-path", default=None,
                   help="JSONL sink for trace spans (defaults to "
                        "$KFTPU_SPAN_PATH; the operator renders "
                        "spec.observability.spanPath and the job's "
                        "$KFTPU_TRACE_ID so worker windows stitch onto "
                        "the control plane's queued/bound/running "
                        "timeline — docs/operations.md Observability)")
    p.add_argument("--obs-metrics-port", type=int, default=None,
                   help="serve this worker's /metrics here (defaults to "
                        "$KFTPU_OBS_METRICS_PORT or off)")
    p.add_argument("--aot", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="AOT warm start: load the keyed serialized step "
                        "executable from --aot-dir (skipping XLA "
                        "entirely on rebind/resize) or compile+export "
                        "it on first bind; falls back to the persistent "
                        "compile cache, then a fresh compile (defaults "
                        "to $KFTPU_AOT or off — docs/operations.md "
                        "'Warm starts and the compile cache')")
    p.add_argument("--aot-dir", default=None,
                   help="where the serialized step executables live "
                        "(defaults to $KFTPU_AOT_DIR or "
                        "<checkpointDir>/.jax-aot-executables)")
    p.add_argument("--sync-every", type=int, default=10,
                   help="host-sync (and metric-fetch) interval in steps")
    p.add_argument("--data-dir",
                   help="ImageNet-style record-shard dir (defaults to "
                        "$KFTPU_DATA_DIR); synthetic data when unset")
    p.add_argument("--input-workers", type=int, default=None,
                   help="decode+augment worker processes feeding the "
                        "shared-memory input ring (0 = in-process "
                        "prefetch thread; defaults to "
                        "$KFTPU_INPUT_WORKERS or 0)")
    p.add_argument("--device-prefetch", type=int, default=None,
                   help="device batches staged ahead of the step via "
                        "async device_put so host→device copies overlap "
                        "compute (0 = place on the critical path; "
                        "defaults to $KFTPU_DEVICE_PREFETCH or 2)")
    p.add_argument("--num-microbatches", type=int, default=4,
                   help="GPipe microbatches (pipelined workloads)")
    p.add_argument("--multislice-pipeline", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="MPMD pipeline-over-DCN: one program per slice "
                        "with explicit activation/grad transfers and a "
                        "1F1B microbatch schedule, instead of one SPMD "
                        "program resharding across the DCN boundary "
                        "(defaults to $KFTPU_MULTISLICE_PIPELINE or "
                        "off — docs/training.md 'Multi-slice "
                        "training')")
    p.add_argument("--multislice-microbatches", type=int, default=None,
                   help="microbatches per step for the MPMD schedule "
                        "(defaults to $KFTPU_MULTISLICE_MICROBATCHES, "
                        "then 4x the slice count; bubble fraction is "
                        "(S-1)/(M+S-1))")
    # training recipe (the tf_cnn_benchmarks flag surface, runtime/recipe.py)
    from .recipe import (ATTENTION_KERNELS, OPTIMIZER_KERNELS, OPTIMIZERS,
                         SCHEDULES, SERVING_KERNELS, WEIGHT_UPDATE_MODES)
    p.add_argument("--weight-update", default=None,
                   choices=WEIGHT_UPDATE_MODES,
                   help="optimizer-update layout across data-parallel "
                        "replicas: 'sharded' = ZeRO-2 (reduce-scatter "
                        "grads, 1/N optimizer state per replica, "
                        "all-gather params — same numerics, ~1/N the "
                        "optimizer HBM traffic); defaults to "
                        "$KFTPU_WEIGHT_UPDATE or 'replicated'")
    p.add_argument("--optimizer", default="momentum", choices=OPTIMIZERS)
    p.add_argument("--lr-schedule", default="constant", choices=SCHEDULES)
    p.add_argument("--warmup-steps", type=int, default=0)
    p.add_argument("--weight-decay", type=float, default=0.0)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--label-smoothing", type=float, default=0.0)
    p.add_argument("--scale-lr-by-batch", action="store_true",
                   help="linear-scaling rule: lr *= global_batch/256")
    p.add_argument("--eval-every", type=int, default=0,
                   help="run the eval pass every N steps (0 = off)")
    p.add_argument("--eval-batches", type=int, default=8,
                   help="batches per eval pass; 0 = the full holdout "
                        "(use for the final acceptance number)")
    p.add_argument("--eval-data-dir",
                   help="held-out shard dir (defaults to "
                        "$KFTPU_EVAL_DATA_DIR); synthetic eval when unset")
    p.add_argument("--fused-blocks", action="store_true",
                   help="opt-in ghost-BN fused bottleneck kernels "
                        "(resnet>=50): per-tile BN statistics, fewer HBM "
                        "passes per step (docs/training.md)")
    p.add_argument("--fused-tile-bt", type=int, default=0,
                   help="ghost-batch tile size for --fused-blocks "
                        "(0 = auto by VMEM budget)")
    p.add_argument("--kernel-attention", default=None,
                   choices=list(ATTENTION_KERNELS),
                   help="attention kernel tier for transformer "
                        "workloads (default $KFTPU_KERNEL_ATTENTION "
                        "or einsum); baked into the recipe "
                        "fingerprint + AOT step key")
    p.add_argument("--kernel-optimizer", default=None,
                   choices=list(OPTIMIZER_KERNELS),
                   help="optimizer kernel tier: fused_adam runs the "
                        "fused Pallas update (requires --optimizer "
                        "adam; default $KFTPU_KERNEL_OPTIMIZER or "
                        "stock)")
    p.add_argument("--kernel-serving", default=None,
                   choices=list(SERVING_KERNELS),
                   help="serving kernel tier recorded for this job "
                        "(int8 = quantized serving behind the parity "
                        "gate; default $KFTPU_KERNEL_SERVING or stock)")
    p.add_argument("--integrity", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="numeric-integrity sentinel: NaN/Inf, loss-"
                        "spike, and cross-replica-agreement detectors "
                        "over the window-drained metrics; a trip exits "
                        "76 for last-known-good rollback (default "
                        "$KFTPU_INTEGRITY or off — docs/operations.md "
                        "'Numeric integrity')")
    p.add_argument("--integrity-spike-z", type=float, default=None,
                   help="z-score threshold for the loss-spike detector "
                        "(default $KFTPU_INTEGRITY_SPIKE_Z or 8.0)")
    p.add_argument("--integrity-window", type=int, default=None,
                   help="EWMA window (steps) for the spike baseline; "
                        "no spike trips until it fills (default "
                        "$KFTPU_INTEGRITY_WINDOW or 32)")
    p.add_argument("--integrity-check-every", type=int, default=None,
                   help="detector cadence in steps — caps --sync-every "
                        "so detection latency is bounded (default "
                        "$KFTPU_INTEGRITY_CHECK_EVERY or 10)")
    p.add_argument("--runtime-schedule", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="feed lr/warmup/total-steps to the optimizer as "
                        "runtime state instead of traced constants so "
                        "hyperparameter-sweep trials share one compiled "
                        "executable (default $KFTPU_RUNTIME_SCHEDULE or "
                        "off; experiment trials set it — "
                        "docs/operations.md 'Hyperparameter search')")
    args = p.parse_args(argv)
    workload_kwargs = {}
    if args.workload in _PIPELINED_WORKLOADS:
        workload_kwargs["num_microbatches"] = args.num_microbatches
    if args.fused_blocks:
        if args.workload not in _IMAGE_WORKLOADS or \
                int(args.workload.removeprefix("resnet")) < 50:
            p.error("--fused-blocks applies to bottleneck resnets "
                    "(depth >= 50) only")
        workload_kwargs["fused"] = True
        if args.fused_tile_bt:
            workload_kwargs["fused_tile_bt"] = args.fused_tile_bt
    result = train(
        workload=args.workload, steps=args.steps,
        global_batch=args.global_batch, learning_rate=args.learning_rate,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every, resume=not args.no_resume,
        resume_from=args.resume_from,
        metrics_path=args.metrics_path, profile_dir=args.profile_dir,
        span_path=args.span_path,
        obs_metrics_port=args.obs_metrics_port,
        tensorboard_dir=args.tensorboard_dir,
        workload_kwargs=workload_kwargs, sync_every=args.sync_every,
        data_dir=args.data_dir,
        input_workers=args.input_workers,
        device_prefetch=args.device_prefetch,
        optimizer=args.optimizer, lr_schedule=args.lr_schedule,
        warmup_steps=args.warmup_steps, weight_decay=args.weight_decay,
        momentum=args.momentum, label_smoothing=args.label_smoothing,
        scale_lr_by_batch=args.scale_lr_by_batch,
        eval_every=args.eval_every, eval_batches=args.eval_batches,
        eval_data_dir=args.eval_data_dir,
        weight_update=args.weight_update,
        aot=args.aot, aot_dir=args.aot_dir,
        multislice_pipeline=args.multislice_pipeline,
        multislice_microbatches=args.multislice_microbatches,
        kernel_attention=args.kernel_attention,
        kernel_optimizer=args.kernel_optimizer,
        kernel_serving=args.kernel_serving,
        integrity=args.integrity,
        integrity_spike_z=args.integrity_spike_z,
        integrity_window=args.integrity_window,
        integrity_check_every=args.integrity_check_every,
        runtime_schedule=args.runtime_schedule)
    log.info("done: %d steps, %.1f examples/sec", result.steps,
             result.examples_per_sec)
    if result.anomaly:
        from .sentinel import ANOMALY_EXIT_CODE
        return ANOMALY_EXIT_CODE
    return PREEMPTED_EXIT_CODE if result.preempted else 0


if __name__ == "__main__":
    raise SystemExit(main())
