"""Worker bootstrap: topology-contract env → jax.distributed → Mesh.

The TPU-native analog of launcher.py:68-88 (TF_CONFIG → CLI flags → TF gRPC
server): the operator rendered KFTPU_* env (api.topology.TopologyContract);
this module consumes it, initializes the JAX distributed runtime (the
coordinator replaces the PS/hostfile machinery), and builds the global mesh.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass
from typing import Optional

import jax
from jax.sharding import Mesh

from ..api.topology import TopologyContract, parse_topology
from ..api.trainingjob import ShardingSpec
from ..parallel.mesh import build_mesh

log = logging.getLogger(__name__)

ENV_SHARDING = "KFTPU_SHARDING"


@dataclass
class WorkerContext:
    contract: Optional[TopologyContract]
    sharding: ShardingSpec
    mesh: Mesh
    process_id: int
    num_processes: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def sharding_from_env(env) -> ShardingSpec:
    raw = env.get(ENV_SHARDING)
    if not raw:
        return ShardingSpec()
    sizes = json.loads(raw)
    return ShardingSpec(**{k: int(v) for k, v in sizes.items()})


def initialize(env=None, strict: bool = False) -> WorkerContext:
    """Bring up the worker. With no contract env (local dev, tests), builds a
    single-process mesh over whatever devices are visible.

    strict=True enforces that visible devices match the contract (production
    pods); strict=False logs and falls back to the visible device count
    (dev machines, CPU meshes).
    """
    env = env if env is not None else os.environ
    contract = None
    if TopologyContract.ENV_TOPOLOGY in env:
        contract = TopologyContract.from_env(env)
        if contract.num_processes > 1:
            # The gang's rendezvous: every pod blocks here until the whole
            # slice is up — the runtime-side half of gang scheduling.
            jax.distributed.initialize(
                coordinator_address=contract.coordinator_address,
                num_processes=contract.num_processes,
                process_id=contract.process_id,
            )
    sharding = sharding_from_env(env)
    if contract is not None:
        expected = contract.slice_topology.num_chips * contract.num_slices
        visible = len(jax.devices())
        if visible != expected:
            msg = (f"contract promises {expected} chips, jax sees {visible}")
            if strict:
                raise RuntimeError(msg)
            log.warning("%s — falling back to visible devices", msg)
            sharding = _refit_sharding(sharding, visible)
    mesh = build_mesh(sharding)
    return WorkerContext(
        contract=contract,
        sharding=sharding,
        mesh=mesh,
        process_id=contract.process_id if contract else jax.process_index(),
        num_processes=contract.num_processes if contract else jax.process_count(),
    )


def _refit_sharding(sharding: ShardingSpec, num_devices: int) -> ShardingSpec:
    """Shrink a sharding spec to a smaller device count, preserving axis
    ratios where possible (dev fallback only)."""
    try:
        sharding.resolve(num_devices)
        return sharding
    except ValueError:
        log.warning("sharding %s does not fit %d devices; using pure DP",
                    sharding.axis_sizes(), num_devices)
        return ShardingSpec()


def context_for_topology(name: str, sharding: Optional[ShardingSpec] = None
                         ) -> WorkerContext:
    """Dev helper: build a context as if running on the named topology,
    over the locally visible devices (e.g. 8 virtual CPU devices)."""
    topo = parse_topology(name)
    sharding = sharding or ShardingSpec()
    mesh = build_mesh(sharding)
    contract = TopologyContract(
        coordinator_address="localhost:8476", num_processes=1, process_id=0,
        slice_topology=topo)
    return WorkerContext(contract=contract, sharding=sharding, mesh=mesh,
                         process_id=0, num_processes=1)


def main(argv=None) -> int:
    """The warm-pod entrypoint (scheduler/warmpool.py build_warm_pod):
    ``--prewarm`` initializes the TPU backend and the persistent compile
    cache, then idles until adopted or retired — the whole point is that
    backend bring-up and cache mount are PAID before a gang lands on
    this host. SIGTERM (retirement / adoption teardown) exits cleanly."""
    import argparse
    p = argparse.ArgumentParser(description="kubeflow-tpu host bootstrap")
    p.add_argument("--prewarm", action="store_true",
                   help="initialize backend + compile cache, then idle "
                        "(the warm-pod pool's pre-initialized state)")
    args = p.parse_args(argv)
    if not args.prewarm:
        p.error("nothing to do (did you mean --prewarm?)")
    from .compile_cache import enable_compilation_cache
    enable_compilation_cache()
    initialize()
    log.info("prewarm: backend up, cache mounted; idling until adopted")
    import signal
    import threading
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
