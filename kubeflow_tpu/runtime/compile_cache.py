"""Persistent XLA compilation cache (startup→first-step latency killer).

The reference has no analog — its workloads pay TF graph-build each start
— but on TPU the first pjit step costs tens of seconds of XLA compile
(69s measured startup→first-step, PERF.md), and a gang restart or warm
start repeats it identically. JAX's persistent compilation cache
serializes compiled executables keyed by (HLO, compile options, jaxlib);
pointing it at the checkpoint volume makes every restart after the first
a cache hit.

Wiring: the TPUJob operator renders ``KFTPU_COMPILE_CACHE_DIR`` into the
gang's pods (defaulting to ``<checkpointDir>/.jax-compile-cache``,
controllers/tpujob.py); the worker and the serving servers call
``enable_compilation_cache()`` before their first jit. Serving reuses the
same mechanism for model-server cold-start (SURVEY §7 hard part e).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger(__name__)

COMPILE_CACHE_ENV = "KFTPU_COMPILE_CACHE_DIR"
# the default cache location on the checkpoint / model volume — the one
# place this name is defined (operator + serving manifest import it)
COMPILE_CACHE_SUBDIR = ".jax-compile-cache"

# Cluster-shared compile-cache service: the operator process carries
# KFTPU_SHARED_CACHE_ROOT (rendered onto its Deployment by
# manifests/training.py, backed by the tpu-compile-cache volume) and
# points EVERY gang of a namespace at <root>/<namespace> — so the first
# job to compile a program warms it for every other job, rebind, resize,
# and serving scale-up in that namespace, not just its own pod restarts.
SHARED_CACHE_ROOT_ENV = "KFTPU_SHARED_CACHE_ROOT"


def namespace_cache_dir(root: str, namespace: str) -> str:
    """One cache directory per namespace under the shared volume:
    namespaces are the tenancy boundary, and a cross-namespace cache
    would leak program shapes between tenants."""
    return root.rstrip("/") + "/" + namespace

# compiles cheaper than this recompile faster than a cache round-trip.
# KFTPU_COMPILE_CACHE_MIN_SECS overrides (tests pin 0: a warm process
# compiles the tiny CPU models in under a second, which silently skipped
# persistence and made cache assertions order-dependent)
_MIN_COMPILE_SECS = 1.0


def default_cache_dir(volume_dir: str) -> str:
    """`<volume>/.jax-compile-cache` with normalized slashes (works for
    local paths and gs://-style URIs alike)."""
    return volume_dir.rstrip("/") + "/" + COMPILE_CACHE_SUBDIR


def enable_compilation_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (defaults to
    $KFTPU_COMPILE_CACHE_DIR). No-op when neither is set. Returns the
    active cache dir, or None.

    Safe to call more than once and before/after backend init; failures
    downgrade to a warning — a broken cache volume must never kill a
    training gang or a model server."""
    path = path or os.environ.get(COMPILE_CACHE_ENV)
    if not path:
        return None
    import jax
    try:
        if "://" in path:
            # bucket URI (gs://...): JAX reaches it through etils.epath;
            # os.makedirs would create a bogus local 'gs:' directory and
            # the cache would silently land on ephemeral disk
            import etils.epath  # noqa: F401 — presence check
        else:
            os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(os.environ.get(
                              "KFTPU_COMPILE_CACHE_MIN_SECS",
                              _MIN_COMPILE_SECS)))
        # jax builds its cache object at the FIRST compile of the
        # process and latches (_cache_initialized): a process that
        # compiled anything before this call — repeated in-process
        # train() in katib studies and tests — latched a None cache and
        # would silently never persist to the newly-set dir. Reset the
        # latch so the config takes effect.
        try:
            from jax._src import compilation_cache as _cc
            if getattr(_cc, "_cache_initialized", False) and \
                    getattr(_cc, "_cache", None) is None:
                _cc.reset_cache()
        except Exception:  # noqa: BLE001 — private API, best effort
            pass
        install_compile_metrics()
        log.info("persistent compilation cache at %s", path)
        return path
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        log.warning("compilation cache disabled (%s): %s", path, e)
        return None


# ---------------------------------------------------------------- metrics

# module-level snapshot the listeners below keep current; compile_stats()
# copies it so the worker can diff before/after its first step (the
# cold-vs-warm evidence on the job's trace timeline) and the bench can
# assert "no XLA compile observed" on the AOT path. NOTE jax's
# backend_compile_duration event wraps compile-OR-cache-load (it fires
# on hits too), so the actual-XLA-compile count is derived:
# requests - hits (each cached compile request either hits or pays XLA).
_STATS = {"cache_hits": 0, "cache_misses": 0, "cache_requests": 0,
          "compiles_or_loads": 0, "compile_or_load_s": 0.0,
          "cache_load_s": 0.0}
_METRICS_INSTALLED = False

# the jax.monitoring event names this module consumes (jax emits them
# from compiler.py / compilation_cache.py / dispatch.py)
_EV_HIT = "/jax/compilation_cache/cache_hits"
_EV_MISS = "/jax/compilation_cache/cache_misses"
_EV_REQ = "/jax/compilation_cache/compile_requests_use_cache"
_EV_BACKEND = "/jax/core/compile/backend_compile_duration"
_EV_LOAD = "/jax/compilation_cache/cache_retrieval_time_sec"


def install_compile_metrics() -> None:
    """Register jax.monitoring listeners that mirror the persistent
    cache's hit/miss/load-time and every actual XLA backend compile into
    the shared obs registry (kftpu_compile_cache_events_total,
    kftpu_xla_backend_compiles_total, kftpu_xla_compile_seconds_total) —
    the per-job cold-vs-warm visibility the fleet dashboards read.
    Idempotent; safe before backend init."""
    global _METRICS_INSTALLED
    if _METRICS_INSTALLED:
        return
    from jax import monitoring

    from ..obs import registry as obsreg

    # families re-resolved per event (a dict lookup — idempotent
    # re-registration): the default registry is resettable (tests,
    # bench arms), and a family captured at install time would keep
    # feeding the dead registry after a reset

    # jax calls listeners INSIDE its compile/cache paths — a raising
    # listener breaks cache writes (observed: it aborts the cache put),
    # so both handlers are wrapped: metrics must never cost the cache
    _STAT_KEY = {_EV_HIT: ("hit", "cache_hits"),
                 _EV_MISS: ("miss", "cache_misses"),
                 _EV_REQ: ("request", "cache_requests")}

    def on_event(event: str, **kw) -> None:
        del kw
        try:
            name, stat = _STAT_KEY.get(event, (None, None))
            if name is None:
                return
            _STATS[stat] += 1
            obsreg.counter(
                "kftpu_compile_cache_events_total",
                "persistent compilation cache activity "
                "(hit/miss/request)",
                labels=("event",)).labels(event=name).inc()
        except Exception:  # noqa: BLE001 — never break a compile
            pass

    def on_duration(event: str, duration: float, **kw) -> None:
        del kw
        try:
            if event == _EV_BACKEND:
                _STATS["compiles_or_loads"] += 1
                _STATS["compile_or_load_s"] += duration
                stage = "compile_or_load"
            elif event == _EV_LOAD:
                _STATS["cache_load_s"] += duration
                stage = "cache_load"
            else:
                return
            obsreg.counter(
                "kftpu_xla_compile_seconds_total",
                "cumulative seconds by stage: jit compile-or-load "
                "(jax's event fires on cache hits too) vs the "
                "persistent-cache executable-load slice of it",
                labels=("stage",)).labels(stage=stage).inc(duration)
        except Exception:  # noqa: BLE001 — never break a compile
            pass

    monitoring.register_event_listener(on_event)
    monitoring.register_event_duration_secs_listener(on_duration)
    _METRICS_INSTALLED = True


def compile_stats() -> dict:
    """Snapshot of the process's compile/cache activity since
    install_compile_metrics() (all zeros before it). Diff two snapshots
    around a program region to attribute its compiles.
    ``xla_backend_compiles`` is the derived actual-XLA-compile count
    (cache requests that did NOT hit) — exact whenever the persistent
    cache is enabled, which every warm-start path guarantees."""
    out = dict(_STATS)
    out["xla_backend_compiles"] = max(
        0, out["cache_requests"] - out["cache_hits"])
    return out
