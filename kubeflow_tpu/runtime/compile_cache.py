"""Persistent XLA compilation cache (startup→first-step latency killer).

The reference has no analog — its workloads pay TF graph-build each start
— but on TPU the first pjit step costs tens of seconds of XLA compile
(69s measured startup→first-step, PERF.md), and a gang restart or warm
start repeats it identically. JAX's persistent compilation cache
serializes compiled executables keyed by (HLO, compile options, jaxlib);
pointing it at the checkpoint volume makes every restart after the first
a cache hit.

Wiring: the TPUJob operator renders ``KFTPU_COMPILE_CACHE_DIR`` into the
gang's pods (defaulting to ``<checkpointDir>/.jax-compile-cache``,
controllers/tpujob.py); the worker and the serving servers call
``enable_compilation_cache()`` before their first jit. Serving reuses the
same mechanism for model-server cold-start (SURVEY §7 hard part e).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger(__name__)

COMPILE_CACHE_ENV = "KFTPU_COMPILE_CACHE_DIR"
# the default cache location on the checkpoint / model volume — the one
# place this name is defined (operator + serving manifest import it)
COMPILE_CACHE_SUBDIR = ".jax-compile-cache"

# compiles cheaper than this recompile faster than a cache round-trip.
# KFTPU_COMPILE_CACHE_MIN_SECS overrides (tests pin 0: a warm process
# compiles the tiny CPU models in under a second, which silently skipped
# persistence and made cache assertions order-dependent)
_MIN_COMPILE_SECS = 1.0


def default_cache_dir(volume_dir: str) -> str:
    """`<volume>/.jax-compile-cache` with normalized slashes (works for
    local paths and gs://-style URIs alike)."""
    return volume_dir.rstrip("/") + "/" + COMPILE_CACHE_SUBDIR


def enable_compilation_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (defaults to
    $KFTPU_COMPILE_CACHE_DIR). No-op when neither is set. Returns the
    active cache dir, or None.

    Safe to call more than once and before/after backend init; failures
    downgrade to a warning — a broken cache volume must never kill a
    training gang or a model server."""
    path = path or os.environ.get(COMPILE_CACHE_ENV)
    if not path:
        return None
    import jax
    try:
        if "://" in path:
            # bucket URI (gs://...): JAX reaches it through etils.epath;
            # os.makedirs would create a bogus local 'gs:' directory and
            # the cache would silently land on ephemeral disk
            import etils.epath  # noqa: F401 — presence check
        else:
            os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(os.environ.get(
                              "KFTPU_COMPILE_CACHE_MIN_SECS",
                              _MIN_COMPILE_SECS)))
        # jax builds its cache object at the FIRST compile of the
        # process and latches (_cache_initialized): a process that
        # compiled anything before this call — repeated in-process
        # train() in katib studies and tests — latched a None cache and
        # would silently never persist to the newly-set dir. Reset the
        # latch so the config takes effect.
        try:
            from jax._src import compilation_cache as _cc
            if getattr(_cc, "_cache_initialized", False) and \
                    getattr(_cc, "_cache", None) is None:
                _cc.reset_cache()
        except Exception:  # noqa: BLE001 — private API, best effort
            pass
        log.info("persistent compilation cache at %s", path)
        return path
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        log.warning("compilation cache disabled (%s): %s", path, e)
        return None
