"""Numeric integrity sentinel: in-step anomaly detection (ISSUE 17).

Every fault the platform survives announces itself — a pod exits 75, a
heartbeat stops, a lease expires. A TPU host computing *wrong numbers*
(silent data corruption, a NaN-producing kernel, a loss blowup after a
bad batch) crashes nothing, so without this module every layer from the
chaos restarts to the health scoring is blind to it and the job burns
chip-hours training garbage.

The sentinel rides the worker's window drain (runtime/worker.py): the
loss / global-grad-norm floats are already fetched to host there, so
detection costs one host compare per closed window — no extra device
round trip. Detectors:

- NaN/Inf on loss and global grad norm (hard trips, no warmup).
- Rolling z-score spike on loss (EWMA mean/variance over
  ``window_steps``; trips only after the window has filled, and only on
  UPWARD spikes — a healthy loss curve descends, which reads as a
  negative z).
- Cross-replica agreement on replicated-math scalars: on the ZeRO-2
  path every replica recomputes the SAME global param sqnorm after the
  all-gather (runtime/trainstep.py exports the per-replica vector);
  disagreement beyond tolerance is SDC evidence that NAMES a replica,
  hence a host.

A trip produces an :class:`AnomalyEvidence` record the worker writes
into its pod annotation (api/trainingjob.py ANOMALY_ANNOTATION) before
exiting ``ANOMALY_EXIT_CODE`` — the operator's restart path reads it,
rolls the job back to the last-known-good checkpoint, and folds a
``numeric-anomaly`` health event onto the suspect host
(scheduler/health.py).

This module is deliberately jax-free: the operator imports the exit
code / evidence parser without pulling jax into the control plane.
"""
from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..obs import registry as obsreg

# worker exit status after a tripped detector: distinct from clean exit
# (0 = Succeeded completes the job) and from the preemption code 75 —
# logs must distinguish "my numbers went bad, roll me back" from "I was
# told to go". EX_PROTOCOL: the numbers broke the contract.
ANOMALY_EXIT_CODE = 76

# operator → worker rollback contract (controllers/tpujob.py renders
# these from the job's anomaly-rollback annotation; NOT spec knobs):
# restore the newest INTACT step <= KFTPU_RESUME_STEP (the LKG), then
# discard the tainted newer steps. KFTPU_REPLAY_RANGE ("lkg:trip") arms
# replay bisection: the worker re-runs the deterministic input pipeline
# over the suspect steps and, when the range replays clean with the
# suspect host evacuated, emits the bisection verdict span — converting
# "the job is cursed" into "host N is bad".
RESUME_STEP_ENV = "KFTPU_RESUME_STEP"
REPLAY_RANGE_ENV = "KFTPU_REPLAY_RANGE"

# detector kinds (the kftpu_anomaly_total{kind} label vocabulary; the
# "heartbeat-nan" kind is the operator-side flag for workers whose OWN
# sentinel is disabled — controllers/tpujob.py)
KIND_NAN_LOSS = "nan-loss"
KIND_NAN_GRAD = "nan-grad"
KIND_LOSS_SPIKE = "loss-spike"
KIND_REPLICA_SKEW = "replica-skew"
KIND_HEARTBEAT_NAN = "heartbeat-nan"
ANOMALY_KINDS = (KIND_NAN_LOSS, KIND_NAN_GRAD, KIND_LOSS_SPIKE,
                 KIND_REPLICA_SKEW, KIND_HEARTBEAT_NAN)

# defaults for the spec.integrity knobs (api/trainingjob.py
# IntegritySpec; docs/training.md). spikeZ=8 is deliberately wide: the
# false-positive budget is ZERO (a spurious trip costs a gang restart),
# and a real blowup clears z=8 by orders of magnitude against the tight
# variance of a converging loss.
DEFAULT_SPIKE_Z = 8.0
DEFAULT_WINDOW_STEPS = 32
DEFAULT_CHECK_EVERY = 10
# relative tolerance for the cross-replica agreement check: the compared
# quantity is bit-identical replicated math absent corruption, so the
# tolerance only has to absorb nondeterministic reduce orders
AGREEMENT_RTOL = 1e-3


def anomaly_counter():
    """The shared kftpu_anomaly_total{kind} counter handle (worker trips
    and the operator's heartbeat-NaN flag both feed it)."""
    return obsreg.counter(
        "kftpu_anomaly_total",
        "numeric anomalies detected, by detector kind",
        labels=("kind",))


def lkg_gauge():
    """kftpu_lkg_step: the newest last-known-good checkpoint step."""
    return obsreg.gauge(
        "kftpu_lkg_step",
        "newest last-known-good checkpoint step (sentinel-cleared)")


@dataclass
class AnomalyEvidence:
    """One tripped detector, in the shape the wire contract carries:
    worker pod annotation → operator condition/health event → dashboard
    panel. ``lkg`` is the rollback target the worker knew at trip time
    (None when no checkpoint had been cleared yet)."""

    kind: str
    step: int
    value: float
    lkg: Optional[int] = None
    detail: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {"kind": self.kind, "step": int(self.step),
             # NaN/Inf must survive strict-JSON consumers: stringify
             "value": repr(float(self.value)),
             "lkg": self.lkg if self.lkg is None else int(self.lkg)}
        if self.detail:
            d["detail"] = self.detail
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, raw: str) -> Optional["AnomalyEvidence"]:
        """Parse the annotation payload; None on garbage — a malformed
        annotation must degrade to "no anomaly evidence", never crash
        the operator's reconcile loop."""
        try:
            d = json.loads(raw)
            return cls(kind=str(d["kind"]), step=int(d["step"]),
                       value=float(d.get("value", "nan")),
                       lkg=None if d.get("lkg") is None
                       else int(d["lkg"]),
                       detail=dict(d.get("detail") or {}))
        except (KeyError, TypeError, ValueError):
            return None


def _bad(x: float) -> bool:
    return not math.isfinite(x)


class NumericSentinel:
    """Stateful per-worker detector bank over the window-drained host
    floats. ``observe`` returns evidence on the FIRST trip and arms
    nothing afterwards (the worker exits on a trip; a fresh process gets
    a fresh sentinel)."""

    def __init__(self, spike_z: float = DEFAULT_SPIKE_Z,
                 window_steps: int = DEFAULT_WINDOW_STEPS,
                 agreement_rtol: float = AGREEMENT_RTOL):
        if spike_z <= 0:
            raise ValueError(f"spike_z must be > 0, got {spike_z}")
        if window_steps < 2:
            raise ValueError(
                f"window_steps must be >= 2, got {window_steps}")
        self.spike_z = float(spike_z)
        self.window_steps = int(window_steps)
        self.agreement_rtol = float(agreement_rtol)
        # EWMA mean/variance of the loss, alpha = 2/(window+1) (the
        # classic span-EWMA); stats update only on ACCEPTED samples so
        # an anomalous value can never launder itself into the baseline
        self._alpha = 2.0 / (self.window_steps + 1.0)
        self._n = 0
        self._mean = 0.0
        self._var = 0.0
        self.trips = 0

    def _trip(self, kind: str, step: int, value: float,
              lkg: Optional[int], **detail) -> AnomalyEvidence:
        self.trips += 1
        anomaly_counter().labels(kind=kind).inc()
        return AnomalyEvidence(kind=kind, step=int(step),
                               value=float(value), lkg=lkg,
                               detail=detail)

    def observe(self, step: int, loss: Optional[float] = None,
                grad_norm: Optional[float] = None,
                replica_sqnorms: Optional[Sequence[float]] = None,
                lkg: Optional[int] = None) -> Optional[AnomalyEvidence]:
        """Feed one drained window's host floats; evidence on a trip,
        None when the window is clean (which is what promotes the
        preceding checkpoint to LKG — runtime/worker.py)."""
        if grad_norm is not None:
            g = float(grad_norm)
            if _bad(g):
                return self._trip(KIND_NAN_GRAD, step, g, lkg)
        if replica_sqnorms is not None:
            ev = self._check_agreement(step, replica_sqnorms, lkg)
            if ev is not None:
                return ev
        if loss is None:
            return None
        x = float(loss)
        if _bad(x):
            return self._trip(KIND_NAN_LOSS, step, x, lkg)
        # spike detection only once the window has filled: the first
        # window_steps samples SET the baseline (a fresh model's loss
        # cliff must not read as an anomaly)
        if self._n >= self.window_steps:
            sd = math.sqrt(max(self._var, 0.0))
            if sd > 0.0:
                z = (x - self._mean) / sd
                if z > self.spike_z:
                    return self._trip(KIND_LOSS_SPIKE, step, x, lkg,
                                      z=round(z, 2),
                                      mean=round(self._mean, 6),
                                      sd=round(sd, 6))
        delta = x - self._mean
        self._mean += self._alpha * delta
        self._var = (1.0 - self._alpha) * \
            (self._var + self._alpha * delta * delta)
        self._n += 1
        return None

    def _check_agreement(self, step: int, sqnorms: Sequence[float],
                         lkg: Optional[int]) -> Optional[AnomalyEvidence]:
        vals = [float(v) for v in sqnorms]
        if len(vals) < 2:
            return None
        for i, v in enumerate(vals):
            if _bad(v):
                return self._trip(KIND_REPLICA_SKEW, step, v, lkg,
                                  replica=i)
        med = sorted(vals)[len(vals) // 2]
        scale = max(abs(med), 1e-12)
        worst_i = max(range(len(vals)),
                      key=lambda i: abs(vals[i] - med))
        rel = abs(vals[worst_i] - med) / scale
        if rel > self.agreement_rtol:
            return self._trip(KIND_REPLICA_SKEW, step, vals[worst_i],
                              lkg, replica=worst_i,
                              rel=repr(rel), median=repr(med))
        return None


def parse_replay_range(raw: Optional[str]) -> Optional[tuple]:
    """Parse the KFTPU_REPLAY_RANGE contract ("lkg:trip"), None on
    absent/garbage — a bad annotation must not kill the gang."""
    if not raw:
        return None
    try:
        lo, hi = raw.split(":", 1)
        lo_i, hi_i = int(lo), int(hi)
    except ValueError:
        return None
    return (lo_i, hi_i) if hi_i > lo_i >= 0 else None


# -------------------------------------------------- numeric fault hook
# The chaos tier's injection contract (cluster/chaos.py NaNInjector /
# BitFlipGrad / LossSpikePoisoner arrange these around a training
# segment; cluster/ stays jax-free so the actual state surgery lives
# here, next to the detectors it exercises):
#   KFTPU_CHAOS_NUMERIC = "<kind>:<step>[:<scale>]"
#   KFTPU_CHAOS_NUMERIC_MARK = fire-marker path (fire count persists
#       across gang restarts — a replayed segment must not re-poison
#       itself forever, that is the whole point of rollback)
#   KFTPU_CHAOS_NUMERIC_FIRES = max fires (default 1; the BitFlipGrad
#       bisection drill uses 2: same-range second trip arms replay)
NUMERIC_FAULT_ENV = "KFTPU_CHAOS_NUMERIC"
NUMERIC_FAULT_MARK_ENV = "KFTPU_CHAOS_NUMERIC_MARK"
NUMERIC_FAULT_FIRES_ENV = "KFTPU_CHAOS_NUMERIC_FIRES"
NUMERIC_FAULT_KINDS = ("nan", "spike", "bitflip")


class NumericFaultHook:
    """Worker-side poisoner: at the armed step, corrupt the train state
    the way the named hardware/software fault would. Off (None from
    from_env) unless the chaos env contract is present."""

    def __init__(self, kind: str, at_step: int, scale: float,
                 mark_path: Optional[str], max_fires: int = 1):
        if kind not in NUMERIC_FAULT_KINDS:
            raise ValueError(f"unknown numeric fault kind {kind!r} "
                             f"(choose from {NUMERIC_FAULT_KINDS})")
        self.kind = kind
        self.at_step = int(at_step)
        self.scale = float(scale)
        self.mark_path = mark_path
        self.max_fires = int(max_fires)

    @classmethod
    def from_env(cls, env=None) -> Optional["NumericFaultHook"]:
        env = os.environ if env is None else env
        raw = env.get(NUMERIC_FAULT_ENV)
        if not raw:
            return None
        parts = raw.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"{NUMERIC_FAULT_ENV} must be kind:step[:scale], "
                f"got {raw!r}")
        kind, at_step = parts[0], int(parts[1])
        scale = float(parts[2]) if len(parts) > 2 else \
            {"nan": float("nan"), "spike": 8.0, "bitflip": 1.25}[kind]
        fires = int(env.get(NUMERIC_FAULT_FIRES_ENV) or 1)
        return cls(kind, at_step, scale,
                   env.get(NUMERIC_FAULT_MARK_ENV), max_fires=fires)

    def _fires(self) -> int:
        if not self.mark_path:
            return 0
        try:
            with open(self.mark_path, encoding="utf-8") as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def should_fire(self, step: int) -> bool:
        return step == self.at_step and self._fires() < self.max_fires

    def _record_fire(self) -> None:
        if not self.mark_path:
            return
        n = self._fires() + 1
        tmp = f"{self.mark_path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(n))
        os.replace(tmp, self.mark_path)

    def poison(self, state, step: int):
        """Corrupt ``state.params`` in place of the fault this hook
        models; returns the (possibly replaced) state. jax import is
        lazy — the module stays importable in the control plane."""
        if not self.should_fire(step):
            return state
        import dataclasses

        import jax
        if self.kind == "nan":
            # a NaN-producing kernel: the next loss is NaN
            factor = float("nan")
        else:
            # spike: a bad batch / blowup (big jump, finite); bitflip:
            # an exponent-bit SDC on one host (modest jump the z-score
            # must still catch)
            factor = self.scale
        params = jax.tree.map(
            lambda x: (x * factor).astype(x.dtype), state.params)
        self._record_fire()
        return dataclasses.replace(state, params=params)
