"""Training recipes: optimizers, LR schedules, regularization.

The reference's vehicle (tf-controller-examples/tf-cnn running
tf_cnn_benchmarks) exposes the classic ImageNet training surface as CLI
flags — --optimizer, learning-rate warmup/decay, --weight_decay — and its
ResNet-50 recipe (lr = 0.1·batch/256 with warmup, step or cosine decay,
weight decay 1e-4 on kernels only, label smoothing 0.1) is what the 76%
top-1 acceptance target assumes. This module is that surface rebuilt
optax-native; runtime/worker.py maps its CLI flags straight onto
``make_optimizer``.

TPU notes: everything here composes into ONE optax transform executed
inside the jitted train step — schedules are traced functions of the step
counter (no host-side LR updates to sync), and the decay mask is a static
pytree so XLA sees a fixed program.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import optax

OPTIMIZERS = ("sgd", "momentum", "nesterov", "adam", "adamw", "lars",
              "rmsprop")
SCHEDULES = ("constant", "cosine", "step", "linear")
# How the optimizer update is laid out across data-parallel replicas
# (ZeRO-2 "sharded" vs "replicated"): the vocabulary lives in the jax-free
# api layer so manifest admission can validate it without importing jax;
# re-exported here because it is a step-engine knob (PERF.md).
from ..api.trainingjob import (WEIGHT_UPDATE_MODES,  # noqa: F401,E402
                               validate_weight_update)
# Kernel-tier vocabularies (ISSUE 16): same jax-free admission-layer
# home, re-exported here because the optimizer rung is a recipe knob
# (make_optimizer(kernels=...)).
from ..api.trainingjob import (ATTENTION_KERNELS,  # noqa: F401,E402
                               OPTIMIZER_KERNELS, SERVING_KERNELS)

# classic ImageNet step-decay epochs 30/60/80 of 90, as fractions of the run
STEP_BOUNDARIES = (1 / 3, 2 / 3, 8 / 9)
STEP_FACTOR = 0.1


def recipe_fingerprint(**knobs) -> str:
    """Stable hash of the WHOLE recipe — model/workload identity,
    optimizer family and its scalars, LR schedule constants, weight
    decay, label smoothing. This is trial/run identity (checkpoints,
    ledgers, logs). For the AOT executable / compile-cache key the
    worker uses ``compile_shape_fingerprint`` instead when the tuned
    scalars (lr, warmup, total steps) are RUNTIME inputs rather than
    trace-time constants — see RUNTIME_CONSTANT_KNOBS. Values must be
    JSON-able; unhashable knobs fall back to repr so a novel workload
    kwarg degrades to a unique (never-colliding-by-silence) fingerprint
    rather than an error."""
    import hashlib
    import json

    def default(o):  # non-JSON knob: repr is stable enough for a key
        return repr(o)

    blob = json.dumps(knobs, sort_keys=True, default=default).encode()
    return hashlib.sha256(blob).hexdigest()[:24]


# The tuned-scalar knobs that stop being compile-time constants when the
# runtime schedule is active (make_optimizer(runtime_schedule=True)):
# they live in the optimizer STATE as device scalars, so the traced HLO
# is identical for any value — and they must NOT key the AOT executable
# or the persistent compile cache, or a hyperparameter sweep would pay
# one cold compile per trial for byte-identical programs. Accepts both
# the worker's kwarg names and the generic shorthand used in tests.
RUNTIME_CONSTANT_KNOBS = frozenset({
    "learning_rate", "lr", "warmup_steps", "steps", "total_steps"})


def split_recipe_knobs(knobs: dict) -> tuple[dict, dict]:
    """Partition recipe knobs into (compile-shape, runtime-constants).
    The compile-shape side is everything that changes the traced
    program; the runtime side is the tuned scalars a runtime-schedule
    trial feeds in as data."""
    shape = {k: v for k, v in knobs.items()
             if k not in RUNTIME_CONSTANT_KNOBS}
    runtime = {k: v for k, v in knobs.items()
               if k in RUNTIME_CONSTANT_KNOBS}
    return shape, runtime


def compile_shape_fingerprint(**knobs) -> str:
    """The AOT/compile-cache half of the split key: hash of every knob
    EXCEPT the runtime constants. Two trials differing only in lr /
    warmup / total steps share this fingerprint — and therefore (with
    the runtime schedule active) one cached executable."""
    shape, _ = split_recipe_knobs(knobs)
    return recipe_fingerprint(**shape)


def runtime_constants_key(**knobs) -> str:
    """Hash of ONLY the runtime-constant knobs — the other half of the
    split: trial identity within a shared compile shape (ledgers, PBT
    lineage), never part of the executable key."""
    _, runtime = split_recipe_knobs(knobs)
    return recipe_fingerprint(**runtime)


def scale_lr(base_lr: float, global_batch: int, base_batch: int = 256
             ) -> float:
    """Linear-scaling rule (Goyal et al.): lr = base · batch/256."""
    return base_lr * global_batch / base_batch


def lr_schedule(name: str, base_lr: float, total_steps: int,
                warmup_steps: int = 0, *, end_scale: float = 0.0,
                boundaries: tuple = STEP_BOUNDARIES,
                factor: float = STEP_FACTOR) -> optax.Schedule:
    """A schedule over the whole run: linear warmup from 0 to base_lr over
    ``warmup_steps``, then the named decay over the remaining steps."""
    if name not in SCHEDULES:
        raise ValueError(f"schedule {name!r} not one of {SCHEDULES}")
    if warmup_steps < 0 or total_steps <= 0:
        raise ValueError("need total_steps > 0 and warmup_steps >= 0")
    warmup_steps = min(warmup_steps, total_steps)
    decay_steps = max(total_steps - warmup_steps, 1)

    if name == "constant":
        decay = optax.constant_schedule(base_lr)
    elif name == "cosine":
        decay = optax.cosine_decay_schedule(
            base_lr, decay_steps, alpha=end_scale)
    elif name == "linear":
        decay = optax.linear_schedule(
            base_lr, base_lr * end_scale, decay_steps)
    else:  # step
        # round (not truncate) so 2/3·90 lands on 60, not 59; very short
        # runs can collide two boundaries on one step — compound the
        # factors instead of silently dropping one
        bounds: dict[int, float] = {}
        for b in boundaries:
            k = max(round(b * decay_steps), 1)
            bounds[k] = bounds.get(k, 1.0) * factor
        decay = optax.piecewise_constant_schedule(base_lr, bounds)

    if warmup_steps == 0:
        return decay
    warmup = optax.linear_schedule(0.0, base_lr, warmup_steps)
    return optax.join_schedules([warmup, decay], [warmup_steps])


def _runtime_lr_at(name: str, count, base_lr, warmup_steps, total_steps, *,
                   end_scale: float = 0.0,
                   boundaries: tuple = STEP_BOUNDARIES,
                   factor: float = STEP_FACTOR):
    """``lr_schedule`` re-derived as traced jnp math over RUNTIME scalar
    inputs. The schedule NAME (and step boundaries/factor) stay static —
    they change the program — but base_lr/warmup/total arrive as device
    scalars, so every lr-variant trial lowers to byte-identical HLO.
    Semantics mirror the optax chain exactly: linear 0→base warmup over
    min(warmup, total) steps, then the named decay over
    max(total−warmup, 1) steps; step-decay factors apply at
    count ≥ boundary and compound on collision."""
    import jax.numpy as jnp
    if name not in SCHEDULES:
        raise ValueError(f"schedule {name!r} not one of {SCHEDULES}")
    count = jnp.asarray(count, jnp.float32)
    base = jnp.asarray(base_lr, jnp.float32)
    total = jnp.maximum(jnp.asarray(total_steps, jnp.float32), 1.0)
    warm = jnp.clip(jnp.asarray(warmup_steps, jnp.float32), 0.0, total)
    decay_steps = jnp.maximum(total - warm, 1.0)
    t = jnp.clip((count - warm) / decay_steps, 0.0, 1.0)

    if name == "constant":
        decayed = base
    elif name == "cosine":
        cosine = 0.5 * (1.0 + jnp.cos(jnp.pi * t))
        decayed = base * ((1.0 - end_scale) * cosine + end_scale)
    elif name == "linear":
        decayed = base + (base * end_scale - base) * t
    else:  # step
        decayed = base
        for b in boundaries:
            k = jnp.maximum(jnp.round(b * decay_steps), 1.0)
            decayed = decayed * jnp.where((count - warm) >= k, factor, 1.0)

    warm_frac = jnp.clip(count / jnp.maximum(warm, 1.0), 0.0, 1.0)
    return jnp.where(count < warm, base * warm_frac, decayed)


class RuntimeLRState(NamedTuple):
    """Tuned scalars ride in the optimizer STATE — jitted-step inputs,
    not trace-time constants — which is the whole trick: the compiled
    executable is shared across trials, each trial's values live in its
    own state (and checkpoint, so restores keep the trial's schedule)."""
    count: object   # int32 scalar: updates applied so far
    base_lr: object       # float32 scalar
    warmup_steps: object  # float32 scalar
    total_steps: object   # float32 scalar


def scale_by_runtime_lr(schedule: str = "constant",
                        learning_rate: float = 0.1,
                        total_steps: int = 1, warmup_steps: int = 0, *,
                        end_scale: float = 0.0,
                        boundaries: tuple = STEP_BOUNDARIES,
                        factor: float = STEP_FACTOR
                        ) -> "optax.GradientTransformation":
    """Multiply updates by lr(count) computed from runtime state. Chains
    AFTER a base optimizer built at lr=1.0: every stock optimizer here
    ends in scale(-lr), so unit-lr descent direction × runtime lr is
    mathematically identical to the baked schedule (momentum traces and
    adam statistics accumulate pre-scale either way). The multiply is
    POSITIVE — the base chain already applied the minus sign."""
    import jax.numpy as jnp
    if schedule not in SCHEDULES:
        raise ValueError(f"schedule {schedule!r} not one of {SCHEDULES}")

    def init_fn(params):
        del params
        return RuntimeLRState(
            count=jnp.zeros([], jnp.int32),
            base_lr=jnp.asarray(learning_rate, jnp.float32),
            warmup_steps=jnp.asarray(warmup_steps, jnp.float32),
            total_steps=jnp.asarray(total_steps, jnp.float32))

    def update_fn(updates, state, params=None):
        del params
        lr = _runtime_lr_at(schedule, state.count, state.base_lr,
                            state.warmup_steps, state.total_steps,
                            end_scale=end_scale, boundaries=boundaries,
                            factor=factor)
        updates = jax.tree.map(lambda u: (lr * u.astype(jnp.float32)
                                          ).astype(u.dtype), updates)
        return updates, state._replace(count=state.count + 1)

    return optax.GradientTransformation(init_fn, update_fn)


def decay_mask(params) -> object:
    """Weight decay applies to kernels only — never to biases or
    BatchNorm scales/offsets (rank-1 leaves), the standard ResNet rule."""
    return jax.tree.map(lambda p: getattr(p, "ndim", 0) > 1, params)


def make_optimizer(
    name: str = "momentum",
    learning_rate: float = 0.1,
    *,
    schedule: str = "constant",
    total_steps: int = 1,
    warmup_steps: int = 0,
    weight_decay: float = 0.0,
    momentum: float = 0.9,
    grad_clip: Optional[float] = 1.0,
    kernels: str = "stock",
    runtime_schedule: bool = False,
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """One optax chain for the whole recipe. Returns (transform, schedule);
    the schedule is also returned alone so callers can log lr(step).

    ``kernels`` selects the optimizer rung of the kernel tier
    (OPTIMIZER_KERNELS): "fused_adam" replaces the
    add_decayed_weights+adam sub-chain with the single fused Pallas
    kernel (ops/fused_adam.py — parity ≤1e-5 vs this function's stock
    chain). Cross-leaf global-norm clipping stays a separate outer
    transform either way. The tier is baked into recipe_fingerprint by
    the worker, so a flip can never alias a cached executable.

    ``runtime_schedule`` builds the base optimizer at unit lr and chains
    ``scale_by_runtime_lr`` after it, moving lr/warmup/total_steps out of
    the traced constants and into optimizer state — the enabler for
    hyperparameter-sweep trials sharing one AOT executable (the worker
    keys the compile cache on ``compile_shape_fingerprint`` when this is
    on). Numerically identical to the baked schedule for every stock
    optimizer. Incompatible with 'fused_adam', which consumes the
    schedule inside the fused kernel."""
    if name not in OPTIMIZERS:
        raise ValueError(f"optimizer {name!r} not one of {OPTIMIZERS}")
    if kernels not in OPTIMIZER_KERNELS:
        raise ValueError(
            f"kernels.optimizer {kernels!r} not one of {OPTIMIZER_KERNELS}")
    if runtime_schedule and kernels == "fused_adam":
        # reject, don't silently downgrade: the fused kernel bakes
        # sched(count) into its launch, so "runtime" lr would be a lie
        raise ValueError(
            "runtime_schedule is incompatible with kernels.optimizer "
            "'fused_adam' (the fused kernel bakes the schedule); use the "
            "stock chain for swept trials")
    sched = lr_schedule(schedule, learning_rate, total_steps, warmup_steps)
    # With the runtime schedule, the inner optimizer runs at unit lr and
    # the trailing scale_by_runtime_lr supplies lr(count) from state.
    inner: object = 1.0 if runtime_schedule else sched

    if kernels == "fused_adam":
        # reject, don't silently downgrade: a requested fused tier that
        # quietly ran the stock chain would be invisible (the same rule
        # as multislice.microbatches-without-pipeline)
        if name != "adam":
            raise ValueError(
                f"kernels.optimizer 'fused_adam' requires optimizer "
                f"'adam', got {name!r}")
        from ..ops.fused_adam import fused_adam
        txs = []
        if grad_clip:
            txs.append(optax.clip_by_global_norm(grad_clip))
        txs.append(fused_adam(sched, weight_decay=weight_decay,
                              mask=decay_mask))
        return optax.chain(*txs), sched

    txs: list[optax.GradientTransformation] = []
    if grad_clip:
        txs.append(optax.clip_by_global_norm(grad_clip))
    # decoupled weight decay for adamw/lars (their own impls); classic
    # L2-into-gradient for the SGD family
    if weight_decay and name in ("sgd", "momentum", "nesterov", "rmsprop",
                                 "adam"):
        txs.append(optax.add_decayed_weights(weight_decay, mask=decay_mask))

    if name == "sgd":
        txs.append(optax.sgd(inner))
    elif name == "momentum":
        txs.append(optax.sgd(inner, momentum=momentum))
    elif name == "nesterov":
        txs.append(optax.sgd(inner, momentum=momentum, nesterov=True))
    elif name == "adam":
        txs.append(optax.adam(inner))
    elif name == "adamw":
        txs.append(optax.adamw(inner, weight_decay=weight_decay,
                               mask=decay_mask))
    elif name == "lars":
        # lars and rmsprop scale by lr BEFORE the momentum trace (the
        # trace accumulates lr-scaled updates), so the runtime scale
        # must sit in that same slot — a trailing multiply would change
        # the momentum dynamics under non-constant schedules.
        if runtime_schedule:
            txs.append(optax.add_decayed_weights(weight_decay,
                                                 mask=decay_mask))
            txs.append(optax.masked(   # optax.lars's trust_coefficient
                optax.scale_by_trust_ratio(trust_coefficient=0.001), True))
            txs.append(optax.scale(-1.0))
            txs.append(scale_by_runtime_lr(
                schedule, learning_rate, total_steps, warmup_steps))
            txs.append(optax.trace(decay=momentum))
        else:
            txs.append(optax.lars(sched, weight_decay=weight_decay,
                                  weight_decay_mask=decay_mask,
                                  momentum=momentum))
    elif name == "rmsprop":
        if runtime_schedule:
            txs.append(optax.scale_by_rms())
            txs.append(optax.scale(-1.0))
            txs.append(scale_by_runtime_lr(
                schedule, learning_rate, total_steps, warmup_steps))
            txs.append(optax.trace(decay=momentum))
        else:
            txs.append(optax.rmsprop(sched, momentum=momentum))
    if runtime_schedule and name not in ("lars", "rmsprop"):
        txs.append(scale_by_runtime_lr(
            schedule, learning_rate, total_steps, warmup_steps))
    return optax.chain(*txs), sched
