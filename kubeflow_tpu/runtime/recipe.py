"""Training recipes: optimizers, LR schedules, regularization.

The reference's vehicle (tf-controller-examples/tf-cnn running
tf_cnn_benchmarks) exposes the classic ImageNet training surface as CLI
flags — --optimizer, learning-rate warmup/decay, --weight_decay — and its
ResNet-50 recipe (lr = 0.1·batch/256 with warmup, step or cosine decay,
weight decay 1e-4 on kernels only, label smoothing 0.1) is what the 76%
top-1 acceptance target assumes. This module is that surface rebuilt
optax-native; runtime/worker.py maps its CLI flags straight onto
``make_optimizer``.

TPU notes: everything here composes into ONE optax transform executed
inside the jitted train step — schedules are traced functions of the step
counter (no host-side LR updates to sync), and the decay mask is a static
pytree so XLA sees a fixed program.
"""

from __future__ import annotations

from typing import Optional

import jax
import optax

OPTIMIZERS = ("sgd", "momentum", "nesterov", "adam", "adamw", "lars",
              "rmsprop")
SCHEDULES = ("constant", "cosine", "step", "linear")
# How the optimizer update is laid out across data-parallel replicas
# (ZeRO-2 "sharded" vs "replicated"): the vocabulary lives in the jax-free
# api layer so manifest admission can validate it without importing jax;
# re-exported here because it is a step-engine knob (PERF.md).
from ..api.trainingjob import (WEIGHT_UPDATE_MODES,  # noqa: F401,E402
                               validate_weight_update)
# Kernel-tier vocabularies (ISSUE 16): same jax-free admission-layer
# home, re-exported here because the optimizer rung is a recipe knob
# (make_optimizer(kernels=...)).
from ..api.trainingjob import (ATTENTION_KERNELS,  # noqa: F401,E402
                               OPTIMIZER_KERNELS, SERVING_KERNELS)

# classic ImageNet step-decay epochs 30/60/80 of 90, as fractions of the run
STEP_BOUNDARIES = (1 / 3, 2 / 3, 8 / 9)
STEP_FACTOR = 0.1


def recipe_fingerprint(**knobs) -> str:
    """Stable hash of everything recipe-shaped that is BAKED into the
    compiled train step — model/workload identity, optimizer family and
    its scalars, LR schedule (base lr, warmup, total steps: schedules
    are traced functions whose constants land in the HLO), weight decay,
    label smoothing. One half of the AOT executable key
    (runtime/aot.py step_key); the other half is the geometry the
    caller supplies there. Values must be JSON-able; unhashable knobs
    fall back to repr so a novel workload kwarg degrades to a unique
    (never-colliding-by-silence) fingerprint rather than an error."""
    import hashlib
    import json

    def default(o):  # non-JSON knob: repr is stable enough for a key
        return repr(o)

    blob = json.dumps(knobs, sort_keys=True, default=default).encode()
    return hashlib.sha256(blob).hexdigest()[:24]


def scale_lr(base_lr: float, global_batch: int, base_batch: int = 256
             ) -> float:
    """Linear-scaling rule (Goyal et al.): lr = base · batch/256."""
    return base_lr * global_batch / base_batch


def lr_schedule(name: str, base_lr: float, total_steps: int,
                warmup_steps: int = 0, *, end_scale: float = 0.0,
                boundaries: tuple = STEP_BOUNDARIES,
                factor: float = STEP_FACTOR) -> optax.Schedule:
    """A schedule over the whole run: linear warmup from 0 to base_lr over
    ``warmup_steps``, then the named decay over the remaining steps."""
    if name not in SCHEDULES:
        raise ValueError(f"schedule {name!r} not one of {SCHEDULES}")
    if warmup_steps < 0 or total_steps <= 0:
        raise ValueError("need total_steps > 0 and warmup_steps >= 0")
    warmup_steps = min(warmup_steps, total_steps)
    decay_steps = max(total_steps - warmup_steps, 1)

    if name == "constant":
        decay = optax.constant_schedule(base_lr)
    elif name == "cosine":
        decay = optax.cosine_decay_schedule(
            base_lr, decay_steps, alpha=end_scale)
    elif name == "linear":
        decay = optax.linear_schedule(
            base_lr, base_lr * end_scale, decay_steps)
    else:  # step
        # round (not truncate) so 2/3·90 lands on 60, not 59; very short
        # runs can collide two boundaries on one step — compound the
        # factors instead of silently dropping one
        bounds: dict[int, float] = {}
        for b in boundaries:
            k = max(round(b * decay_steps), 1)
            bounds[k] = bounds.get(k, 1.0) * factor
        decay = optax.piecewise_constant_schedule(base_lr, bounds)

    if warmup_steps == 0:
        return decay
    warmup = optax.linear_schedule(0.0, base_lr, warmup_steps)
    return optax.join_schedules([warmup, decay], [warmup_steps])


def decay_mask(params) -> object:
    """Weight decay applies to kernels only — never to biases or
    BatchNorm scales/offsets (rank-1 leaves), the standard ResNet rule."""
    return jax.tree.map(lambda p: getattr(p, "ndim", 0) > 1, params)


def make_optimizer(
    name: str = "momentum",
    learning_rate: float = 0.1,
    *,
    schedule: str = "constant",
    total_steps: int = 1,
    warmup_steps: int = 0,
    weight_decay: float = 0.0,
    momentum: float = 0.9,
    grad_clip: Optional[float] = 1.0,
    kernels: str = "stock",
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """One optax chain for the whole recipe. Returns (transform, schedule);
    the schedule is also returned alone so callers can log lr(step).

    ``kernels`` selects the optimizer rung of the kernel tier
    (OPTIMIZER_KERNELS): "fused_adam" replaces the
    add_decayed_weights+adam sub-chain with the single fused Pallas
    kernel (ops/fused_adam.py — parity ≤1e-5 vs this function's stock
    chain). Cross-leaf global-norm clipping stays a separate outer
    transform either way. The tier is baked into recipe_fingerprint by
    the worker, so a flip can never alias a cached executable."""
    if name not in OPTIMIZERS:
        raise ValueError(f"optimizer {name!r} not one of {OPTIMIZERS}")
    if kernels not in OPTIMIZER_KERNELS:
        raise ValueError(
            f"kernels.optimizer {kernels!r} not one of {OPTIMIZER_KERNELS}")
    sched = lr_schedule(schedule, learning_rate, total_steps, warmup_steps)

    if kernels == "fused_adam":
        # reject, don't silently downgrade: a requested fused tier that
        # quietly ran the stock chain would be invisible (the same rule
        # as multislice.microbatches-without-pipeline)
        if name != "adam":
            raise ValueError(
                f"kernels.optimizer 'fused_adam' requires optimizer "
                f"'adam', got {name!r}")
        from ..ops.fused_adam import fused_adam
        txs = []
        if grad_clip:
            txs.append(optax.clip_by_global_norm(grad_clip))
        txs.append(fused_adam(sched, weight_decay=weight_decay,
                              mask=decay_mask))
        return optax.chain(*txs), sched

    txs: list[optax.GradientTransformation] = []
    if grad_clip:
        txs.append(optax.clip_by_global_norm(grad_clip))
    # decoupled weight decay for adamw/lars (their own impls); classic
    # L2-into-gradient for the SGD family
    if weight_decay and name in ("sgd", "momentum", "nesterov", "rmsprop",
                                 "adam"):
        txs.append(optax.add_decayed_weights(weight_decay, mask=decay_mask))

    if name == "sgd":
        txs.append(optax.sgd(sched))
    elif name == "momentum":
        txs.append(optax.sgd(sched, momentum=momentum))
    elif name == "nesterov":
        txs.append(optax.sgd(sched, momentum=momentum, nesterov=True))
    elif name == "adam":
        txs.append(optax.adam(sched))
    elif name == "adamw":
        txs.append(optax.adamw(sched, weight_decay=weight_decay,
                               mask=decay_mask))
    elif name == "lars":
        txs.append(optax.lars(sched, weight_decay=weight_decay,
                              weight_decay_mask=decay_mask,
                              momentum=momentum))
    elif name == "rmsprop":
        txs.append(optax.rmsprop(sched, momentum=momentum))
    return optax.chain(*txs), sched
