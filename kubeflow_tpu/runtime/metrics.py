"""Per-step metrics, throughput, and profiler hooks.

The reference has NO in-repo tracing/profiling (SURVEY.md §5 — perf
measurement was kubebench CSV post-processing only). Here it is first-class:
a step timer that reports examples/sec, a JSONL metrics sink (the kubebench
reporter consumes it), and jax.profiler trace capture around chosen steps.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from ..obs import registry as obsreg

log = logging.getLogger(__name__)

# env contract: where the worker streams per-step JSONL so external
# harnesses (workflows/kubebench reporter) can aggregate the run
METRICS_PATH_ENV = "KFTPU_METRICS_PATH"

# pod self-identity, rendered by the operator into every worker container
# (controllers/tpujob.py — the downward-API analog); with an apiserver URL
# the worker can annotate its OWN pod with the liveness heartbeat
POD_NAME_ENV = "KFTPU_POD_NAME"
POD_NAMESPACE_ENV = "KFTPU_POD_NAMESPACE"
APISERVER_ENV = "KFTPU_APISERVER"


class HeartbeatReporter:
    """Worker-side liveness for the stall watchdog (SURVEY §5
    hung-not-dead): patch our OWN pod's heartbeat annotation with the
    current training step + wall time. The controller restarts a gang
    whose CHIEF heartbeat is staler than runPolicy.stallTimeoutSeconds
    (controllers/tpujob.py) — a wedged collective or a dead TPU runtime
    under a live pod never produces a Failed phase on its own, so this
    annotation is the only signal the watchdog has.

    Failure policy: reporting is best-effort and rate-limited — a flaky
    apiserver must never take down a healthy training loop, it only costs
    heartbeat freshness (and, eventually, a watchdog restart)."""

    def __init__(self, client, namespace: str, pod: str,
                 interval_s: float = 10.0):
        self.client = client
        self.namespace = namespace
        self.pod = pod
        self.interval_s = interval_s
        self._last = 0.0
        # last SUCCESSFUL beat as gauges: a scrape shows a hung chief
        # (beat age growing past stallTimeoutSeconds) BEFORE the
        # controller watchdog acts — alerting can fire on
        # time() - kftpu_heartbeat_last_time_seconds without apiserver
        # access to the annotation
        self._g_time = obsreg.gauge(
            "kftpu_heartbeat_last_time_seconds",
            "unix time of the last heartbeat annotation patch that "
            "succeeded")
        self._g_step = obsreg.gauge(
            "kftpu_heartbeat_last_step",
            "training step advertised by the last successful heartbeat")

    @classmethod
    def from_env(cls, client=None, env: Optional[dict] = None,
                 interval_s: float = 10.0) -> Optional["HeartbeatReporter"]:
        """Build from the operator-rendered pod identity env, or None when
        this process has no pod to annotate (bare-metal runs, tests) or no
        way to reach an apiserver."""
        env = os.environ if env is None else env
        pod = env.get(POD_NAME_ENV)
        if not pod:
            return None
        if client is None:
            url = env.get(APISERVER_ENV)
            if not url:
                return None
            from ..cluster.http_client import HttpKubeClient
            # beat() runs synchronously inside the train loop, so this
            # client must fail FAST: no retries (the next window's beat is
            # the retry) and a short timeout — with the defaults (30s x 4
            # attempts) an apiserver outage would stall training for
            # minutes per window and itself trip the stall watchdog
            client = HttpKubeClient(url, timeout=5.0, retries=0)
        return cls(client, env.get(POD_NAMESPACE_ENV, "default"), pod,
                   interval_s=interval_s)

    def beat(self, step: int, force: bool = False) -> bool:
        """Record progress at `step`. Rate-limited to one patch per
        interval unless forced; returns whether a patch was sent."""
        # import here keeps module import light; trainingjob is jax-free
        from ..api.trainingjob import HEARTBEAT_ANNOTATION
        now = time.time()
        if not force and now - self._last < self.interval_s:
            return False
        payload = json.dumps({"step": int(step), "time": now})
        try:
            self.client.patch(
                "v1", "Pod", self.namespace, self.pod,
                {"metadata": {"annotations": {HEARTBEAT_ANNOTATION:
                                              payload}}})
        except Exception as e:  # noqa: BLE001 — liveness must not kill work
            log.warning("heartbeat patch for %s/%s failed: %s",
                        self.namespace, self.pod, e)
            return False
        self._last = now
        self._g_time.set(now)
        self._g_step.set(int(step))
        return True


@dataclass
class StepStats:
    step: int
    step_time_s: float
    examples_per_sec: float
    metrics: dict[str, float] = field(default_factory=dict)
    # number of device steps this record averages over (>1 when the worker
    # only syncs every N steps — per-step host fetches defeat async dispatch)
    window: int = 1

    def to_dict(self) -> dict:
        d = {"step": self.step, "step_time_s": self.step_time_s,
             "examples_per_sec": self.examples_per_sec, **self.metrics}
        if self.window != 1:
            d["window"] = self.window
        return d


class MetricsLogger:
    """Accumulates per-step stats; optionally streams JSONL to a file."""

    def __init__(self, path: Optional[str] = None, batch_size: int = 0,
                 log_every: int = 10, tensorboard_dir: Optional[str] = None):
        self.path = path
        self.batch_size = batch_size
        self.log_every = log_every
        self.history: list[StepStats] = []
        self._last_t: Optional[float] = None
        self._fh = open(path, "a") if path else None
        self._tb = None
        if tensorboard_dir:
            from ..utils.tbevents import EventWriter
            self._tb = EventWriter(tensorboard_dir)
        # shared-registry mirror of the JSONL stream (obs/registry.py):
        # handles resolved ONCE here — record_window is on the worker
        # loop's window edge, so its obs cost must stay at a few lock'd
        # float ops (bench.py --mode obs holds the <1%-of-step-time line)
        self._obs_step = obsreg.histogram(
            "kftpu_step_seconds",
            "per-device-step wall time (window average)")
        self._obs_eps = obsreg.gauge(
            "kftpu_examples_per_sec",
            "training throughput over the last closed window")
        self._obs_windows = obsreg.counter(
            "kftpu_train_windows_total",
            "closed timing windows (one host sync each)")

    def start_step(self) -> None:
        self._last_t = time.perf_counter()

    def end_step(self, step: int, metrics: Optional[dict] = None) -> StepStats:
        return self.end_window(step, 1, metrics)

    def end_window(self, step: int, n_steps: int,
                   metrics: Optional[dict] = None) -> StepStats:
        """Close a timing window of `n_steps` device steps with ONE host
        sync. The recorded step_time_s is the window average; the JSONL
        line carries the window size so consumers can weight it."""
        now = time.perf_counter()
        total = now - (self._last_t if self._last_t is not None else now)
        self._last_t = now
        return self.record_window(step, n_steps, total, metrics)

    def record_window(self, step: int, n_steps: int, wall_s: float,
                      metrics: Optional[dict] = None) -> StepStats:
        """Record an already-timed window. The async-fetch worker loop
        times windows itself (the metric fetch lags the window edge by a
        window so it never drains the dispatch queue — AsyncWindowFetch),
        so the wall time arrives here as data, not as "now minus last"."""
        dt = wall_s / max(n_steps, 1)
        scalars = {}
        for k, v in (metrics or {}).items():
            try:
                scalars[k] = float(v)
            except (TypeError, ValueError):
                continue
        stats = StepStats(
            step=step, step_time_s=dt,
            examples_per_sec=(self.batch_size / dt) if dt > 0 else 0.0,
            metrics=scalars, window=max(n_steps, 1))
        self.history.append(stats)
        self._obs_step.observe(dt)
        self._obs_eps.set(stats.examples_per_sec)
        self._obs_windows.inc()
        if self._fh:
            self._fh.write(json.dumps(stats.to_dict()) + "\n")
            self._fh.flush()
        if self._tb:
            self._tb.add_scalars(
                {"throughput/examples_per_sec": stats.examples_per_sec,
                 "timing/step_time_s": dt, **scalars}, step)
        # log when this window crosses a log_every boundary (covers both
        # per-step records and multi-step windows without flooding)
        if self.log_every and \
                step // self.log_every > (step - n_steps) // self.log_every:
            log.info("step %d: %.1f ex/s %s", step, stats.examples_per_sec,
                     scalars)
        return stats

    def event(self, step: int, metrics: dict) -> None:
        """Stream an out-of-band record (eval results, checkpoints) to the
        JSONL without touching the timing history."""
        if self._fh:
            self._fh.write(json.dumps(
                {"step": step, "event": True,
                 "metrics": {k: float(v) for k, v in metrics.items()}})
                + "\n")
            self._fh.flush()
        if self._tb:
            self._tb.add_scalars(
                {f"eval/{k.removeprefix('eval_')}": float(v)
                 for k, v in metrics.items()}, step)

    def summary(self, warmup: int = 1) -> dict[str, float]:
        """Steady-state throughput, skipping compile/warmup records.
        Window records are weighted by the number of steps they cover.

        Degrades gracefully when fewer than ``warmup + 1`` windows were
        recorded (short runs, a run preempted inside warmup): drop as
        many leading warmup windows as the history affords while always
        keeping at least the final window — never an empty slice whose
        zero sums would divide into the throughput, and never the old
        fallback of silently averaging the compile window back in."""
        if not self.history:
            return {"steps": 0, "examples_per_sec": 0.0, "mean_step_time_s": 0.0}
        start = min(max(int(warmup), 0), len(self.history) - 1)
        steady = self.history[start:]
        n = sum(s.window for s in steady)
        t = sum(s.step_time_s * s.window for s in steady)
        first = self.history[0] if self.history else None
        return {
            "steps": sum(s.window for s in self.history),
            "mean_step_time_s": t / n if n else 0.0,
            "examples_per_sec": (self.batch_size * n / t) if t else 0.0,
            # the first window carries compile + dispatch warmup — the
            # startup cost a warm compile cache is meant to cut
            "first_window_s": (first.step_time_s * first.window)
            if first else 0.0,
        }

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None
        if self._tb:
            self._tb.close()
            self._tb = None


class AsyncWindowFetch:
    """Window-edge metrics without draining the dispatch queue.

    The worker loop used to fetch a window's metrics with blocking
    ``float()`` at the window edge — a hard device→host barrier that
    empties the dispatch queue; refilling it costs ~160 ms of round trips
    on tunneled hosts (PERF.md "Worker loop vs bench loop"). Instead:
    ``submit()`` starts the device→host copy (``copy_to_host_async``)
    for a just-closed window and ``drain()`` resolves windows ``lag``
    submissions later, by which point the copies have long completed and
    the ``float()`` returns without stalling dispatch. Hard sync points
    (checkpoint, eval, preemption, the final step) force the drain, so
    reported metrics are always complete and ordered."""

    def __init__(self, lag: int = 1):
        self.lag = max(0, int(lag))
        self._pending: deque = deque()

    def submit(self, step: int, n_steps: int, wall_s: float,
               metrics: dict) -> None:
        """Queue a closed window; starts the async copy of every device
        value (host scalars pass through untouched)."""
        for v in metrics.values():
            start = getattr(v, "copy_to_host_async", None)
            if start is not None:
                start()
        self._pending.append((step, n_steps, wall_s, metrics))

    def drain(self, force: bool = False
              ) -> list[tuple[int, int, float, dict]]:
        """Windows ready to report, oldest first, metric values resolved
        to host floats. Without ``force`` the newest ``lag`` submissions
        stay pending (their copies may still be in flight)."""
        out = []
        while self._pending and (force or len(self._pending) > self.lag):
            step, n_steps, wall_s, metrics = self._pending.popleft()
            out.append((step, n_steps, wall_s,
                        {k: float(v) for k, v in metrics.items()}))
        return out

    @property
    def pending(self) -> int:
        return len(self._pending)


@contextlib.contextmanager
def profile_trace(out_dir: Optional[str], enabled: bool = True,
                  tracer=None):
    """Capture an XLA/JAX profiler trace around a block (view in XProf /
    tensorboard-plugin-profile). With a ``tracer`` (obs/trace.py
    SpanWriter) the capture is recorded as a child span of the job's
    trace — the timeline links "this window was slow" to "a profiler
    capture covers it" — with the trace dir in the span attrs."""
    if not (enabled and out_dir):
        yield
        return
    import jax
    os.makedirs(out_dir, exist_ok=True)
    span = tracer.span("profile", out_dir=out_dir) \
        if tracer is not None else contextlib.nullcontext()
    with span:
        jax.profiler.start_trace(out_dir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", out_dir)
