"""Per-step metrics, throughput, and profiler hooks.

The reference has NO in-repo tracing/profiling (SURVEY.md §5 — perf
measurement was kubebench CSV post-processing only). Here it is first-class:
a step timer that reports examples/sec, a JSONL metrics sink (the kubebench
reporter consumes it), and jax.profiler trace capture around chosen steps.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..obs import registry as obsreg

log = logging.getLogger(__name__)

# env contract: where the worker streams per-step JSONL so external
# harnesses (workflows/kubebench reporter) can aggregate the run
METRICS_PATH_ENV = "KFTPU_METRICS_PATH"

# flight-recorder ring depth (windows kept); 0 disables the recorder
FLIGHT_WINDOWS_ENV = "KFTPU_FLIGHT_WINDOWS"
# span name a flight-recorder dump lands under in the trace sink
FLIGHT_RECORD_SPAN = "flight-record"

# pod self-identity, rendered by the operator into every worker container
# (controllers/tpujob.py — the downward-API analog); with an apiserver URL
# the worker can annotate its OWN pod with the liveness heartbeat
POD_NAME_ENV = "KFTPU_POD_NAME"
POD_NAMESPACE_ENV = "KFTPU_POD_NAMESPACE"
APISERVER_ENV = "KFTPU_APISERVER"


class HeartbeatReporter:
    """Worker-side liveness for the stall watchdog (SURVEY §5
    hung-not-dead): patch our OWN pod's heartbeat annotation with the
    current training step + wall time. The controller restarts a gang
    whose CHIEF heartbeat is staler than runPolicy.stallTimeoutSeconds
    (controllers/tpujob.py) — a wedged collective or a dead TPU runtime
    under a live pod never produces a Failed phase on its own, so this
    annotation is the only signal the watchdog has.

    Failure policy: reporting is best-effort and rate-limited — a flaky
    apiserver must never take down a healthy training loop, it only costs
    heartbeat freshness (and, eventually, a watchdog restart)."""

    def __init__(self, client, namespace: str, pod: str,
                 interval_s: float = 10.0):
        self.client = client
        self.namespace = namespace
        self.pod = pod
        self.interval_s = interval_s
        self._last = 0.0
        # last SUCCESSFUL beat as gauges: a scrape shows a hung chief
        # (beat age growing past stallTimeoutSeconds) BEFORE the
        # controller watchdog acts — alerting can fire on
        # time() - kftpu_heartbeat_last_time_seconds without apiserver
        # access to the annotation
        self._g_time = obsreg.gauge(
            "kftpu_heartbeat_last_time_seconds",
            "unix time of the last heartbeat annotation patch that "
            "succeeded")
        self._g_step = obsreg.gauge(
            "kftpu_heartbeat_last_step",
            "training step advertised by the last successful heartbeat")

    @classmethod
    def from_env(cls, client=None, env: Optional[dict] = None,
                 interval_s: float = 10.0) -> Optional["HeartbeatReporter"]:
        """Build from the operator-rendered pod identity env, or None when
        this process has no pod to annotate (bare-metal runs, tests) or no
        way to reach an apiserver."""
        env = os.environ if env is None else env
        pod = env.get(POD_NAME_ENV)
        if not pod:
            return None
        if client is None:
            url = env.get(APISERVER_ENV)
            if not url:
                return None
            from ..cluster.http_client import HttpKubeClient
            # beat() runs synchronously inside the train loop, so this
            # client must fail FAST: no retries (the next window's beat is
            # the retry) and a short timeout — with the defaults (30s x 4
            # attempts) an apiserver outage would stall training for
            # minutes per window and itself trip the stall watchdog
            client = HttpKubeClient(url, timeout=5.0, retries=0)
        return cls(client, env.get(POD_NAMESPACE_ENV, "default"), pod,
                   interval_s=interval_s)

    def beat(self, step: int, force: bool = False,
             loss: Optional[float] = None,
             grad_norm: Optional[float] = None) -> bool:
        """Record progress at `step`. Rate-limited to one patch per
        interval unless forced; returns whether a patch was sent.

        `loss`/`grad_norm` ride along as lastLoss/lastGradNorm so the
        operator can flag a NaN-emitting worker even when the worker's
        own sentinel is disabled (controllers/tpujob.py
        _note_numeric_health). Stringified via repr(): json.dumps would
        emit bare NaN/Infinity, which strict parsers reject — and NaN is
        exactly the value this channel exists to carry."""
        # import here keeps module import light; trainingjob is jax-free
        from ..api.trainingjob import HEARTBEAT_ANNOTATION
        now = time.time()
        if not force and now - self._last < self.interval_s:
            return False
        body: dict = {"step": int(step), "time": now}
        if loss is not None:
            body["lastLoss"] = repr(float(loss))
        if grad_norm is not None:
            body["lastGradNorm"] = repr(float(grad_norm))
        payload = json.dumps(body)
        try:
            self.client.patch(
                "v1", "Pod", self.namespace, self.pod,
                {"metadata": {"annotations": {HEARTBEAT_ANNOTATION:
                                              payload}}})
        except Exception as e:  # noqa: BLE001 — liveness must not kill work
            log.warning("heartbeat patch for %s/%s failed: %s",
                        self.namespace, self.pod, e)
            return False
        self._last = now
        self._g_time.set(now)
        self._g_step.set(int(step))
        return True

    def annotate(self, annotation: str, payload: str) -> bool:
        """Patch an arbitrary annotation onto our own pod — the anomaly
        evidence channel (ANOMALY_ANNOTATION): the sentinel trips, the
        worker posts the evidence here, then exits ANOMALY_EXIT_CODE so
        the operator finds both. Best-effort like beat()."""
        try:
            self.client.patch(
                "v1", "Pod", self.namespace, self.pod,
                {"metadata": {"annotations": {annotation: payload}}})
        except Exception as e:  # noqa: BLE001 — evidence is best-effort
            log.warning("annotation patch %s for %s/%s failed: %s",
                        annotation, self.namespace, self.pod, e)
            return False
        return True


@dataclass
class StepStats:
    step: int
    step_time_s: float
    examples_per_sec: float
    metrics: dict[str, float] = field(default_factory=dict)
    # number of device steps this record averages over (>1 when the worker
    # only syncs every N steps — per-step host fetches defeat async dispatch)
    window: int = 1

    def to_dict(self) -> dict:
        d = {"step": self.step, "step_time_s": self.step_time_s,
             "examples_per_sec": self.examples_per_sec, **self.metrics}
        if self.window != 1:
            d["window"] = self.window
        return d


class MetricsLogger:
    """Accumulates per-step stats; optionally streams JSONL to a file."""

    def __init__(self, path: Optional[str] = None, batch_size: int = 0,
                 log_every: int = 10, tensorboard_dir: Optional[str] = None):
        self.path = path
        self.batch_size = batch_size
        self.log_every = log_every
        self.history: list[StepStats] = []
        self._last_t: Optional[float] = None
        self._fh = open(path, "a") if path else None
        self._tb = None
        if tensorboard_dir:
            from ..utils.tbevents import EventWriter
            self._tb = EventWriter(tensorboard_dir)
        # shared-registry mirror of the JSONL stream (obs/registry.py):
        # handles resolved ONCE here — record_window is on the worker
        # loop's window edge, so its obs cost must stay at a few lock'd
        # float ops (bench.py --mode obs holds the <1%-of-step-time line)
        self._obs_step = obsreg.histogram(
            "kftpu_step_seconds",
            "per-device-step wall time (window average)")
        self._obs_eps = obsreg.gauge(
            "kftpu_examples_per_sec",
            "training throughput over the last closed window")
        self._obs_windows = obsreg.counter(
            "kftpu_train_windows_total",
            "closed timing windows (one host sync each)")

    def start_step(self) -> None:
        self._last_t = time.perf_counter()

    def end_step(self, step: int, metrics: Optional[dict] = None) -> StepStats:
        return self.end_window(step, 1, metrics)

    def end_window(self, step: int, n_steps: int,
                   metrics: Optional[dict] = None) -> StepStats:
        """Close a timing window of `n_steps` device steps with ONE host
        sync. The recorded step_time_s is the window average; the JSONL
        line carries the window size so consumers can weight it."""
        now = time.perf_counter()
        total = now - (self._last_t if self._last_t is not None else now)
        self._last_t = now
        return self.record_window(step, n_steps, total, metrics)

    def record_window(self, step: int, n_steps: int, wall_s: float,
                      metrics: Optional[dict] = None) -> StepStats:
        """Record an already-timed window. The async-fetch worker loop
        times windows itself (the metric fetch lags the window edge by a
        window so it never drains the dispatch queue — AsyncWindowFetch),
        so the wall time arrives here as data, not as "now minus last"."""
        dt = wall_s / max(n_steps, 1)
        scalars = {}
        for k, v in (metrics or {}).items():
            try:
                scalars[k] = float(v)
            except (TypeError, ValueError):
                continue
        stats = StepStats(
            step=step, step_time_s=dt,
            examples_per_sec=(self.batch_size / dt) if dt > 0 else 0.0,
            metrics=scalars, window=max(n_steps, 1))
        self.history.append(stats)
        self._obs_step.observe(dt)
        self._obs_eps.set(stats.examples_per_sec)
        self._obs_windows.inc()
        if self._fh:
            self._fh.write(json.dumps(stats.to_dict()) + "\n")
            self._fh.flush()
        if self._tb:
            self._tb.add_scalars(
                {"throughput/examples_per_sec": stats.examples_per_sec,
                 "timing/step_time_s": dt, **scalars}, step)
        # log when this window crosses a log_every boundary (covers both
        # per-step records and multi-step windows without flooding)
        if self.log_every and \
                step // self.log_every > (step - n_steps) // self.log_every:
            log.info("step %d: %.1f ex/s %s", step, stats.examples_per_sec,
                     scalars)
        return stats

    def event(self, step: int, metrics: dict) -> None:
        """Stream an out-of-band record (eval results, checkpoints) to the
        JSONL without touching the timing history."""
        if self._fh:
            self._fh.write(json.dumps(
                {"step": step, "event": True,
                 "metrics": {k: float(v) for k, v in metrics.items()}})
                + "\n")
            self._fh.flush()
        if self._tb:
            self._tb.add_scalars(
                {f"eval/{k.removeprefix('eval_')}": float(v)
                 for k, v in metrics.items()}, step)

    def summary(self, warmup: int = 1) -> dict[str, float]:
        """Steady-state throughput, skipping compile/warmup records.
        Window records are weighted by the number of steps they cover.

        Degrades gracefully when fewer than ``warmup + 1`` windows were
        recorded (short runs, a run preempted inside warmup): drop as
        many leading warmup windows as the history affords while always
        keeping at least the final window — never an empty slice whose
        zero sums would divide into the throughput, and never the old
        fallback of silently averaging the compile window back in."""
        if not self.history:
            return {"steps": 0, "examples_per_sec": 0.0, "mean_step_time_s": 0.0}
        start = min(max(int(warmup), 0), len(self.history) - 1)
        steady = self.history[start:]
        n = sum(s.window for s in steady)
        t = sum(s.step_time_s * s.window for s in steady)
        first = self.history[0] if self.history else None
        return {
            "steps": sum(s.window for s in self.history),
            "mean_step_time_s": t / n if n else 0.0,
            "examples_per_sec": (self.batch_size * n / t) if t else 0.0,
            # the first window carries compile + dispatch warmup — the
            # startup cost a warm compile cache is meant to cut
            "first_window_s": (first.step_time_s * first.window)
            if first else 0.0,
        }

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None
        if self._tb:
            self._tb.close()
            self._tb = None


class AsyncWindowFetch:
    """Window-edge metrics without draining the dispatch queue.

    The worker loop used to fetch a window's metrics with blocking
    ``float()`` at the window edge — a hard device→host barrier that
    empties the dispatch queue; refilling it costs ~160 ms of round trips
    on tunneled hosts (PERF.md "Worker loop vs bench loop"). Instead:
    ``submit()`` starts the device→host copy (``copy_to_host_async``)
    for a just-closed window and ``drain()`` resolves windows ``lag``
    submissions later, by which point the copies have long completed and
    the ``float()`` returns without stalling dispatch. Hard sync points
    (checkpoint, eval, preemption, the final step) force the drain, so
    reported metrics are always complete and ordered."""

    def __init__(self, lag: int = 1):
        self.lag = max(0, int(lag))
        self._pending: deque = deque()

    def submit(self, step: int, n_steps: int, wall_s: float,
               metrics: dict) -> None:
        """Queue a closed window; starts the async copy of every device
        value (host scalars pass through untouched)."""
        for v in metrics.values():
            start = getattr(v, "copy_to_host_async", None)
            if start is not None:
                start()
        self._pending.append((step, n_steps, wall_s, metrics))

    def drain(self, force: bool = False
              ) -> list[tuple[int, int, float, dict]]:
        """Windows ready to report, oldest first, metric values resolved
        to host floats. Without ``force`` the newest ``lag`` submissions
        stay pending (their copies may still be in flight)."""
        out = []
        while self._pending and (force or len(self._pending) > self.lag):
            step, n_steps, wall_s, metrics = self._pending.popleft()
            out.append((step, n_steps, wall_s,
                        {k: float(v) for k, v in metrics.items()}))
        return out

    @property
    def pending(self) -> int:
        return len(self._pending)


class FlightRecorder:
    """Step-time flight recorder: a bounded in-memory ring of per-window
    timing records with the host-side stage breakdown (data wait, H2D,
    dispatch, end-of-window drain, and the residual the device kept the
    host blocked for), dumped to the span sink on SIGTERM/crash and on
    demand — so a wedged worker the stall watchdog tears down finally
    leaves evidence of WHERE it stuck (ISSUE 10).

    The hot path is two ``mark()`` attribute writes and one
    ``note_step()`` float-accumulate per step — no locks, no I/O; the
    lock only guards ring snapshots against the dump paths (signal
    handler, HTTP peek), which run concurrently with the loop."""

    # input-pipeline stage counters snapshotted per window
    # (data/mp_augment.py, data/device_prefetch.py label values)
    INPUT_STAGES = ("augment", "device_put")

    def __init__(self, windows: int = 64):
        self.enabled = windows > 0
        self._ring: deque = deque(maxlen=max(1, windows))
        self._lock = threading.Lock()
        self._stage = "init"
        self._stage_step = -1
        self._stage_since = time.time()
        self._acc = self._fresh_acc()
        self._input_counters = None
        self._input_last: dict[str, float] = {}
        # modeled per-step comm split from the HLO comm profile
        # (obs/collectives.py), set once at the first step
        self._comm_ici_s = 0.0
        self._comm_dcn_s = 0.0

    @staticmethod
    def _fresh_acc() -> dict:
        return {"data_s": 0.0, "h2d_s": 0.0, "dispatch_s": 0.0,
                "first_step_s": 0.0, "steps": 0}

    def _input_totals(self) -> dict[str, float]:
        if self._input_counters is None:
            fam = obsreg.counter(
                "kftpu_input_batches_total",
                "batches delivered by each input-pipeline stage",
                labels=("stage",))
            self._input_counters = {s: fam.labels(stage=s)
                                    for s in self.INPUT_STAGES}
        return {s: c.value for s, c in self._input_counters.items()}

    def set_comm_model(self, ici_s_per_step: float,
                       dcn_s_per_step: float) -> None:
        """Adopt the comm profile's modeled per-step ICI/DCN seconds
        (obs/collectives.py, computed once from the compiled step's
        HLO). Subsequent window records carry the modeled split as its
        OWN keyed fields — never folded into the ``device_wait``
        residual, which stays a pure measurement (the PR 10 rule that
        split out ``first_step_s``)."""
        self._comm_ici_s = max(0.0, float(ici_s_per_step))
        self._comm_dcn_s = max(0.0, float(dcn_s_per_step))

    # ------------------------------------------------------------ hot path

    def mark(self, stage: str, step: int) -> None:
        """Record what the loop is ABOUT to do — the dump's "where it
        stuck" pointer. Two attribute writes; wall time is read lazily
        at dump, not here."""
        self._stage = stage
        self._stage_step = step
        self._stage_since = time.time()

    def note_step(self, data_s: float = 0.0, h2d_s: float = 0.0,
                  dispatch_s: float = 0.0,
                  first_step_s: float = 0.0) -> None:
        """``first_step_s`` carries the FIRST step's compile + blocking
        sync separately: charging a multi-second cold compile to
        dispatch_s would make the first window's record claim the loop
        spent seconds 'dispatching' — the opposite of the accurate
        where-it-stuck evidence the recorder exists for."""
        acc = self._acc
        acc["data_s"] += data_s
        acc["h2d_s"] += h2d_s
        acc["dispatch_s"] += dispatch_s
        acc["first_step_s"] += first_step_s
        acc["steps"] += 1

    def close_window(self, step: int, steps: int, wall_s: float,
                     drain_s: float = 0.0) -> None:
        """Fold the accumulated per-step stage times into one ring
        record at the window edge (the same cadence as the window span,
        so recorder and trace agree on boundaries)."""
        if not self.enabled:
            return
        acc = self._acc
        host = acc["data_s"] + acc["h2d_s"] + acc["dispatch_s"] + \
            acc["first_step_s"]
        totals = self._input_totals()
        deltas = {s: round(totals[s] - self._input_last.get(s, totals[s]))
                  for s in totals}
        self._input_last = totals
        rec = {
            "step": int(step), "steps": int(steps),
            "wall_s": round(wall_s, 6),
            "data_s": round(acc["data_s"], 6),
            "h2d_s": round(acc["h2d_s"], 6),
            "dispatch_s": round(acc["dispatch_s"], 6),
            "drain_s": round(drain_s, 6),
            # what the host spent BLOCKED on the device inside dispatch/
            # fetch — everything the host-side stages can't explain
            "device_wait_s": round(max(0.0, wall_s + drain_s - host), 6),
            "input_batches": deltas,
        }
        if acc["first_step_s"]:
            rec["first_step_s"] = round(acc["first_step_s"], 6)
        if self._comm_ici_s or self._comm_dcn_s:
            # modeled, clearly keyed as such (the device_wait residual
            # above is measured and deliberately does NOT subtract this)
            rec["comm_ici_s"] = round(self._comm_ici_s * steps, 6)
            rec["comm_dcn_s"] = round(self._comm_dcn_s * steps, 6)
        with self._lock:
            self._ring.append(rec)
        self._acc = self._fresh_acc()

    # --------------------------------------------------------------- dumps

    def snapshot(self) -> dict:
        """The ring plus the in-progress state. SIGNAL-SAFE: the dump
        runs inside the SIGTERM handler, which interrupts the main
        thread mid-bytecode — if that thread holds this lock (a
        close_window in flight), a blocking acquire would deadlock the
        process the watchdog is trying to tear down. Non-blocking
        acquire, then a best-effort copy (CPython deque appends are
        atomic; a concurrent-mutation RuntimeError retries once)."""
        got = self._lock.acquire(blocking=False)
        try:
            try:
                records = list(self._ring)
            except RuntimeError:   # mutated mid-copy (lockless path)
                records = list(self._ring)
        finally:
            if got:
                self._lock.release()
        acc = dict(self._acc)
        return {
            "records": records,
            "inProgress": {
                "stage": self._stage,
                "step": self._stage_step,
                "stuckSeconds": round(time.time() - self._stage_since, 3),
                **{k: round(v, 6) if isinstance(v, float) else v
                   for k, v in acc.items()},
            },
        }

    def dump(self, tracer, reason: str, **attrs) -> Optional[dict]:
        """Write the ring to the span sink as ONE ``flight-record``
        span. Signal-handler and finally-block safe: never raises —
        losing the dump must not mask the failure being dumped."""
        if not self.enabled or tracer is None:
            return None
        try:
            snap = self.snapshot()
            return tracer.emit(FLIGHT_RECORD_SPAN, start=time.time(),
                               reason=reason, **snap, **attrs)
        except Exception as e:  # noqa: BLE001 — evidence is best-effort
            log.warning("flight-recorder dump (%s) failed: %s", reason, e)
            return None


class ProfileArm:
    """On-demand profiler trigger (ISSUE 10 satellite): ``POST
    /profile?steps=N`` on the worker's ObsServer arms a jax.profiler
    capture around the NEXT N steps and returns the artifact dir —
    previously profiling was CLI-only (``--profile-dir``) and required
    a restart. The HTTP thread only flips armed state under the lock;
    the capture itself starts/stops on the LOOP thread at step
    boundaries (the profiler is not thread-safe against the program it
    profiles)."""

    def __init__(self, base_dir: str,
                 start_fn: Optional[Callable] = None,
                 stop_fn: Optional[Callable] = None,
                 tracer=None):
        self.base_dir = base_dir
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._tracer = tracer
        self._lock = threading.Lock()
        self._pending = 0
        self._active = 0
        self._dir: Optional[str] = None
        self._t0 = 0.0

    def request(self, steps: int) -> tuple[int, dict]:
        """The HTTP handler: arm a capture of ``steps`` steps. Returns
        (status, body) — 409 while a capture is already armed/active
        (two overlapping jax traces would corrupt both)."""
        try:
            steps = int(steps)
        except (TypeError, ValueError):
            return 400, {"error": "steps must be an integer"}
        if steps <= 0:
            return 400, {"error": f"steps must be > 0, got {steps}"}
        with self._lock:
            if self._pending or self._active:
                return 409, {"error": "a profile capture is already "
                                      "armed or active",
                             "dir": self._dir}
            self._dir = os.path.join(self.base_dir,
                                     f"profile-{int(time.time())}")
            self._pending = steps
            return 200, {"armed": True, "steps": steps, "dir": self._dir}

    def on_step_start(self) -> None:
        """Loop thread, before dispatching a step: start a pending
        capture. Failures disarm with a warning — profiling must never
        kill training."""
        with self._lock:
            if not self._pending:
                return
            self._active = self._pending
            self._pending = 0
            out_dir = self._dir
        try:
            os.makedirs(out_dir, exist_ok=True)
            if self._start_fn is not None:
                self._start_fn(out_dir)
            else:
                import jax
                jax.profiler.start_trace(out_dir)
            self._t0 = time.time()
        except Exception as e:  # noqa: BLE001
            log.warning("on-demand profile start failed: %s", e)
            with self._lock:
                self._active = 0

    def on_step_end(self, step: int) -> None:
        """Loop thread, after a step completes: count down and stop."""
        with self._lock:
            if not self._active:
                return
            self._active -= 1
            if self._active:
                return
            out_dir = self._dir
        try:
            if self._stop_fn is not None:
                self._stop_fn()
            else:
                import jax
                jax.profiler.stop_trace()
            log.info("on-demand profiler trace written to %s", out_dir)
            if self._tracer is not None:
                self._tracer.emit("profile", start=self._t0,
                                  end=time.time(), out_dir=out_dir,
                                  step=step, on_demand=True)
        except Exception as e:  # noqa: BLE001
            log.warning("on-demand profile stop failed: %s", e)


@contextlib.contextmanager
def profile_trace(out_dir: Optional[str], enabled: bool = True,
                  tracer=None):
    """Capture an XLA/JAX profiler trace around a block (view in XProf /
    tensorboard-plugin-profile). With a ``tracer`` (obs/trace.py
    SpanWriter) the capture is recorded as a child span of the job's
    trace — the timeline links "this window was slow" to "a profiler
    capture covers it" — with the trace dir in the span attrs."""
    if not (enabled and out_dir):
        yield
        return
    import jax
    os.makedirs(out_dir, exist_ok=True)
    span = tracer.span("profile", out_dir=out_dir) \
        if tracer is not None else contextlib.nullcontext()
    with span:
        jax.profiler.start_trace(out_dir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", out_dir)
