"""Checkpoint / resume via orbax.

A core component here (the reference delegates model checkpoints entirely to
workloads via storage params — SURVEY.md §5 "Checkpoint/resume"); the TPUJob
controller exposes `resumeFrom`, and this module is what the worker runtime
calls. Restore is sharding-aware: each host restores only its shards.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import jax

log = logging.getLogger(__name__)

try:
    import orbax.checkpoint as ocp
    HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    ocp = None
    HAVE_ORBAX = False


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager for TrainState pytrees."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        if not HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is not available")
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps),
        )

    def should_save(self, step: int) -> bool:
        """Whether save() at this step would actually write (interval gate).
        Lets callers avoid host-syncing device state for skipped steps."""
        return bool(self._mgr.should_save(step))

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)
        if saved:
            log.info("checkpoint saved at step %d -> %s", step, self.directory)
        return saved

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_template: Any, step: Optional[int] = None) -> Any:
        """Restore into the template's shardings (template = an abstract or
        concrete TrainState with the target shardings attached)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if hasattr(x, "sharding") else x,
            state_template)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def restore_params(self, step: Optional[int] = None) -> Any:
        """Restore just the model params, template-free. The trainer writes
        full TrainState pytrees; a server watching the directory only wants
        params and has no opt_state template to offer — restore the raw
        tree (orbax saves pytrees as nested dicts) and take its 'params'
        subtree, or the whole tree for params-only checkpoints."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        raw = self._mgr.restore(step, args=ocp.args.StandardRestore())
        if isinstance(raw, dict) and "params" in raw:
            return raw["params"]
        return raw

    def close(self) -> None:
        self._mgr.close()
