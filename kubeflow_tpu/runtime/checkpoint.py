"""Checkpoint / resume via orbax, hardened for preemption-heavy fleets.

A core component here (the reference delegates model checkpoints entirely to
workloads via storage params — SURVEY.md §5 "Checkpoint/resume"); the TPUJob
controller exposes `resumeFrom`, and this module is what the worker runtime
calls. Restore is sharding-aware: each host restores only its shards.

Integrity layer (the part preemption actually exercises):

- **Commit detection.** A step directory without orbax's commit metadata
  (``_CHECKPOINT_METADATA``) is half-written — a writer died between
  creating the directory and finalizing it — and is never offered by
  ``latest_step()`` or picked by ``restore()``.
- **Checksum manifest.** After an async save completes, process 0 writes
  ``kftpu.manifest.json`` into the step directory: per-file size + crc32,
  committed by atomic rename. On restore the manifest is verified first;
  a truncated or bit-flipped payload file fails verification.
- **Fallback restore.** ``restore()``/``restore_params()`` with no explicit
  step walk intact steps newest-first: a step that fails verification OR
  raises during the actual restore is logged and skipped, falling back to
  the previous intact step. Only an empty directory raises.
- **Retried saves.** Transient I/O errors at save submission retry with
  exponential backoff before surfacing (async write failures still surface
  in ``wait()``, as before).
"""

from __future__ import annotations

import json
import logging
import os
import time
import zlib
from typing import Any, Callable, Optional

import jax

from ..obs import registry as obsreg
from ..obs.goodput import SPAN_CKPT_RESTORE, SPAN_CKPT_SAVE

log = logging.getLogger(__name__)


def _obs_duration(op: str):
    """Histogram child for one checkpoint operation (save submission,
    restore, verify) — the durations the recovery paths spend."""
    return obsreg.histogram(
        "kftpu_checkpoint_seconds",
        "checkpoint operation wall time by op (save = synchronous "
        "submission of the async write; restore; verify = manifest "
        "crc pass)", labels=("op",)).labels(op=op)

try:
    import orbax.checkpoint as ocp
    HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    ocp = None
    HAVE_ORBAX = False

class ElasticContractError(ValueError):
    """An elastic-resize restore contract breach (changed global batch,
    non-dividing replica degree): NEVER absorbed by the newest-first
    fallback walk — every candidate step carries the same breach, and
    silently restoring an older one would change the trajectory the
    check exists to protect."""


# orbax finalizes a step by renaming the tmp dir and writing this marker;
# its absence means the step never committed (half-written)
ORBAX_COMMIT_MARKER = "_CHECKPOINT_METADATA"
# our integrity manifest, written AFTER the orbax commit (so its presence
# implies the payload below it was complete at manifest time)
MANIFEST_NAME = "kftpu.manifest.json"
# last-known-good marker (runtime/sentinel.py): the newest step the
# numeric-integrity sentinel cleared the FOLLOWING window for — the step
# an anomaly rollback resumes from. Atomic-rename committed, monotonic.
LKG_MARKER = "kftpu.lkg.json"


def _crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def write_manifest(step_dir: str,
                   run_meta: Optional[dict] = None) -> dict:
    """Record every payload file's size + crc32 and commit the manifest by
    atomic rename — the cheap corruption detector a plain rename-commit
    (which only proves the DIRECTORY was finalized) cannot give.
    ``run_meta`` (the elastic-resize contract: replicaDegree,
    globalBatch) rides along under the "run" key so a restore at a
    DIFFERENT replica degree can validate the fixed-global-batch
    invariant before reshaping the state."""
    entries: dict[str, dict] = {}
    for root, _dirs, files in os.walk(step_dir):
        for fname in files:
            if fname == MANIFEST_NAME:
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, step_dir)
            entries[rel] = {"size": os.path.getsize(path),
                            "crc32": _crc32_file(path)}
    manifest = {"version": 1, "files": entries}
    if run_meta:
        manifest["run"] = dict(run_meta)
    tmp = os.path.join(step_dir, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(step_dir, MANIFEST_NAME))
    return manifest


def verify_step_dir(step_dir: str) -> tuple[bool, str]:
    """(intact, reason). Uncommitted (no orbax marker) and
    manifest-mismatched steps are not intact; a committed step without a
    manifest is accepted (manifests arrive asynchronously / older writers
    never wrote one)."""
    if not os.path.isdir(step_dir):
        return False, "missing"
    if not os.path.exists(os.path.join(step_dir, ORBAX_COMMIT_MARKER)):
        return False, "uncommitted (no orbax commit metadata)"
    mpath = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return True, "no manifest (accepted)"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"unreadable manifest: {e}"
    for rel, want in manifest.get("files", {}).items():
        path = os.path.join(step_dir, rel)
        if not os.path.exists(path):
            return False, f"missing file {rel}"
        size = os.path.getsize(path)
        if size != want.get("size"):
            return False, (f"size mismatch {rel}: {size} != "
                           f"{want.get('size')} (truncated write?)")
        if _crc32_file(path) != want.get("crc32"):
            return False, f"checksum mismatch {rel}"
    return True, "verified"


class CheckpointManager:
    """Wrapper over orbax CheckpointManager for TrainState pytrees, with
    commit/corruption detection and previous-step fallback on restore."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1,
                 save_retries: int = 2, retry_backoff_s: float = 0.5,
                 save_delay_s: float = 0.0,
                 run_meta: Optional[dict] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        if not HAVE_ORBAX:
            raise RuntimeError("orbax-checkpoint is not available")
        self.save_retries = max(0, int(save_retries))
        self.retry_backoff_s = retry_backoff_s
        # stamped into every manifest's "run" block (elastic resizing:
        # the replica degree + global batch this writer trained at —
        # restore across a different degree validates against it)
        self.run_meta = dict(run_meta) if run_meta else None
        # fault-injection knob (cluster/chaos.py "slow checkpoint I/O"):
        # sleep this long before submitting each save
        self.save_delay_s = save_delay_s
        # steps saved but not yet manifest-covered; flushed once the async
        # write completes (wait/close) so saves stay async on the hot path
        self._pending_manifest: set[int] = set()
        # steps whose manifest-backed verification already passed: a
        # committed step with its manifest is immutable, so re-verifying
        # (a full crc32 pass over every payload byte) on every
        # latest_step() poll — the serving registry polls it every 30s —
        # would turn a metadata lookup into continuous disk reads
        self._intact_cache: set[int] = set()
        # retention is OURS, not orbax's: orbax keep-last-N counts every
        # step directory — an uncommitted/corrupt newest step would
        # consume a retention slot and evict the last RESTORABLE step.
        # _retain() counts only intact steps and never drops the LKG.
        self.max_to_keep = max_to_keep
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=None,
                save_interval_steps=save_interval_steps),
        )
        # wall-clock op log for the goodput ledger (obs/goodput.py):
        # (op, start, end, step) per completed save/restore, drained by
        # the worker into ckpt-save/ckpt-restore trace spans. Bounded so
        # undrained consumers (serving, tests) never grow it unbounded.
        self._op_log: list[tuple] = []

    def _log_op(self, op: str, t0_wall: float, step) -> None:
        self._op_log.append((op, t0_wall, time.time(),
                             int(step) if step is not None else -1))
        del self._op_log[:-256]

    def drain_op_log(self) -> list[tuple]:
        """Pop the recorded (op, wall_start, wall_end, step) entries —
        the worker turns them into trace spans so checkpoint time lands
        in the job's badput decomposition."""
        out, self._op_log = self._op_log, []
        return out

    # ------------------------------------------------------------------ save

    def should_save(self, step: int) -> bool:
        """Whether save() at this step would actually write (interval gate).
        Lets callers avoid host-syncing device state for skipped steps."""
        return bool(self._mgr.should_save(step))

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        if self.save_delay_s > 0:
            time.sleep(self.save_delay_s)
        t0_wall = time.time()
        t0 = time.perf_counter()
        delay = self.retry_backoff_s
        for attempt in range(self.save_retries + 1):
            try:
                saved = self._mgr.save(
                    step, args=ocp.args.StandardSave(state), force=force)
                break
            except Exception as e:  # noqa: BLE001 — transient fs/IO errors
                if attempt >= self.save_retries:
                    raise
                # The resume-replay collision (chaos-suite find): restore
                # fell back past a CORRUPT step N, training replayed up to
                # N, and this save now hits orbax's "step already exists"
                # on N's remains — unretryable unless the remains go.
                # Clearing is gated on verify_step failing: an INTACT
                # existing step is never deleted to paper over a
                # programming error.
                self._clear_corrupt_step(step)
                log.warning("checkpoint save @%d failed (%s); retry %d/%d "
                            "in %.1fs", step, e, attempt + 1,
                            self.save_retries, delay)
                time.sleep(delay)
                delay *= 2
        if saved:
            log.info("checkpoint saved at step %d -> %s", step, self.directory)
            self._pending_manifest.add(step)
            _obs_duration("save").observe(time.perf_counter() - t0)
            self._log_op(SPAN_CKPT_SAVE, t0_wall, step)
        return saved

    def wait(self) -> None:
        self._mgr.wait_until_finished()
        self._flush_manifests()

    def _clear_corrupt_step(self, step: int) -> None:
        """Remove a NON-INTACT step directory and make orbax forget it.
        Multi-host safe: every host may try, rmtree tolerates the loser
        seeing a half-removed tree."""
        step_dir = os.path.join(self.directory, str(step))
        if not os.path.isdir(step_dir):
            return
        ok, reason = verify_step_dir(step_dir)
        if ok:
            return
        import shutil
        log.warning("clearing corrupt remains of step %d (%s)", step, reason)
        shutil.rmtree(step_dir, ignore_errors=True)
        self._intact_cache.discard(step)
        try:
            self._mgr.reload()   # drop orbax's cached step list
        except Exception as e:  # noqa: BLE001 — reload is best-effort
            log.warning("orbax reload after clearing step %d failed: %s",
                        step, e)

    def _flush_manifests(self) -> None:
        pending, self._pending_manifest = self._pending_manifest, set()
        if jax.process_index() != 0:
            return  # one writer: every host sees the same fs in a gang
        for step in sorted(pending):
            step_dir = os.path.join(self.directory, str(step))
            if not os.path.isdir(step_dir):
                continue  # already pruned by retention
            try:
                write_manifest(step_dir, run_meta=self.run_meta)
            except OSError as e:
                # a missing manifest only downgrades verification, never
                # the checkpoint itself — don't fail the run over it
                log.warning("manifest write for step %d failed: %s", step, e)
        self._retain()

    def _retain(self) -> None:
        """Keep-last-N counting only INTACT steps, never the LKG.

        Only intact steps beyond the keep set are deleted: a non-intact
        directory may be an in-flight async save (deleting it would race
        the writer), and it costs no retention slot anyway. Process 0
        only (called under the _flush_manifests gate).

        Deliberately does NOT warm the intact cache: retention runs on
        every flush, and caching "intact at write time" here would mask
        corruption that lands AFTER the save (truncation, bit rot) from
        every later restore-side verify in this same process — the
        exact faults tests/test_chaos.py injects."""
        if not self.max_to_keep or self.max_to_keep <= 0:
            return
        intact = []
        for s in self.all_steps():
            if s in self._intact_cache or \
                    verify_step_dir(os.path.join(self.directory,
                                                 str(s)))[0]:
                intact.append(s)
        keep = set(intact[-self.max_to_keep:])
        lkg = self.lkg_step()
        if lkg is not None:
            keep.add(lkg)
        drop = [s for s in intact if s not in keep]
        if not drop:
            return
        import shutil
        for s in drop:
            log.info("retention: dropping intact step %d (keep-last-%d "
                     "+ LKG)", s, self.max_to_keep)
            shutil.rmtree(os.path.join(self.directory, str(s)),
                          ignore_errors=True)
            self._intact_cache.discard(s)
        try:
            self._mgr.reload()   # drop orbax's cached step list
        except Exception as e:  # noqa: BLE001 — reload is best-effort
            log.warning("orbax reload after retention failed: %s", e)

    # -------------------------------------------------------- LKG tagging

    def lkg_step(self) -> Optional[int]:
        """Last-known-good step per the marker file, or None. The marker
        outlives manager instances (a rollback-restarted worker reads the
        LKG its predecessor tagged)."""
        try:
            with open(os.path.join(self.directory, LKG_MARKER)) as f:
                step = json.load(f).get("step")
        except (OSError, ValueError):
            return None
        return int(step) if isinstance(step, int) else None

    def tag_lkg(self, step: int) -> None:
        """Mark ``step`` last-known-good — the sentinel cleared the window
        AFTER it, so its state is trusted for anomaly rollback. Monotonic
        (an older tag never overwrites a newer one) and atomic; retention
        (_retain) never GCs the tagged step."""
        step = int(step)
        cur = self.lkg_step()
        if cur is not None and cur >= step:
            return
        if jax.process_index() == 0:
            tmp = os.path.join(self.directory, LKG_MARKER + ".tmp")
            with open(tmp, "w") as f:
                json.dump({"step": step, "time": time.time()}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.directory, LKG_MARKER))
        from .sentinel import lkg_gauge
        lkg_gauge().set(step)

    def discard_steps_after(self, step: int) -> None:
        """Delete every step directory NEWER than ``step``: the anomaly
        rollback path restored the LKG, so newer steps are tainted by the
        trip and must not shadow it on the next restore — and their
        remains would trip orbax's "step already exists" when training
        replays through them. Process 0 only."""
        if jax.process_index() != 0:
            return
        import shutil
        for s in self.all_steps():
            if s > step:
                log.warning("rollback: discarding tainted step %d "
                            "(> LKG %d)", s, step)
                shutil.rmtree(os.path.join(self.directory, str(s)),
                              ignore_errors=True)
                self._intact_cache.discard(s)
                self._pending_manifest.discard(s)
        try:
            self._mgr.reload()
        except Exception as e:  # noqa: BLE001 — reload is best-effort
            log.warning("orbax reload after rollback discard failed: %s", e)

    # ----------------------------------------------------------- inspection

    def all_steps(self) -> list[int]:
        """Integer-named step directories, ascending (committed or not)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(int(n) for n in names
                      if n.isdigit() and
                      os.path.isdir(os.path.join(self.directory, n)))

    def verify_step(self, step: int) -> tuple[bool, str]:
        step_dir = os.path.join(self.directory, str(step))
        if step in self._intact_cache:
            if os.path.isdir(step_dir):
                return True, "verified (cached)"
            self._intact_cache.discard(step)   # pruned by max_to_keep
            return False, "missing"
        t0 = time.perf_counter()
        ok, reason = verify_step_dir(step_dir)
        _obs_duration("verify").observe(time.perf_counter() - t0)
        if ok and os.path.exists(os.path.join(step_dir, MANIFEST_NAME)):
            # cache manifest-backed positives only: a committed step
            # without a manifest may gain one later (async flush)
            self._intact_cache.add(step)
        return ok, reason

    def run_meta_of(self, step: int) -> dict:
        """The "run" block of a step's manifest (replicaDegree,
        globalBatch — what the writer trained at). {} when the step has
        no manifest or an unreadable one: older writers never stamped
        run metadata, and that must degrade to "no validation", not an
        error."""
        mpath = os.path.join(self.directory, str(step), MANIFEST_NAME)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return {}
        run = manifest.get("run")
        return dict(run) if isinstance(run, dict) else {}

    def intact_steps(self) -> list[int]:
        """Committed + checksum-verified steps, ascending."""
        out = []
        for step in self.all_steps():
            ok, reason = self.verify_step(step)
            if ok:
                out.append(step)
            else:
                log.warning("checkpoint step %d skipped: %s", step, reason)
        return out

    def latest_step(self) -> Optional[int]:
        """Newest INTACT step — a half-written or corrupted latest
        directory is skipped, not blindly offered to restore(). Walks
        newest-first and stops at the first intact step, so the common
        case (healthy newest checkpoint) verifies exactly one step."""
        for step in reversed(self.all_steps()):
            ok, reason = self.verify_step(step)
            if ok:
                return step
            log.warning("checkpoint step %d skipped: %s", step, reason)
        return None

    # --------------------------------------------------------------- restore

    def _restore_with_fallback(self, restore_fn: Callable[[int], Any],
                               step: Optional[int],
                               max_step: Optional[int] = None) -> Any:
        """Explicit step: verify + restore that exact step (an operator
        asked for it; silently restoring another would be worse than
        failing). Implicit latest: walk intact steps newest-first and fall
        back past any step that fails verification or restore.
        ``max_step`` caps the walk (anomaly rollback: resume from the
        newest intact step ≤ LKG, never a newer tainted one) — if the
        capped step itself is corrupt the walk falls back past it."""
        if step is not None:
            ok, reason = self.verify_step(step)
            if not ok:
                raise ValueError(
                    f"checkpoint step {step} in {self.directory} is not "
                    f"intact: {reason}")
            t0_wall = time.time()
            t0 = time.perf_counter()
            out = restore_fn(step)
            _obs_duration("restore").observe(time.perf_counter() - t0)
            self._log_op(SPAN_CKPT_RESTORE, t0_wall, step)
            return out
        last_err: Optional[BaseException] = None
        # newest-first, verifying LAZILY: older steps only pay their
        # verification cost if every newer candidate was rejected
        for candidate in reversed(self.all_steps()):
            if max_step is not None and candidate > max_step:
                continue
            ok, reason = self.verify_step(candidate)
            if not ok:
                log.warning("checkpoint step %d skipped: %s",
                            candidate, reason)
                continue
            try:
                t0_wall = time.time()
                t0 = time.perf_counter()
                out = restore_fn(candidate)
                _obs_duration("restore").observe(time.perf_counter() - t0)
                self._log_op(SPAN_CKPT_RESTORE, t0_wall, candidate)
                return out
            except ElasticContractError:
                raise   # a breach is a breach at EVERY step: no fallback
            except Exception as e:  # noqa: BLE001 — fall back to prior step
                last_err = e
                log.warning("restore of step %d failed (%s); falling back "
                            "to the previous intact step", candidate, e)
        if last_err is not None:
            raise last_err
        raise FileNotFoundError(f"no intact checkpoint in {self.directory}")

    def check_elastic_resume(self, step: Optional[int],
                             replica_degree: Optional[int],
                             global_batch: Optional[int]) -> dict:
        """The elastic-resize restore contract, validated BEFORE the
        reshape: when the checkpoint was written at a different
        data-parallel replica degree than the reader's, the GLOBAL
        batch size must be unchanged (resizes trade replica count for
        per-replica batch, never the optimization trajectory — a
        changed global batch would silently alter the data order and
        the gradient noise scale) and must divide the new degree.
        Returns {"resharded": bool, "from": N, "to": M}; {} when the
        step carries no run metadata (pre-elastic writers) or no
        degree change is happening. Raises ValueError on a contract
        breach — loudly at restore, not subtly at step 1."""
        if step is None:
            step = self.latest_step()
        if step is None or replica_degree is None:
            return {}
        saved = self.run_meta_of(step)
        saved_degree = saved.get("replicaDegree")
        if not saved_degree or saved_degree == replica_degree:
            return {}
        saved_gb = saved.get("globalBatch")
        if saved_gb and global_batch and saved_gb != global_batch:
            raise ElasticContractError(
                f"elastic restore of step {step}: checkpoint was "
                f"written at global batch {saved_gb} but this worker "
                f"runs {global_batch} — resizing keeps the global "
                f"batch FIXED (only the replica degree changes); "
                f"refusing a silent trajectory change")
        if global_batch and global_batch % replica_degree:
            raise ElasticContractError(
                f"elastic restore of step {step}: global batch "
                f"{global_batch} does not divide the new replica "
                f"degree {replica_degree}")
        log.info("elastic restore @%d: reshaping state across replica "
                 "degrees %d -> %d (global batch fixed)", step,
                 saved_degree, replica_degree)
        obsreg.counter(
            "kftpu_checkpoint_elastic_restores_total",
            "restores that reshaped sharded state across a different "
            "data-parallel replica degree (elastic resize)").inc()
        return {"resharded": True, "from": saved_degree,
                "to": replica_degree}

    def restore(self, state_template: Any, step: Optional[int] = None,
                expect_run: Optional[tuple] = None,
                max_step: Optional[int] = None) -> Any:
        """Restore into the template's shardings (template = an abstract or
        concrete TrainState with the target shardings attached). This IS
        the elastic reshape: the template carries the CURRENT mesh's
        shardings, so a checkpoint written at replica degree N restores
        onto a degree-M mesh by resharding every leaf — params,
        per-replica-distributed optimizer moments (weight_update=sharded
        lays adam mu/nu over the replica axes), batch stats — into the
        new layout on load. Leaf SHAPES are degree-invariant (global
        logical arrays). ``expect_run`` = (replica_degree, global_batch)
        of the READER: the elastic contract is then validated per
        candidate step — against the step ACTUALLY restored, not merely
        the newest one, so a fallback past a corrupt step cannot dodge
        the check (a breach raises ElasticContractError instead of
        falling back: every candidate carries the same breach)."""
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if hasattr(x, "sharding") else x,
            state_template)

        def _restore(s: int) -> Any:
            if expect_run is not None:
                self.check_elastic_resume(s, *expect_run)
            return self._mgr.restore(
                s, args=ocp.args.StandardRestore(abstract))

        return self._restore_with_fallback(_restore, step,
                                           max_step=max_step)

    def restore_params(self, step: Optional[int] = None) -> Any:
        """Restore just the model params, template-free. The trainer writes
        full TrainState pytrees; a server watching the directory only wants
        params and has no opt_state template to offer — restore the raw
        tree (orbax saves pytrees as nested dicts) and take its 'params'
        subtree, or the whole tree for params-only checkpoints."""

        def _restore(s: int) -> Any:
            raw = self._mgr.restore(s, args=ocp.args.StandardRestore())
            if isinstance(raw, dict) and "params" in raw:
                return raw["params"]
            return raw

        return self._restore_with_fallback(_restore, step)

    def close(self) -> None:
        try:
            self._mgr.wait_until_finished()
            self._flush_manifests()
        except Exception as e:  # noqa: BLE001 — close stays best-effort
            log.warning("manifest flush on close failed: %s", e)
        self._mgr.close()
