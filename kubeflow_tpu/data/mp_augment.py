"""Multi-process decode+augment stage over a shared-memory ring buffer.

The single prefetch thread that used to decode+augment record batches is
GIL-bound: PERF.md's input-path table measures the real-data worker at a
fraction of the synthetic rate with the augment stage on the critical
path. This module fans the stage out over spawned worker processes with
ZERO per-batch pickling:

- one ``multiprocessing.shared_memory`` segment holds a ring of
  fixed-size slots, each sized for a full batch: a raw-record region the
  feeder memcpys into, and an output region (augmented images + labels)
  the worker writes through numpy views;
- the feeder thread (in the parent — the epoch shuffle order must come
  from the one shared record pipeline) takes a free slot, copies the raw
  slab, and enqueues a tiny (slot, seq, augment_base, n) task;
- workers decode+augment in place and post the slot back done;
- the consumer reassembles batches IN SUBMIT ORDER (determinism) and
  returns them as fresh arrays — ``jax.device_put`` may alias host
  memory on some backends, and a ring view would be overwritten on slot
  reuse, so the one host memcpy per batch is the price of a provably
  safe ring.

Backpressure is the ring itself: with every slot in flight the feeder
blocks, so host memory is bounded at ``slots`` batches regardless of how
far the record reader could run ahead. Workers are spawned (never fork a
JAX-initialized parent) and import only numpy + the data layer.

Determinism: the augment RNG base is computed by the caller per
(seed, epoch, batch index) (imagenet.augment_base), so the output is
byte-identical to the single-thread path — restart/resume and chaos
parity ride on this, and tests pin it.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import queue as thqueue
import threading
import time
from multiprocessing import shared_memory
from typing import Iterable, Optional

import numpy as np

log = logging.getLogger(__name__)


def _attach_shm(name: str) -> shared_memory.SharedMemory:
    """Attach to the parent's segment WITHOUT registering it with the
    resource tracker: the parent owns and unlinks the ring (bpo-38119 —
    an attach re-registers the name, and since the tracker's cache is a
    set, sibling workers' registrations collapse and an exiting worker
    would unlink the ring under everyone else). Suppressing the
    registration beats unregistering after the fact, which double-removes
    across siblings."""
    from multiprocessing import resource_tracker
    orig_register = resource_tracker.register
    resource_tracker.register = lambda *a, **kw: None
    try:
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig_register


def _worker_main(shm_name: str, slot_bytes: int, batch_records: int,
                 record_bytes: int, image_size: int, output: str,
                 out_dtype_str: str, pad_px: int, do_augment: bool,
                 tasks, done) -> None:
    """Augment worker entrypoint (spawned; module-level so it pickles).

    Loops: take a task, decode the slot's raw region, augment into the
    slot's output region, post done. Exceptions are reported per task —
    the parent raises them to the consuming iterator (an augment crash
    must fail the run, never truncate the epoch). Exits on the ``None``
    sentinel or SIGTERM (default handler — the parent's close()
    terminates stragglers; the processes are daemonic so a dying parent
    reaps them either way)."""
    import signal
    signal.signal(signal.SIGINT, signal.SIG_IGN)  # parent drives shutdown
    from .imagenet import augment_batch, decode_records
    out_dtype = np.dtype(out_dtype_str)
    hw3 = image_size * image_size * 3

    def process(shm, slot: int, base: int, n: int) -> None:
        # function-local views: they must all be released before the
        # final shm.close() (mmap refuses to close with exported buffers)
        off = slot * slot_bytes
        # private copy of the slab before the gather: the augment's
        # random-access reads are measurably slower against shm pages
        # the feeder's core just dirtied (cross-core coherence misses);
        # one sequential memcpy is cheaper than paying them per pixel
        raw = np.array(np.frombuffer(shm.buf, np.uint8, n * record_bytes,
                                     off).reshape(n, record_bytes))
        images, labels = decode_records(raw, image_size)
        out = augment_batch(images, base, pad_px,
                            do_flip=do_augment, do_crop=do_augment,
                            output=output, image_dtype=out_dtype)
        img_off = off + batch_records * record_bytes
        lab_off = img_off + batch_records * hw3 * out_dtype.itemsize
        np.frombuffer(shm.buf, out_dtype, n * hw3, img_off).reshape(
            n, image_size, image_size, 3)[:] = out
        np.frombuffer(shm.buf, np.int32, n, lab_off)[:] = labels

    shm = _attach_shm(shm_name)
    try:
        while True:
            task = tasks.get()
            if task is None:
                return
            slot, seq, base, n = task
            try:
                process(shm, slot, base, n)
                done.put(("ok", slot, seq, n))
            except Exception as e:  # noqa: BLE001 - surfaced to the consumer
                done.put(("error", slot, seq, f"{type(e).__name__}: {e}"))
    finally:
        shm.close()


class AugmentPool:
    """Bounded multi-process decode+augment pipeline (see module doc).

    Usage::

        pool = AugmentPool(workers=4, batch_records=B, record_bytes=R,
                           image_size=S, output="uint8")
        pool.start(gen)          # gen yields (raw_records, augment_base)
        for batch in pool:       # {"images": ..., "labels": ...} in order
            ...
        pool.close()

    The iterator raises the feeder's exception (after delivering every
    batch submitted before it), a worker task failure, or a
    RuntimeError when a worker process dies — a crashed stage must fail
    the run, never silently truncate it.
    """

    def __init__(self, *, workers: int, batch_records: int,
                 record_bytes: int, image_size: int,
                 output: str = "uint8", image_dtype=np.float32,
                 pad_px: int = 4, augment: bool = True,
                 slots: Optional[int] = None):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.workers = int(workers)
        self.batch_records = int(batch_records)
        self.record_bytes = int(record_bytes)
        self.image_size = int(image_size)
        self.output = output
        self.out_dtype = np.dtype(np.uint8 if output == "uint8"
                                  else image_dtype)
        # ring depth = the backpressure bound: the feeder blocks once
        # every slot is in flight. workers+2 keeps each worker busy with
        # one slab queued and one finished batch awaiting the consumer.
        self.slots = int(slots) if slots else self.workers + 2
        if self.slots < 2:
            raise ValueError(f"slots must be >= 2, got {self.slots}")
        hw3 = self.image_size * self.image_size * 3
        self._raw_bytes = self.batch_records * self.record_bytes
        self._img_bytes = self.batch_records * hw3 * self.out_dtype.itemsize
        self._lab_bytes = self.batch_records * 4
        self.slot_bytes = self._raw_bytes + self._img_bytes + self._lab_bytes
        # everything close() touches exists BEFORE anything that can
        # fail mid-construction (shm create, worker spawn): a partial
        # __init__ must still tear down cleanly instead of leaking the
        # ring segment and already-started workers
        self._closed = False
        # input-stage rate for the shared registry (obs/registry.py):
        # handle resolved once — __next__ is the per-batch hot path
        from ..obs import registry as obsreg
        self._obs_batches = obsreg.counter(
            "kftpu_input_batches_total",
            "batches delivered by each input-pipeline stage",
            labels=("stage",)).labels(stage="augment")
        self._stop = threading.Event()
        self._feeder: Optional[threading.Thread] = None
        self._feed_error: Optional[BaseException] = None
        self._feed_total: Optional[int] = None
        self._ready: dict[int, tuple[int, int]] = {}
        self._next_seq = 0
        self._procs: list = []
        self._shm = None
        self._free: thqueue.Queue = thqueue.Queue()
        for s in range(self.slots):
            self._free.put(s)
        ctx = mp.get_context("spawn")   # never fork a JAX-initialized parent
        self._tasks = ctx.Queue()
        self._done = ctx.Queue()
        try:
            self._shm = shared_memory.SharedMemory(
                create=True, size=self.slots * self.slot_bytes)
            self._procs = [
                ctx.Process(
                    target=_worker_main,
                    args=(self._shm.name, self.slot_bytes,
                          self.batch_records, self.record_bytes,
                          self.image_size, output, self.out_dtype.str,
                          pad_px, augment, self._tasks, self._done),
                    daemon=True, name=f"kftpu-augment-{i}")
                for i in range(self.workers)]
            for p in self._procs:
                p.start()
        except BaseException:
            self.close()
            raise

    # -- feeding ------------------------------------------------------------

    def start(self, source: Iterable) -> "AugmentPool":
        """Begin feeding from ``source``, which yields
        (raw_records (n, record_bytes) uint8, augment_base) pairs."""
        if self._feeder is not None:
            raise RuntimeError("AugmentPool already started")
        self._feeder = threading.Thread(target=self._feed, args=(source,),
                                        daemon=True,
                                        name="kftpu-augment-feed")
        self._feeder.start()
        return self

    def _feed(self, source) -> None:
        seq = 0
        try:
            for raw, base in source:
                slot = self._take_slot()
                if slot is None:
                    return          # closing
                raw = np.ascontiguousarray(raw, np.uint8)
                n = raw.shape[0]
                if n > self.batch_records:
                    raise ValueError(
                        f"batch of {n} records exceeds the ring's slab "
                        f"capacity {self.batch_records}")
                off = slot * self.slot_bytes
                np.frombuffer(self._shm.buf, np.uint8,
                              n * self.record_bytes, off)[:] = \
                    raw.reshape(-1)
                self._tasks.put((slot, seq, int(base), n))
                seq += 1
        except BaseException as e:  # noqa: BLE001 - surfaced to the consumer
            self._feed_error = e
        finally:
            self._feed_total = seq

    def _take_slot(self) -> Optional[int]:
        while not self._stop.is_set():
            try:
                return self._free.get(timeout=0.1)
            except thqueue.Empty:
                continue
        return None

    # -- consuming ----------------------------------------------------------

    def __iter__(self) -> "AugmentPool":
        return self

    def __next__(self) -> dict:
        if self._closed:
            raise RuntimeError("AugmentPool is closed")
        while True:
            if self._next_seq in self._ready:
                slot, n = self._ready.pop(self._next_seq)
                batch = self._copy_out(slot, n)
                self._free.put(slot)
                self._next_seq += 1
                self._obs_batches.inc()
                return batch
            total = self._feed_total
            if total is not None and self._next_seq >= total \
                    and not self._ready:
                # every submitted batch delivered; the feeder's outcome
                # decides between clean EOF and a propagated crash
                if self._feed_error is not None:
                    raise self._feed_error
                raise StopIteration
            try:
                msg = self._done.get(timeout=0.2)
            except thqueue.Empty:
                self._check_workers()
                continue
            if msg[0] == "ok":
                _, slot, seq, n = msg
                self._ready[seq] = (slot, n)
            else:
                _, _slot, seq, err = msg
                raise RuntimeError(
                    f"augment worker failed on batch {seq}: {err}")

    def _check_workers(self) -> None:
        for p in self._procs:
            if not p.is_alive():
                raise RuntimeError(
                    f"augment worker {p.name} died "
                    f"(exitcode {p.exitcode}) — input stage lost")

    def _copy_out(self, slot: int, n: int) -> dict:
        """Fresh arrays, not ring views: jax.device_put may alias host
        memory, and a view would be overwritten on slot reuse."""
        hw3 = self.image_size * self.image_size * 3
        off = slot * self.slot_bytes
        img_off = off + self._raw_bytes
        lab_off = img_off + self._img_bytes
        images = np.frombuffer(self._shm.buf, self.out_dtype, n * hw3,
                               img_off).reshape(
            n, self.image_size, self.image_size, 3).copy()
        labels = np.frombuffer(self._shm.buf, np.int32, n, lab_off).copy()
        return {"images": images, "labels": labels}

    # -- teardown -----------------------------------------------------------

    def close(self) -> None:
        """Idempotent teardown, safe from SIGTERM/preemption handling:
        stop the feeder, sentinel + join the workers (terminating
        stragglers), drain the queues so their flush threads exit, and
        unlink the shared-memory ring."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._feeder is not None:
            self._feeder.join(timeout=10)
            if self._feeder.is_alive():   # wedged in the record reader
                log.warning("augment feeder did not stop within 10s")
        for _ in self._procs:
            try:
                self._tasks.put(None)
            except (ValueError, OSError):
                break
        started = [p for p in self._procs if p.pid is not None]
        deadline = time.monotonic() + 5.0
        for p in started:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
        for p in started:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
        try:
            while True:
                self._done.get_nowait()
        except (thqueue.Empty, ValueError, OSError):
            pass
        for q in (self._tasks, self._done):
            try:
                q.close()
                q.cancel_join_thread()
            except (ValueError, OSError):
                pass
        self._ready.clear()
        if self._shm is None:     # construction failed before the ring
            return
        try:
            self._shm.close()
        except BufferError:
            # a stray view still exports the mmap; unlink below still
            # releases the name, and the map goes with the process
            log.warning("shared-memory ring closed with live views")
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass

    def __enter__(self) -> "AugmentPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
