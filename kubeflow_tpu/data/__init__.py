"""Host-side input pipeline (the PS-role host path, SURVEY.md §2.5 row 1).

``RecordPipeline`` reads fixed-size records from shard files with seeded
epoch shuffling and threaded prefetch — native C++ core when the toolchain
is available (native/datapipe), pure-Python fallback otherwise. Both
implementations produce IDENTICAL record order for a given seed.
"""

from .pipeline import PyRecordPipeline, RecordPipeline, epoch_order
from .native import NativeRecordPipeline, native_available

__all__ = ["RecordPipeline", "PyRecordPipeline", "NativeRecordPipeline",
           "native_available", "epoch_order"]
