"""ImageNet-style record dataset: fixed-size image records under the
record pipeline (native C++ fast path, Python fallback).

The reference's flagship workload trains ResNet-50 on real ImageNet via
tf_cnn_benchmarks --data_dir (tf-controller-examples/tf-cnn/launcher.py:
68-93); this is the TPU-native input path for the same job: shard files of
fixed-size records streamed by the prefetching record pipeline
(data/native.py / data/pipeline.py), decoded and augmented host-side with
numpy, fed to the device as one placed batch per step.

Record layout (record_bytes = 4 + H*W*3):
    int32 LE label | uint8 image[H][W][3]

A `meta.json` sidecar makes shard dirs self-describing:
    {"image_size": H, "num_classes": N, "record_bytes": B,
     "num_records": R, "format": "kftpu-imagenet-v1"}

Augmentation is the tf_cnn_benchmarks training default reduced to what
fixed-size storage supports: random horizontal flip + random crop with
4-pixel reflection padding, seeded per epoch so runs are deterministic
per (seed, epoch) — the determinism contract the tests pin down.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, Optional

import numpy as np

from .pipeline import RecordPipeline

META_NAME = "meta.json"
FORMAT = "kftpu-imagenet-v1"
LABEL_BYTES = 4

# ImageNet channel stats (tf_cnn_benchmarks preprocessing constants)
MEAN_RGB = np.array([0.485, 0.456, 0.406], np.float32)
STDDEV_RGB = np.array([0.229, 0.224, 0.225], np.float32)

_MASK = (1 << 64) - 1
_GOLDEN = 0x9E3779B97F4A7C15


def augment_base(seed: int, epoch: int, batch_index: int) -> int:
    """The per-batch augment RNG base; stream = pure fn of (data, seed)."""
    return (((seed << 20) ^ epoch) * 1_000_003 + batch_index) & _MASK


def augment_params(base: int, n: int, pad: int
                   ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-record (flip, dy, dx) — the splitmix64 derivation mirrored in
    native/augment/augment.cc params_for (keep in sync!). Vectorized."""
    idx = np.arange(1, n + 1, dtype=np.uint64)
    state = (np.uint64(base) + idx * np.uint64(_GOLDEN)) & np.uint64(_MASK)

    def splitmix(state):
        state = (state + np.uint64(_GOLDEN)) & np.uint64(_MASK)
        z = state
        z = ((z ^ (z >> np.uint64(30))) *
             np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(_MASK)
        z = ((z ^ (z >> np.uint64(27))) *
             np.uint64(0x94D049BB133111EB)) & np.uint64(_MASK)
        return z ^ (z >> np.uint64(31)), state

    z1, state = splitmix(state)
    z2, state = splitmix(state)
    flip = (z1 & np.uint64(1)) != 0
    span = np.uint64(2 * pad + 1)
    dy = ((z2 >> np.uint64(1)) % span).astype(np.int64)
    dx = ((z2 >> np.uint64(33)) % span).astype(np.int64)
    return flip, dy, dx


def _py_augment(images: np.ndarray, base: int, pad: int, *,
                do_flip: bool, do_crop: bool,
                normalize: bool = True) -> np.ndarray:
    """Numpy fallback producing the native kernel's exact output."""
    n, h, w, _ = images.shape
    flip, dy, dx = augment_params(base, n, pad)
    if not do_flip:
        flip = np.zeros(n, bool)
    if not do_crop:
        dy = np.full(n, pad, np.int64)
        dx = np.full(n, pad, np.int64)
    coords = np.arange(h)

    def reflect(v, size):
        v = np.abs(v)
        return np.where(v >= size, 2 * size - 2 - v, v)

    out = np.empty((n, h, w, 3),
                   np.float32 if normalize else np.uint8)
    for i in range(n):
        sy = reflect(coords + dy[i] - pad, h)
        sx = reflect(coords + dx[i] - pad, w)
        if flip[i]:
            sx = w - 1 - sx
        out[i] = images[i][np.ix_(sy, sx)]
    if not normalize:
        return out
    # same op order as the C++ kernel (x*scale - shift, f32) so the two
    # paths are bit-identical
    scale = np.float32(1.0) / (np.float32(255.0) * STDDEV_RGB)
    shift = MEAN_RGB / STDDEV_RGB
    out *= scale
    out -= shift
    return out


def decode_records(raw: np.ndarray, image_size: int
                   ) -> tuple[np.ndarray, np.ndarray]:
    """(N, record_bytes) uint8 rows → ((N,H,H,3) uint8 image view,
    (N,) int32 labels). Shared by ImageNetSource and the multi-process
    augment workers (data/mp_augment.py) so the two paths cannot drift."""
    n = raw.shape[0]
    labels = raw[:, :LABEL_BYTES].copy().view("<i4").reshape(n)
    images = raw[:, LABEL_BYTES:].reshape(n, image_size, image_size, 3)
    return images, labels.astype(np.int32, copy=False)


def augment_batch(images: np.ndarray, base: int, pad: int, *,
                  do_flip: bool, do_crop: bool, output: str = "normalized",
                  image_dtype=np.float32) -> np.ndarray:
    """One fused augment pass over a decoded uint8 batch: flip +
    reflect-pad crop (+ normalize unless output='uint8', the
    device-normalize mode). Native C++ fast path
    (native/augment/augment.cc), numpy fallback computing the
    bit-identical result from the same splitmix64 parameters
    (KFTPU_AUGMENT_IMPL=py kill-switches the native kernel — also how
    ``bench.py --mode input`` pins BOTH A/B arms to the GIL-bound
    implementation for a matched architecture comparison). Pure
    function of (images, base) — the determinism contract the
    single-thread and multi-process paths both ride."""
    from .native import native_augment, native_augment_u8, native_available
    use_native = native_available() and \
        os.environ.get("KFTPU_AUGMENT_IMPL", "native") != "py"
    if output == "uint8":
        if use_native:
            return native_augment_u8(images, base, pad,
                                     do_flip=do_flip, do_crop=do_crop)
        return _py_augment(images, base, pad, do_flip=do_flip,
                           do_crop=do_crop, normalize=False)
    if use_native:
        out = native_augment(images, base, pad, MEAN_RGB, STDDEV_RGB,
                             do_flip=do_flip, do_crop=do_crop)
    else:
        out = _py_augment(images, base, pad, do_flip=do_flip,
                          do_crop=do_crop)
    return out.astype(image_dtype, copy=False)


def device_normalize(images_u8):
    """The on-device half of the uint8 input mode: identical math to the
    host normalize (x*(1/(255*std)) - mean/std, f32). Runs inside jit on
    the already-placed batch so only uint8 crosses host→device."""
    import jax.numpy as jnp
    scale = jnp.asarray(1.0 / (255.0 * STDDEV_RGB), jnp.float32)
    shift = jnp.asarray(MEAN_RGB / STDDEV_RGB, jnp.float32)
    return images_u8.astype(jnp.float32) * scale - shift


def record_bytes(image_size: int) -> int:
    return LABEL_BYTES + image_size * image_size * 3


def write_shards(out_dir: str, images: np.ndarray, labels: np.ndarray,
                 *, shard_records: int = 1024,
                 num_classes: Optional[int] = None) -> dict:
    """Write (N,H,W,3) uint8 images + (N,) int labels as record shards.

    The fixture/ingest writer (the analog of the reference's imagenet
    preprocessing scripts feeding tf_cnn_benchmarks)."""
    images = np.ascontiguousarray(images, np.uint8)
    labels = np.asarray(labels)
    if images.ndim != 4 or images.shape[3] != 3 or \
            images.shape[1] != images.shape[2]:
        raise ValueError(f"images must be (N,H,H,3) uint8, got {images.shape}")
    if len(labels) != len(images):
        raise ValueError("images/labels length mismatch")
    image_size = images.shape[1]
    os.makedirs(out_dir, exist_ok=True)
    n = len(images)
    shard = 0
    for start in range(0, n, shard_records):
        end = min(start + shard_records, n)
        path = os.path.join(out_dir, f"shard-{shard:05d}.rec")
        with open(path, "wb") as f:
            for i in range(start, end):
                f.write(np.int32(labels[i]).tobytes())
                f.write(images[i].tobytes())
        shard += 1
    meta = {
        "format": FORMAT,
        "image_size": image_size,
        "num_classes": int(num_classes if num_classes is not None
                           else int(labels.max()) + 1 if n else 0),
        "record_bytes": record_bytes(image_size),
        "num_records": n,
    }
    with open(os.path.join(out_dir, META_NAME), "w") as f:
        json.dump(meta, f)
    return meta


def read_meta(data_dir: str) -> dict:
    path = os.path.join(data_dir, META_NAME)
    with open(path) as f:
        meta = json.load(f)
    if meta.get("format") != FORMAT:
        raise ValueError(f"{path}: unknown format {meta.get('format')!r}")
    return meta


def shard_paths(data_dir: str) -> list[str]:
    return sorted(
        os.path.join(data_dir, f) for f in os.listdir(data_dir)
        if f.endswith(".rec"))


class _Prefetcher:
    """Run an iterator on a daemon thread, `depth` items ahead (the
    input-overlap half of launcher.py's async data pipeline). stop() is
    safe to call from the consumer side and JOINS the producer, so the
    owner may tear down resources the iterator uses afterwards."""

    _END = object()

    def __init__(self, it: Iterator, depth: int):
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        # producer outcome, tracked OUTSIDE the queue: the queued END /
        # exception item can be lost (a stop() drain, a failed _put), and
        # a consumer that then sees only a dead thread must be able to
        # tell "finished cleanly" from "died mid-epoch" — the latter used
        # to end iteration silently, truncating the epoch while the run
        # "succeeded" on partial data.
        self._done = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._produce, args=(it,),
                                        daemon=True,
                                        name="imagenet-prefetch")
        self._thread.start()

    def _put(self, item) -> bool:
        import queue
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self, it) -> None:
        try:
            for item in it:
                if not self._put(item):
                    self._done = True   # consumer-initiated stop, not a death
                    return
            self._done = True
            self._put(self._END)
        except BaseException as e:  # noqa: BLE001 - surface to consumer
            self._error = e
            self._put(e)

    def __iter__(self) -> Iterator:
        import queue
        try:
            while True:
                try:
                    item = self._q.get(timeout=0.5)
                except queue.Empty:
                    if not self._thread.is_alive():
                        if self._error is not None:
                            # the queued exception was lost (put raced a
                            # stop/drain) — raise the tracked copy
                            raise self._error
                        if not self._done:
                            raise RuntimeError(
                                "prefetch producer died without an error "
                                "or EOF — refusing to pass a truncated "
                                "epoch off as complete")
                        return
                    continue
                if item is self._END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            self.stop()

    def stop(self) -> None:
        import queue
        self._stop.set()
        try:  # unblock a producer stuck on a full queue
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)


class ImageNetSource:
    """Decoded, augmented, normalized batches from a shard dir.

    Yields {"images": float32 (B,H,H,3) normalized, "labels": int32 (B,)}.
    Epochs reshuffle with a derived seed; augmentation RNG is seeded per
    epoch so the stream is a pure function of (data, seed)."""

    def __init__(self, data_dir: str, batch_size: int, *,
                 augment: bool = True, pad_px: int = 4,
                 num_threads: int = 2, queue_depth: int = 4,
                 image_dtype: Optional[np.dtype] = None,
                 output: str = "normalized",
                 drop_remainder: bool = True,
                 workers: int = 0,
                 ring_slots: Optional[int] = None):
        if workers < 0:
            raise ValueError(f"workers must be >= 0, got {workers}")
        if output not in ("normalized", "uint8"):
            raise ValueError(f"output {output!r} not in "
                             "('normalized', 'uint8')")
        if output == "uint8" and image_dtype is not None:
            raise ValueError(
                "image_dtype conflicts with output='uint8' (bytes ship "
                "as-is; normalize on device picks the compute dtype)")
        # "uint8": ship raw augmented bytes and normalize ON DEVICE
        # (device_normalize) — 1/4 the host→device traffic
        self.output = output
        self.meta = read_meta(data_dir)
        self.image_size = int(self.meta["image_size"])
        self.num_classes = int(self.meta["num_classes"])
        self.batch_size = batch_size
        self.augment = augment
        self.pad_px = pad_px
        self.image_dtype = image_dtype or np.float32
        # multi-process augment stage: decode+augment fan out over
        # `workers` spawned processes writing a shared-memory ring
        # (data/mp_augment.py), byte-identical to the in-process path.
        # 0 = the single prefetch-thread path.
        self.workers = int(workers)
        self._ring_slots = ring_slots
        self._mp_pool = None
        self._num_threads = num_threads
        self._queue_depth = queue_depth
        self._paths = shard_paths(data_dir)
        if not self._paths:
            raise FileNotFoundError(f"no .rec shards in {data_dir}")
        # validate from meta; the pipeline itself is constructed lazily on
        # first epoch() with the real seed (constructing it here would
        # start a prefetch pass epoch() immediately throws away)
        self.drop_remainder = drop_remainder
        n_rec = int(self.meta["num_records"])
        self.num_batches = (n_rec // batch_size if drop_remainder
                            else -(-n_rec // batch_size))
        if self.num_batches == 0:
            raise ValueError(
                f"{data_dir}: {self.meta['num_records']} records < "
                f"batch_size {batch_size} (empty epochs)")
        self._pipeline = None
        self._prefetcher = None

    # -- decode / augment (host-side) ---------------------------------------

    def _decode(self, raw: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        return decode_records(raw, self.image_size)

    def _augment_normalize(self, images: np.ndarray, base: int,
                           augment: bool) -> np.ndarray:
        return augment_batch(images, base, self.pad_px,
                             do_flip=augment, do_crop=augment,
                             output=self.output,
                             image_dtype=self.image_dtype)

    # -- iteration -----------------------------------------------------------

    def _epoch_pipeline(self, epoch: int, seed: int):
        """The record pipeline reset/constructed for one epoch's shuffle."""
        if self._pipeline is None:
            self._pipeline = RecordPipeline(
                self._paths, self.meta["record_bytes"], self.batch_size,
                num_threads=self._num_threads,
                queue_depth=self._queue_depth, seed=seed + epoch,
                drop_remainder=self.drop_remainder)
        else:
            self._pipeline.reset(seed + epoch)
        return self._pipeline

    def epoch(self, epoch: int, seed: int = 0, skip: int = 0
              ) -> Iterator[dict]:
        """One pass over the data for the given epoch index. ``skip``
        drops the first N batches (resume); determinism holds because the
        augment RNG is derived per (seed, epoch, batch index), not drawn
        sequentially."""
        for i, raw in enumerate(self._epoch_pipeline(epoch, seed)):
            if i < skip:
                continue
            images, labels = self._decode(raw)
            base = augment_base(seed, epoch, i)
            yield {"images": self._augment_normalize(images, base,
                                                     self.augment),
                   "labels": labels}

    def batches(self, seed: int = 0, start_batch: int = 0,
                prefetch: int = 2) -> Iterator[dict]:
        """Infinite stream across epochs (the train-loop feed).
        ``start_batch`` = global batch index to resume from (checkpoint
        restarts must not replay already-seen batches). ``prefetch``
        decode+augment batches ahead on a worker thread so host
        preprocessing overlaps device compute (0 = synchronous). With
        ``workers > 0`` the decode+augment stage instead fans out over
        that many spawned processes through a shared-memory ring
        (data/mp_augment.py) — same batches, byte-identical."""
        if self.workers > 0:
            yield from self._mp_batches(seed, start_batch)
            return

        def gen():
            epoch = start_batch // self.num_batches
            skip = start_batch % self.num_batches
            while True:
                yield from self.epoch(epoch, seed, skip=skip)
                epoch += 1
                skip = 0

        if prefetch <= 0:
            yield from gen()
            return
        # the source owns the prefetcher: close() must JOIN the producer
        # before destroying the pipeline it reads from. One active stream
        # per source: a new batches() call supersedes the previous one
        # (two producers would race the shared record pipeline).
        if self._prefetcher is not None:
            self._prefetcher.stop()
        self._prefetcher = _Prefetcher(gen(), depth=prefetch)
        yield from self._prefetcher

    def _mp_batches(self, seed: int, start_batch: int) -> Iterator[dict]:
        """The multi-process augment stage: this process only READS raw
        record batches (the shuffle order must come from the one shared
        pipeline) and memcpys them into the shared-memory ring; spawned
        workers decode+augment each slab in place; batches come back in
        submit order. Determinism: identical to the single-thread path
        because the augment RNG is a pure function of
        (seed, epoch, batch index) — pinned by tests."""
        from .mp_augment import AugmentPool
        if self._mp_pool is not None:
            self._mp_pool.close()
        pool = AugmentPool(
            workers=self.workers,
            batch_records=self.batch_size,
            record_bytes=int(self.meta["record_bytes"]),
            image_size=self.image_size,
            output=self.output,
            image_dtype=self.image_dtype,
            pad_px=self.pad_px,
            augment=self.augment,
            slots=self._ring_slots)
        self._mp_pool = pool

        def feed():
            epoch = start_batch // self.num_batches
            skip = start_batch % self.num_batches
            while True:
                for i, raw in enumerate(self._epoch_pipeline(epoch, seed)):
                    if i < skip:
                        continue
                    yield raw, augment_base(seed, epoch, i)
                epoch += 1
                skip = 0

        pool.start(feed())
        yield from pool

    def close(self) -> None:
        # stop + join the producers FIRST: the prefetch thread / the mp
        # feeder may be inside the native pipeline's dp_next, which must
        # not race dp_destroy
        if self._prefetcher is not None:
            self._prefetcher.stop()
            self._prefetcher = None
        if self._mp_pool is not None:
            self._mp_pool.close()
            self._mp_pool = None
        if self._pipeline is not None:
            self._pipeline.close()
            self._pipeline = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
