"""Device-side input prefetch: double-buffered ``jax.device_put``.

Stage host batches onto the mesh ``depth`` batches ahead of the
consuming train step, so host→device copies overlap device compute
instead of serializing with it — the prefetch-to-device half of the
overlapped input pipeline (PERF.md "Real-data input path"; the standard
design in the MLPerf-style ImageNet reference trainers).

``jax.device_put`` is an async dispatch: placing batch N+depth returns
immediately while the transfer proceeds in the background, and the step
consuming batch N synchronizes only on the buffers it actually reads.
Depth 2 (double buffering) hides any transfer shorter than a step;
deeper pipelines buy slack against jittery host-side producers at the
cost of ``depth`` extra device-resident batches — the ONLY extra HBM
this holds (buffers are handed off, never retained, so device memory
does not grow with iteration count).

The prefetcher tops the queue up to ``depth`` BEFORE yielding, so every
batch it returns had its transfer dispatched at least one call earlier —
the lead time that hides H2D under the step. The flip side is accepted
deliberately: when the host-side producer stalls, ``__next__`` waits for
the refill even while staged batches sit ready. Buffering against
producer jitter is the upstream augment ring's job (its slots already
hold ``workers + 2`` finished slabs); this stage's one job is transfer
lead, and yielding refill-first would hand 1-in-``depth`` batches to the
step with a zero-lead, critical-path copy.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable


class DevicePrefetcher:
    """Iterator of device-placed batches, ``depth`` ahead of the consumer.

    ``place_fn`` is typically ``TrainStepBuilder.place_batch`` — whatever
    it returns is what the consumer sees, so sharded placement is exactly
    the non-prefetched path's (tests pin this)."""

    def __init__(self, source: Iterable, place_fn: Callable[[Any], Any],
                 depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._it = iter(source)
        self._place = place_fn
        self.depth = int(depth)
        self._buf: deque = deque()
        self._exhausted = False
        # staging rate for the shared registry: one inc per dispatched
        # transfer (handle resolved once — _fill is per-batch)
        from ..obs import registry as obsreg
        self._obs_batches = obsreg.counter(
            "kftpu_input_batches_total",
            "batches delivered by each input-pipeline stage",
            labels=("stage",)).labels(stage="device_put")

    @property
    def in_flight(self) -> int:
        """Batches currently staged on device (≤ depth — the HBM bound)."""
        return len(self._buf)

    def _fill(self) -> None:
        while not self._exhausted and len(self._buf) < self.depth:
            try:
                item = next(self._it)
            except StopIteration:
                self._exhausted = True
                return
            self._buf.append(self._place(item))
            self._obs_batches.inc()

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        # fill-then-pop: topping up BEFORE yielding guarantees the
        # returned batch was placed at least one call earlier, i.e. its
        # transfer had a full step to complete (see module doc for why
        # this wins over yielding staged batches refill-first)
        self._fill()
        if not self._buf:
            raise StopIteration
        return self._buf.popleft()

    def close(self) -> None:
        """Drop the staged batches (releases their device buffers) and
        stop pulling from the source — which its owner closes; an
        early-stopped run must not leave ``depth`` batches pinned in
        HBM or a producer feeding a dead consumer."""
        self._buf.clear()
        self._exhausted = True
