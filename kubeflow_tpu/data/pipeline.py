"""Pure-Python record pipeline (fallback + reference semantics).

Same contract as the native core (native/datapipe/datapipe.cc): fixed-size
records across shard files, seeded splitmix64 Fisher-Yates epoch shuffle,
threaded prefetch of whole batches, in-order delivery. The native core is
the production path; this one is the portable fallback and the executable
spec the native core is tested against (identical record order per seed).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Iterator, Optional, Sequence

import numpy as np

_MASK = (1 << 64) - 1


def _splitmix64(state: int) -> tuple[int, int]:
    state = (state + 0x9E3779B97F4A7C15) & _MASK
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
    return z ^ (z >> 31), state


def epoch_order(n: int, seed: int) -> np.ndarray:
    """The epoch's record permutation — bit-identical to the native core."""
    order = np.arange(n, dtype=np.int64)
    state = seed & _MASK
    for i in range(n - 1, 0, -1):
        r, state = _splitmix64(state)
        j = r % (i + 1)
        order[i], order[j] = order[j], order[i]
    return order


class PyRecordPipeline:
    """Threaded prefetching reader over fixed-size record shard files."""

    def __init__(self, paths: Sequence[str], record_bytes: int,
                 batch_records: int, *, queue_depth: int = 4,
                 seed: int = 0, drop_remainder: bool = True,
                 num_threads: int = 1):
        if record_bytes <= 0 or batch_records <= 0:
            raise ValueError("record_bytes and batch_records must be > 0")
        if not paths:
            raise ValueError("at least one shard file required")
        self.paths = list(paths)
        self.record_bytes = record_bytes
        self.batch_records = batch_records
        self.queue_depth = max(2, queue_depth)
        self.drop_remainder = drop_remainder
        self.num_threads = max(1, num_threads)  # parity with native arg

        self._spans: list[tuple[str, int, int]] = []  # path, first, records
        cursor = 0
        for p in self.paths:
            size = os.path.getsize(p)
            records = size // record_bytes
            self._spans.append((p, cursor, records))
            cursor += records
        self.total_records = cursor
        self._files = {p: open(p, "rb") for p in self.paths}
        self._file_lock = threading.Lock()
        self._epoch_state: Optional[tuple] = None
        self.reset(seed)

    @property
    def num_batches(self) -> int:
        if self.drop_remainder:
            return self.total_records // self.batch_records
        return -(-self.total_records // self.batch_records)

    def reset(self, seed: int) -> None:
        """New epoch: reshuffle and restart the prefetcher."""
        self._stop_prefetch()
        self.order = epoch_order(self.total_records, seed)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        self._stop = threading.Event()
        # producer outcome tracked outside the queue (the queued EOF /
        # exception can be lost to a stop-side drain): a consumer facing
        # a dead thread must distinguish clean EOF from a mid-epoch death
        # — and must never block forever on an empty queue
        self._finished = False
        self._error: "Exception | None" = None
        self._thread = threading.Thread(target=self._producer, daemon=True,
                                        name="py-datapipe")
        self._thread.start()

    def _stop_prefetch(self) -> None:
        stop = getattr(self, "_stop", None)
        if stop is not None:
            stop.set()
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5)

    def _read_record(self, global_idx: int, out: memoryview) -> None:
        for path, first, records in self._spans:
            if first <= global_idx < first + records:
                with self._file_lock:
                    f = self._files[path]
                    f.seek((global_idx - first) * self.record_bytes)
                    data = f.read(self.record_bytes)
                out[:] = data
                return
        raise IndexError(f"record {global_idx} out of range")

    def _producer(self) -> None:
        try:
            for b in range(self.num_batches):
                if self._stop.is_set():
                    return
                start = b * self.batch_records
                end = min(start + self.batch_records, self.total_records)
                buf = np.empty(((end - start) * self.record_bytes,), np.uint8)
                view = memoryview(buf)
                for i, idx in enumerate(self.order[start:end]):
                    self._read_record(
                        int(idx),
                        view[i * self.record_bytes:(i + 1) * self.record_bytes])
                while not self._stop.is_set():
                    try:
                        self._q.put(buf, timeout=0.1)
                        break
                    except queue.Full:
                        continue
            self._finished = True
            if not self._stop.is_set():
                self._q.put(None)  # EOF
        except Exception as e:  # noqa: BLE001 - surfaced to the consumer
            self._error = e
            self._q.put(e)

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            try:
                item = self._q.get(timeout=0.5)
            except queue.Empty:
                if not self._thread.is_alive():
                    if self._error is not None:
                        raise self._error   # queued copy lost to a drain
                    if not self._finished and not self._stop.is_set():
                        raise RuntimeError(
                            "record pipeline producer died without an "
                            "error or EOF — partial epoch")
                    return
                continue
            if item is None:
                return
            if isinstance(item, Exception):
                raise item
            yield item.reshape(-1, self.record_bytes)

    def close(self) -> None:
        self._stop_prefetch()
        for f in self._files.values():
            f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def RecordPipeline(paths: Sequence[str], record_bytes: int,
                   batch_records: int, **kw):
    """Factory: native core when buildable, Python fallback otherwise."""
    from .native import NativeRecordPipeline, native_available
    if native_available():
        return NativeRecordPipeline(paths, record_bytes, batch_records, **kw)
    return PyRecordPipeline(paths, record_bytes, batch_records, **kw)
