"""ctypes binding to the native data-pipeline core (native/datapipe).

Built lazily with the baked-in g++ (no pip; pybind11 unavailable by policy
— ctypes over a C ABI instead). ``native_available()`` gates the fast path;
everything degrades to the pure-Python pipeline when the toolchain or build
is missing.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Iterator, Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libkfdatapipe.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    """make, serialized across processes: a fleet of workers starting
    with a stale .so must not race g++ against each other's dlopen."""
    try:
        os.makedirs(os.path.join(_NATIVE_DIR, "build"), exist_ok=True)
        lock_path = os.path.join(_NATIVE_DIR, "build", ".build.lock")
        with open(lock_path, "w") as lock:
            try:
                import fcntl
                fcntl.flock(lock, fcntl.LOCK_EX)
            except ImportError:  # non-posix: best effort
                pass
            subprocess.run(["make", "-C", _NATIVE_DIR],
                           check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.warning("native datapipe build failed (%s); using the "
                    "pure-Python pipeline", e)
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        def try_load():
            lib = ctypes.CDLL(_SO_PATH)
            lib.kf_augment_u8  # symbol probe: stale pre-augment builds
            return lib

        lib = None
        if os.path.exists(_SO_PATH):
            try:
                lib = try_load()
            except (OSError, AttributeError):
                lib = None  # stale/corrupt build: rebuild below
        if lib is None:
            if not _build():
                _build_failed = True
                return None
            try:
                lib = try_load()
            except (OSError, AttributeError) as e:
                log.warning("cannot load %s: %s", _SO_PATH, e)
                _build_failed = True
                return None
        lib.dp_create.restype = ctypes.c_void_p
        lib.dp_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_int32]
        lib.dp_next.restype = ctypes.c_int32
        lib.dp_next.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_uint8),
                                ctypes.c_int64]
        lib.dp_reset.restype = None
        lib.dp_reset.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dp_total_records.restype = ctypes.c_int64
        lib.dp_total_records.argtypes = [ctypes.c_void_p]
        lib.dp_num_batches.restype = ctypes.c_int64
        lib.dp_num_batches.argtypes = [ctypes.c_void_p]
        lib.dp_last_error.restype = ctypes.c_char_p
        lib.dp_last_error.argtypes = [ctypes.c_void_p]
        lib.dp_destroy.restype = None
        lib.dp_destroy.argtypes = [ctypes.c_void_p]
        lib.kf_augment.restype = None
        lib.kf_augment.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32]
        lib.kf_augment_u8.restype = None
        lib.kf_augment_u8.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class NativeRecordPipeline:
    """Same contract as PyRecordPipeline, backed by the C++ core."""

    def __init__(self, paths: Sequence[str], record_bytes: int,
                 batch_records: int, *, queue_depth: int = 4, seed: int = 0,
                 drop_remainder: bool = True, num_threads: int = 2):
        lib = _load()
        if lib is None:
            raise RuntimeError("native datapipe unavailable "
                               "(use PyRecordPipeline)")
        self._lib = lib
        self.record_bytes = record_bytes
        self.batch_records = batch_records
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths])
        self._handle = lib.dp_create(
            arr, len(paths), record_bytes, batch_records, queue_depth,
            num_threads, seed & (2 ** 64 - 1), 1 if drop_remainder else 0)
        if not self._handle:
            raise RuntimeError(
                f"dp_create failed for {list(paths)} "
                f"(record_bytes={record_bytes})")
        self.total_records = lib.dp_total_records(self._handle)

    @property
    def num_batches(self) -> int:
        return self._lib.dp_num_batches(self._handle)

    def reset(self, seed: int) -> None:
        self._lib.dp_reset(self._handle, seed & (2 ** 64 - 1))

    def __iter__(self) -> Iterator[np.ndarray]:
        buf = np.empty((self.batch_records * self.record_bytes,), np.uint8)
        ptr = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        while True:
            n = self._lib.dp_next(self._handle, ptr, buf.nbytes)
            if n == 0:
                return
            if n < 0:
                err = self._lib.dp_last_error(self._handle)
                raise RuntimeError(
                    f"datapipe error: {(err or b'').decode()}")
            yield buf[: n * self.record_bytes].reshape(
                n, self.record_bytes).copy()

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.dp_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def native_augment(images: "np.ndarray", base_state: int, pad: int,
                   mean: "np.ndarray", std: "np.ndarray", *,
                   do_flip: bool = True, do_crop: bool = True,
                   num_threads: int = 4) -> "np.ndarray":
    """Fused flip + reflect-pad crop + normalize (native/augment/augment.cc):
    uint8 (N,H,W,3) records → float32 feed buffer in one multithreaded
    pass. Parameter derivation matches data/imagenet.py::augment_params
    bit-identically (the shared splitmix64 spec)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native augment unavailable")
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    if c != 3 or h != w:
        raise ValueError(f"expected (N,H,H,3) uint8, got {images.shape}")
    out = np.empty((n, h, w, 3), np.float32)
    mean32 = np.ascontiguousarray(mean, np.float32)
    std32 = np.ascontiguousarray(std, np.float32)
    lib.kf_augment(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n, h, w, pad, base_state & (2 ** 64 - 1),
        mean32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        1 if do_flip else 0, 1 if do_crop else 0, num_threads)
    return out


def native_augment_u8(images: "np.ndarray", base_state: int, pad: int, *,
                      do_flip: bool = True, do_crop: bool = True,
                      num_threads: int = 4) -> "np.ndarray":
    """Augment WITHOUT normalization, uint8→uint8: the device-normalize
    input mode ships 1/4 the bytes host→device and normalizes inside the
    jitted step (data/imagenet.py device_normalize)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native augment unavailable")
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    if c != 3 or h != w:
        raise ValueError(f"expected (N,H,H,3) uint8, got {images.shape}")
    out = np.empty((n, h, w, 3), np.uint8)
    lib.kf_augment_u8(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, h, w, pad, base_state & (2 ** 64 - 1),
        1 if do_flip else 0, 1 if do_crop else 0, num_threads)
    return out
