"""Auth-checking ingress: the IAP / basic-auth ingress data plane.

The reference's GCP package fronts Kubeflow with an Envoy that verifies
IAP JWTs per request (kubeflow/gcp/prototypes/iap-ingress.jsonnet:1-16,
iap.libsonnet envoy config: checks x-goog-iap-jwt-assertion and forwards
identity headers) or, in the basic-auth flavor, routes every request
through the gatekeeper's ext-authz check
(kubeflow/common/ambassador.libsonnet:149-176 authservice annotation +
kubeflow/gcp basic-auth-ingress prototype).

This is the TPU-native equivalent as one in-repo data-plane component: a
reverse proxy with a pluggable per-request authenticator —

- ``JwtVerifier``  — IAP mode: verifies the ``x-goog-iap-jwt-assertion``
  compact JWS (HS256 against a cluster secret here; Google's ES256 public
  keys slot into the same seam), checks audience/issuer/expiry, and
  forwards ``x-goog-authenticated-user-email`` upstream exactly as IAP's
  Envoy filter does.
- ``ExtAuthzVerifier`` — basic-auth mode: mirrors the Cookie/Authorization
  headers to the gatekeeper's GET /auth (webapps/gatekeeper.py) and lets
  the 200/401 decide; 401 redirects the browser to the login page.

Everything is stdlib; no Envoy image, no egress.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler
from typing import Optional

from ._http import ThreadedServer

IAP_JWT_HEADER = "x-goog-iap-jwt-assertion"
IAP_EMAIL_HEADER = "x-goog-authenticated-user-email"
DEFAULT_ISSUER = "https://cloud.google.com/iap"

# hop-by-hop headers a proxy must not forward (RFC 7230 §6.1)
_HOP_HEADERS = {"connection", "keep-alive", "proxy-authenticate",
                "proxy-authorization", "te", "trailers",
                "transfer-encoding", "upgrade", "host"}


# -- compact JWS (HS256), stdlib only ---------------------------------------

def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def jwt_encode(claims: dict, key: str) -> str:
    """Mint an HS256 JWT (test traffic + in-cluster service identity)."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(key.encode(), signing_input, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


class JwtError(Exception):
    pass


def jwt_verify(token: str, key: str, audience: Optional[str] = None,
               issuer: Optional[str] = None, now=time.time) -> dict:
    """Verify signature + exp/aud/iss; returns the claims.

    The verification contract matches what IAP's Envoy filter enforces
    (signature, audience = the backend-service id, issuer, expiry); the
    signature scheme is the pluggable part.
    """
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        header = json.loads(_b64url_decode(header_b64))
    except (ValueError, json.JSONDecodeError) as e:
        raise JwtError(f"malformed token: {e}") from None
    if not isinstance(header, dict) or header.get("alg") != "HS256":
        raise JwtError("unsupported alg")
    signing_input = f"{header_b64}.{payload_b64}".encode()
    expected = hmac.new(key.encode(), signing_input, hashlib.sha256).digest()
    try:
        if not hmac.compare_digest(expected, _b64url_decode(sig_b64)):
            raise JwtError("bad signature")
        claims = json.loads(_b64url_decode(payload_b64))
    except (ValueError, json.JSONDecodeError) as e:
        raise JwtError(f"malformed token: {e}") from None
    if not isinstance(claims, dict):
        raise JwtError("claims is not an object")
    exp = claims.get("exp")
    if exp is not None and now() >= float(exp):
        raise JwtError("token expired")
    if audience is not None and claims.get("aud") != audience:
        raise JwtError(f"audience mismatch: {claims.get('aud')!r}")
    if issuer is not None and claims.get("iss") != issuer:
        raise JwtError(f"issuer mismatch: {claims.get('iss')!r}")
    return claims


# -- authenticators ----------------------------------------------------------

class AuthDecision:
    def __init__(self, ok: bool, identity: str = "",
                 redirect: Optional[str] = None, reason: str = ""):
        self.ok = ok
        self.identity = identity
        self.redirect = redirect
        self.reason = reason


@dataclass
class JwtVerifier:
    """IAP mode: the request must carry a valid signed assertion."""

    key: str
    audience: Optional[str] = None
    issuer: Optional[str] = DEFAULT_ISSUER

    def check(self, headers) -> AuthDecision:
        token = headers.get(IAP_JWT_HEADER)
        if not token:
            return AuthDecision(False, reason="missing IAP assertion")
        try:
            claims = jwt_verify(token, self.key, audience=self.audience,
                                issuer=self.issuer)
        except JwtError as e:
            return AuthDecision(False, reason=str(e))
        return AuthDecision(True, identity=claims.get("email", ""))


@dataclass
class ExtAuthzVerifier:
    """Basic-auth mode: defer to the gatekeeper's /auth check endpoint,
    mirroring the credentials headers (the ambassador authservice shape)."""

    auth_url: str                      # e.g. http://127.0.0.1:PORT/auth
    login_path: str = "/login"
    forward_headers: tuple = ("Cookie", "Authorization")

    def check(self, headers) -> AuthDecision:
        req = urllib.request.Request(self.auth_url, method="GET")
        for name in self.forward_headers:
            if headers.get(name):
                req.add_header(name, headers[name])
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                identity = resp.headers.get("X-Auth-User", "")
                return AuthDecision(True, identity=identity)
        except urllib.error.HTTPError as e:
            if e.code in (401, 403):
                return AuthDecision(False, redirect=self.login_path,
                                    reason="unauthenticated")
            return AuthDecision(False, reason=f"authz backend error {e.code}")
        except OSError as e:
            # fail closed, like the gatekeeper itself does
            return AuthDecision(False, reason=f"authz unreachable: {e}")


# -- the proxy ---------------------------------------------------------------

@dataclass
class Route:
    prefix: str
    upstream: str                      # host:port


class _PassThrough(urllib.request.HTTPErrorProcessor):
    """Return every upstream response verbatim: a proxy must relay 3xx/4xx
    to the client, not chase redirects or raise (urllib's default would
    follow an upstream 303 and return the wrong resource)."""

    def http_response(self, request, response):
        return response

    https_response = http_response


_PROXY_OPENER = urllib.request.build_opener(_PassThrough)


class AuthIngress(ThreadedServer):
    """Authenticate-then-proxy. Longest-prefix route table, identity
    header injection, hop-header hygiene. ``public_prefixes`` name paths
    that skip the auth check (the login page itself — otherwise the
    302-to-login loops through the authenticator forever)."""

    def __init__(self, authenticator, routes: list[Route],
                 host: str = "127.0.0.1", port: int = 0,
                 public_prefixes: tuple = ()):
        self.authenticator = authenticator
        self.routes = sorted(routes, key=lambda r: -len(r.prefix))
        self.public_prefixes = tuple(public_prefixes)
        ingress = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _drain_body(self) -> Optional[bytes]:
                """Read the request body up-front: on keep-alive
                connections an unread body would be parsed as the next
                request line. Returns None on a bad Content-Length."""
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    return None
                return self.rfile.read(length) if length > 0 else b""

            def _deny(self, decision: AuthDecision):
                if decision.redirect:
                    # carry the original destination so the login page can
                    # send the browser back after auth (kflogin rd param)
                    sep = "&" if "?" in decision.redirect else "?"
                    loc = (decision.redirect + sep + "rd=" +
                           urllib.parse.quote(self.path, safe=""))
                    self.send_response(302)
                    self.send_header("Location", loc)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                else:
                    body = json.dumps({"error": decision.reason}).encode()
                    self.send_response(401)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

            def _proxy(self, method: str):
                payload = self._drain_body()
                if payload is None:
                    body = b'{"error": "bad Content-Length"}'
                    self.send_response(400)
                    self.send_header("Content-Length", str(len(body)))
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.wfile.write(body)
                    self.close_connection = True
                    return
                if ingress.is_public(self.path):
                    decision = AuthDecision(True)
                else:
                    decision = ingress.authenticator.check(self.headers)
                if not decision.ok:
                    self._deny(decision)
                    return
                route = ingress.match(self.path)
                if route is None:
                    body = b'{"error": "no route"}'
                    self.send_response(404)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                payload = payload or None
                url = f"http://{route.upstream}{self.path}"
                req = urllib.request.Request(url, data=payload, method=method)
                # never forward hop headers, the assertion, or any inbound
                # identity header — identity is MINTED here, client-supplied
                # values would let callers spoof it (IAP/Envoy strips these
                # the same way)
                drop = _HOP_HEADERS | {IAP_JWT_HEADER,
                                       IAP_EMAIL_HEADER.lower()}
                for name, value in self.headers.items():
                    if name.lower() not in drop:
                        req.add_header(name, value)
                if decision.identity:
                    # IAP convention: accounts.google.com:<email>
                    req.add_header(IAP_EMAIL_HEADER,
                                   f"accounts.google.com:{decision.identity}")
                try:
                    with _PROXY_OPENER.open(req, timeout=30) as resp:
                        data = resp.read()
                        self.send_response(resp.status)
                        for name, value in resp.headers.items():
                            if name.lower() not in _HOP_HEADERS and \
                                    name.lower() != "content-length":
                                self.send_header(name, value)
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
                except OSError as e:
                    data = json.dumps({"error": f"upstream: {e}"}).encode()
                    self.send_response(502)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)

            def do_GET(self):
                self._proxy("GET")

            def do_POST(self):
                self._proxy("POST")

            def do_PUT(self):
                self._proxy("PUT")

            def do_DELETE(self):
                self._proxy("DELETE")

        super().__init__(Handler, host=host, port=port, name="auth-ingress")

    def match(self, path: str) -> Optional[Route]:
        for route in self.routes:
            if path.startswith(route.prefix):
                return route
        return None

    def is_public(self, path: str) -> bool:
        bare = path.split("?", 1)[0]
        return any(bare == p or bare.startswith(p.rstrip("/") + "/")
                   for p in self.public_prefixes)


def build_ext_authz_ingress(cfg: dict, host: str = "127.0.0.1",
                            port: int = 0) -> AuthIngress:
    """Wire the basic-auth flavor: every request checked against the
    gatekeeper's /auth, EXCEPT the login/logout pages, which proxy to the
    gatekeeper itself unauthenticated so the browser can actually log in
    (the ambassador kflogin-mapping shape). Used by main() and tests."""
    login_path = cfg.get("login_path", "/login")
    auth_url = cfg["auth_url"]
    gate_upstream = urllib.parse.urlsplit(auth_url).netloc
    routes = [Route("/", cfg["upstream"]),
              Route(login_path, gate_upstream),
              Route("/logout", gate_upstream)]
    auth = ExtAuthzVerifier(auth_url=auth_url, login_path=login_path)
    return AuthIngress(auth, routes, host=host, port=port,
                       public_prefixes=(login_path, "/logout"))


# -- pod entrypoint ----------------------------------------------------------

def _read_config_dir(path: str) -> dict:
    """ConfigMaps mount as one file per key; read them all."""
    import os
    out = {}
    for name in os.listdir(path):
        full = os.path.join(path, name)
        if os.path.isfile(full):
            with open(full) as f:
                out[name] = f.read().strip()
    return out


def main(argv=None) -> int:
    """The container entrypoint the iap-ingress / basic-auth-ingress
    Deployments run (manifests/cloud_gcp.py)."""
    import argparse
    import os
    import signal

    p = argparse.ArgumentParser(description="kubeflow-tpu auth ingress")
    p.add_argument("--mode", choices=["iap", "ext-authz"], required=True)
    p.add_argument("--config-dir", required=True,
                   help="mounted ConfigMap dir (one file per key)")
    p.add_argument("--key-file",
                   help="IAP signing-key secret file (iap mode)")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--host", default="0.0.0.0")
    args = p.parse_args(argv)

    cfg = _read_config_dir(args.config_dir)
    if args.mode == "iap":
        key_file = args.key_file or "/etc/iap-key/key"
        with open(key_file) as f:
            key = f.read().strip()
        auth = JwtVerifier(key=key, audience=cfg.get("audience") or None,
                           issuer=cfg.get("issuer", DEFAULT_ISSUER))
        ingress = AuthIngress(auth, [Route("/", cfg["upstream"])],
                              host=args.host, port=args.port)
    else:
        ingress = build_ext_authz_ingress(cfg, host=args.host,
                                          port=args.port)
    ingress.start()
    stop = {"flag": False}
    signal.signal(signal.SIGTERM, lambda *a: stop.update(flag=True))
    try:
        while not stop["flag"]:
            signal.pause() if hasattr(signal, "pause") else time.sleep(1)
    except KeyboardInterrupt:
        pass
    ingress.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
