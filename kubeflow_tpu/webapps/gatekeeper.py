"""Gatekeeper: basic-auth gate with cookie sessions.

The reference's Go auth server (components/gatekeeper/auth/AuthServer.go:
36-153, main.go:42): credentials from env (KUBEFLOW_USERNAME /
KUBEFLOW_PASSWORD, apps/group.go:58-59), an in-memory cookie session table
with 12h expiry, and an ext-authz style check endpoint the ingress calls
per request (ambassador auth service wiring,
kubeflow/common/ambassador.libsonnet).

Routes:
  POST /login        (form or basic auth) → sets session cookie
  GET  /auth         → 200 if cookie/basic valid else 401 (ext-authz check)
  GET  /logout       → clears session
  GET  /healthz
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import secrets
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

USERNAME_ENV = "KUBEFLOW_USERNAME"
PASSWORD_ENV = "KUBEFLOW_PASSWORD"
COOKIE_NAME = "kubeflow-session"
SESSION_TTL_S = 12 * 3600  # 12h, AuthServer.go expiry

# the kflogin page analog (components/kflogin React app → one form with
# the redirect-back + error-banner behavior of the React page)
LOGIN_HTML = """<!doctype html>
<html><head><title>Kubeflow login</title><style>
body{font-family:sans-serif;display:flex;justify-content:center;
margin-top:15vh}form{display:flex;flex-direction:column;gap:0.6rem;
min-width:18rem}input{padding:0.5rem}button{padding:0.6rem}
.err{color:#b00020;margin:0;font-size:0.9rem}</style>
</head><body><form method="post" action="/login">
<h2>Kubeflow TPU</h2>
<!--ERROR--><input type="hidden" name="rd" value="__RD__">
<input name="username" placeholder="username" autofocus>
<input name="password" type="password" placeholder="password">
<button type="submit">Log in</button></form></body></html>"""

ERROR_BANNER = '<p class="err">Invalid username or password.</p>'


def safe_redirect(rd: Optional[str]) -> str:
    """Clamp the post-login destination to a same-origin absolute path —
    anything else (//evil.com, http://..., relative) is an open-redirect
    vector and collapses to /."""
    if (rd and rd.startswith("/") and not rd.startswith("//")
            and "\\" not in rd  # browsers normalize \ to / → //evil.com
            # control chars (CR/LF) would splice raw response headers
            and not any(c < " " or c == "\x7f" for c in rd)):
        return rd
    return "/"


def render_login(rd: str = "/", error: bool = False) -> str:
    import html as _html
    page = LOGIN_HTML.replace("__RD__", _html.escape(safe_redirect(rd),
                                                     quote=True))
    if error:
        page = page.replace("<!--ERROR-->", ERROR_BANNER)
    return page


class SessionStore:
    def __init__(self, ttl_s: float = SESSION_TTL_S, clock=time.time):
        self.ttl_s = ttl_s
        self.clock = clock
        self._sessions: dict[str, float] = {}  # token -> expiry
        self._lock = threading.Lock()

    def create(self) -> str:
        self.sweep()  # opportunistic GC so dead tokens can't accumulate
        token = secrets.token_urlsafe(32)
        with self._lock:
            self._sessions[token] = self.clock() + self.ttl_s
        return token

    def valid(self, token: Optional[str]) -> bool:
        if not token:
            return False
        with self._lock:
            expiry = self._sessions.get(token)
            if expiry is None:
                return False
            if self.clock() > expiry:
                del self._sessions[token]
                return False
            return True

    def revoke(self, token: Optional[str]) -> None:
        with self._lock:
            self._sessions.pop(token or "", None)

    def sweep(self) -> int:
        """Drop expired sessions; returns the number removed."""
        now = self.clock()
        with self._lock:
            dead = [t for t, exp in self._sessions.items() if now > exp]
            for t in dead:
                del self._sessions[t]
            return len(dead)


class Gatekeeper:
    def __init__(self, username: Optional[str] = None,
                 password: Optional[str] = None,
                 ttl_s: float = SESSION_TTL_S, clock=time.time):
        self.username = username if username is not None else \
            os.environ.get(USERNAME_ENV, "admin")
        # store only the digest, compare in constant time; empty/unset
        # password FAILS CLOSED — an auth gate with no credentials
        # configured must reject everything, not admit everything
        pw = password if password is not None else \
            os.environ.get(PASSWORD_ENV, "")
        self._enabled = bool(pw)
        self._pw_digest = hashlib.sha256(pw.encode()).digest()
        self.sessions = SessionStore(ttl_s=ttl_s, clock=clock)

    def check_credentials(self, username: str, password: str) -> bool:
        if not self._enabled:
            return False
        digest = hashlib.sha256(password.encode()).digest()
        return hmac.compare_digest(digest, self._pw_digest) and \
            hmac.compare_digest(username.encode(), self.username.encode())

    def check_basic_header(self, header: Optional[str]) -> bool:
        if not header or not header.startswith("Basic "):
            return False
        try:
            decoded = base64.b64decode(header[6:]).decode()
            username, _, password = decoded.partition(":")
        except Exception:  # noqa: BLE001 - malformed header is just a 401
            return False
        return self.check_credentials(username, password)

    def login(self, username: str, password: str) -> Optional[str]:
        if not self.check_credentials(username, password):
            return None
        return self.sessions.create()

    def authorized(self, cookie_token: Optional[str],
                   basic_header: Optional[str] = None) -> bool:
        return self.authorized_user(cookie_token, basic_header) is not None

    def authorized_user(self, cookie_token: Optional[str],
                        basic_header: Optional[str] = None) -> Optional[str]:
        """The authenticated identity, or None. The gatekeeper is
        single-credential (AuthServer.go's u/p pair), so any valid
        session or basic header resolves to the configured username —
        returned on /auth as X-Auth-User for the ingress to mint the
        upstream identity header from."""
        if self.sessions.valid(cookie_token) or \
                self.check_basic_header(basic_header):
            return self.username
        return None


class GatekeeperServer:
    def __init__(self, gatekeeper: Optional[Gatekeeper] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.gate = gatekeeper or Gatekeeper()
        self._httpd = ThreadingHTTPServer((host, port),
                                          _make_handler(self.gate))
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="gatekeeper")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def _cookie_token(handler: BaseHTTPRequestHandler) -> Optional[str]:
    raw = handler.headers.get("Cookie", "")
    for part in raw.split(";"):
        name, _, value = part.strip().partition("=")
        if name == COOKIE_NAME:
            return value
    return None


def _make_handler(gate: Gatekeeper):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, body: bytes = b"",
                  headers: Optional[dict] = None):
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                return self._send(200, b"ok")
            parsed = urllib.parse.urlsplit(self.path)
            if parsed.path in ("/", "/login"):
                q = urllib.parse.parse_qs(parsed.query)
                page = render_login(rd=(q.get("rd") or ["/"])[0],
                                    error=bool(q.get("error")))
                return self._send(200, page.encode(),
                                  {"Content-Type":
                                   "text/html; charset=utf-8"})
            if self.path.startswith("/auth"):
                user = gate.authorized_user(
                    _cookie_token(self), self.headers.get("Authorization"))
                if user is not None:
                    return self._send(200, headers={"X-Auth-User": user})
                return self._send(401, b"unauthorized",
                                  {"WWW-Authenticate": "Basic"})
            if self.path.startswith("/logout"):
                gate.sessions.revoke(_cookie_token(self))
                return self._send(
                    200, b"logged out",
                    {"Set-Cookie": f"{COOKIE_NAME}=; Max-Age=0"})
            return self._send(404)

        def do_POST(self):
            if self.path != "/login":
                return self._send(404)
            length = int(self.headers.get("Content-Length", 0))
            form = urllib.parse.parse_qs(
                self.rfile.read(length).decode() if length else "")
            username = (form.get("username") or [""])[0]
            password = (form.get("password") or [""])[0]
            rd = (form.get("rd") or [None])[0]
            if not username and \
                    gate.check_basic_header(self.headers.get("Authorization")):
                token = gate.sessions.create()
            else:
                token = gate.login(username, password)
            if token is None:
                if rd is not None:  # browser form flow: back to the page
                    loc = "/login?error=1&rd=" + \
                        urllib.parse.quote(safe_redirect(rd), safe="")
                    return self._send(303, b"", {"Location": loc})
                return self._send(401, b"bad credentials")
            cookie = (f"{COOKIE_NAME}={token}; HttpOnly; Path=/; "
                      f"Max-Age={int(gate.sessions.ttl_s)}")
            if rd is not None:  # browser form flow: back to where they were
                return self._send(303, b"", {"Location": safe_redirect(rd),
                                             "Set-Cookie": cookie})
            return self._send(200, b"ok", {"Set-Cookie": cookie})

    return Handler
