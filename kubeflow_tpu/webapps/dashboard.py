"""Central dashboard API: namespaces, activities, cluster metrics.

The reference's centraldashboard backend (components/centraldashboard/
app/api.ts:26-30 router; app/k8s_service.ts namespace/activity proxying;
app/metrics_service.ts pluggable MetricsService with a Stackdriver impl,
exercised in api_test.ts:30-99). Same surface here over the KubeClient,
plus a TPU-native addition: a slice inventory endpoint summarizing TPU
node pools (topology, chips, schedulable) that the reference's GPU-era
dashboard had no analog for.

Routes:
  GET /api/namespaces
  GET /api/activities/{namespace}          (Events, newest first)
  GET /api/metrics/{type}?window=          (podcpu | podmem | node)
  GET /api/tpu/slices
  GET /healthz
"""

from __future__ import annotations

from typing import Optional

from ..api import k8s
from ..cluster.client import KubeClient
from ._http import ApiError, JsonApp, JsonServer, RawResponse

METRIC_TYPES = ("podcpu", "podmem", "node")

# The SPA shell (the Polymer frontend analog, API-first): one static page
# that renders the dashboard's own API. Other apps embed via links the way
# the reference used iframes.
INDEX_HTML = """<!doctype html>
<html><head><title>Kubeflow TPU</title><style>
body{font-family:sans-serif;margin:2rem;max-width:60rem}
table{border-collapse:collapse;margin:0.5rem 0 1.5rem}
td,th{border:1px solid #ccc;padding:0.3rem 0.8rem;text-align:left}
h2{margin-top:1.5rem}</style></head><body>
<h1>Kubeflow TPU dashboard</h1>
<h2>TPU slices</h2><table id="slices"></table>
<h2>Namespaces</h2><table id="namespaces"></table>
<h2>Nodes</h2><table id="nodes"></table>
<script>
function esc(v) {  // values come from cluster objects: escape before HTML
  return String(v).replace(/[&<>"']/g,
    ch => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[ch]));
}
async function fill(id, rows, cols) {
  const t = document.getElementById(id);
  t.innerHTML = "<tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("")
    + "</tr>" +
    rows.map(r => "<tr>" + cols.map(c => `<td>${esc(r[c] ?? r)}</td>`)
             .join("") + "</tr>").join("");
}
(async () => {
  const slices = await (await fetch("api/tpu/slices")).json();
  fill("slices", slices, ["topology", "accelerator", "hosts", "chips",
                          "ready"]);
  const ns = await (await fetch("api/namespaces")).json();
  fill("namespaces", ns.map(n => ({name: n})), ["name"]);
  const nodes = await (await fetch("api/metrics/node")).json();
  fill("nodes", nodes, ["node", "value"]);
})();
</script></body></html>"""


class MetricsService:
    """Pluggable cluster-metrics backend (metrics_service.ts interface)."""

    def query(self, metric_type: str, window_s: int) -> list[dict]:
        raise NotImplementedError


class NullMetricsService(MetricsService):
    """No metrics backend configured (the dashboard renders an empty
    chart); a Prometheus-backed impl plugs in the same way Stackdriver did."""

    def query(self, metric_type: str, window_s: int) -> list[dict]:
        return []


class ClusterMetricsService(MetricsService):
    """Derives coarse utilization from the cluster state itself: pod counts
    per node as a proxy when no timeseries backend exists."""

    def __init__(self, client: KubeClient):
        self.client = client

    def query(self, metric_type: str, window_s: int) -> list[dict]:
        pods = self.client.list("v1", "Pod")
        if metric_type in ("podcpu", "podmem"):
            bucket = "cpu" if metric_type == "podcpu" else "memory"
            out = []
            for p in pods:
                total = 0.0
                for c in p.get("spec", {}).get("containers", []) or []:
                    req = (c.get("resources", {}) or {}).get("requests") or {}
                    try:
                        total += k8s.parse_quantity(req.get(bucket, 0))
                    except (TypeError, ValueError):
                        continue
                out.append({"pod": k8s.name_of(p),
                            "namespace": k8s.namespace_of(p, "default"),
                            "value": total})
            return out
        nodes = self.client.list("v1", "Node")
        by_node: dict[str, int] = {}
        for p in pods:
            node = p.get("spec", {}).get("nodeName")
            if node:
                by_node[node] = by_node.get(node, 0) + 1
        return [{"node": k8s.name_of(n),
                 "value": by_node.get(k8s.name_of(n), 0)} for n in nodes]


def build_dashboard_app(client: KubeClient,
                        metrics: Optional[MetricsService] = None) -> JsonApp:
    metrics = metrics or ClusterMetricsService(client)
    app = JsonApp()

    @app.route("GET", "/healthz")
    def healthz(params, query, body):
        return 200, {"ok": True}

    @app.route("GET", "/")
    def index(params, query, body):
        return 200, RawResponse(INDEX_HTML,
                                content_type="text/html; charset=utf-8")

    @app.route("GET", "/api/namespaces")
    def namespaces(params, query, body):
        return 200, [k8s.name_of(n)
                     for n in client.list("v1", "Namespace")]

    @app.route("GET", "/api/activities/{namespace}")
    def activities(params, query, body):
        events = client.list("v1", "Event", params["namespace"])
        events.sort(key=lambda e: e.get("lastTimestamp", ""), reverse=True)
        return 200, [{
            "reason": e.get("reason", ""),
            "message": e.get("message", ""),
            "type": e.get("type", "Normal"),
            "involvedObject": (e.get("involvedObject") or {}).get("name", ""),
            "lastTimestamp": e.get("lastTimestamp", ""),
        } for e in events]

    @app.route("GET", "/api/metrics/{mtype}")
    def metrics_route(params, query, body):
        mtype = params["mtype"]
        if mtype not in METRIC_TYPES:
            raise ApiError(400, f"metric type {mtype!r} not in "
                                f"{METRIC_TYPES}")
        try:
            window = int(query.get("window", 900))
        except ValueError:
            raise ApiError(400, f"window must be an integer, got "
                                f"{query.get('window')!r}")
        return 200, metrics.query(mtype, window)

    @app.route("GET", "/api/tpu/slices")
    def tpu_slices(params, query, body):
        pools: dict[str, dict] = {}
        for node in client.list("v1", "Node"):
            labels = k8s.labels_of(node)
            topo = labels.get("cloud.google.com/gke-tpu-topology")
            if not topo:
                continue
            alloc = node.get("status", {}).get("allocatable", {}) or {}
            pool = pools.setdefault(topo, {
                "topology": topo,
                "accelerator": labels.get(
                    "cloud.google.com/gke-tpu-accelerator", ""),
                "hosts": 0, "chips": 0, "ready": 0})
            pool["hosts"] += 1
            pool["chips"] += int(float(alloc.get("google.com/tpu", 0)))
            if k8s.condition_true(node, "Ready"):
                pool["ready"] += 1
        return 200, sorted(pools.values(), key=lambda p: p["topology"])

    return app


class DashboardServer(JsonServer):
    def __init__(self, client: KubeClient,
                 metrics: Optional[MetricsService] = None, **kw):
        super().__init__(build_dashboard_app(client, metrics),
                         name="centraldashboard", **kw)
