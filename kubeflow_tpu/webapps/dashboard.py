"""Central dashboard API: namespaces, activities, cluster metrics.

The reference's centraldashboard backend (components/centraldashboard/
app/api.ts:26-30 router; app/k8s_service.ts namespace/activity proxying;
app/metrics_service.ts pluggable MetricsService with a Stackdriver impl,
exercised in api_test.ts:30-99). Same surface here over the KubeClient,
plus a TPU-native addition: a slice inventory endpoint summarizing TPU
node pools (topology, chips, schedulable) that the reference's GPU-era
dashboard had no analog for.

Routes:
  GET /api/namespaces
  GET /api/activities/{namespace}          (Events, newest first)
  GET /api/metrics/{type}?window=          (podcpu | podmem | node)
  GET /api/tpu/slices
  GET /api/sched/queues                    (gang-scheduler queue state)
  GET /api/sched/nodes                     (per-host health + quarantine)
  GET /api/obs/goodput/{ns}/{name}         (per-job goodput ledger)
  GET /api/obs/goodput                     (cluster chip-hour rollup)
  GET /api/obs/anomalies/{ns}/{name}       (per-job numeric-integrity
                                            panel: rollback budget, LKG
                                            directive, anomaly +
                                            bisection-verdict spans)
  GET /api/obs/serving                     (per-model serving rollup:
                                            latency percentiles, goodput
                                            vs serving badput, SLO)
  GET /api/obs/fleet                       (fleet-router rollup: retries,
                                            hedges, per-replica wins,
                                            fleet badput)
  GET /api/obs/comm/{ns}/{name}            (per-job comm profile: DCN vs
                                            ICI bytes/step, per-link
                                            collective mix, full-reshard
                                            verdict)
  GET /api/obs/controlplane                (HA leases: current leaders,
                                            lease age, transitions; plus
                                            telemetry: per-component pass
                                            stats, apiserver audit rollup,
                                            metric-series cardinality)
  GET /healthz
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..api import k8s
from ..cluster.client import KubeClient
from ._http import ApiError, JsonApp, JsonServer, RawResponse

METRIC_TYPES = ("podcpu", "podmem", "node")

# The SPA shell: sidebar + namespace selector + one view container; all
# rendering happens in the static app bundle (static/dashboard.js — the
# Polymer main-page.js analog, no build infra). Chart colors are CSS
# custom properties per color-scheme: single sequential hue for the bar
# charts, fixed status palette for run phases (icon + label pairing),
# text in ink tokens — never the series color.
INDEX_HTML = """<!doctype html>
<html><head><title>Kubeflow TPU</title><meta charset="utf-8"><style>
:root{color-scheme:light dark;
 --surface-1:#fcfcfb;--surface-2:#f1f0ec;
 --text-primary:#0b0b0b;--text-secondary:#52514e;--text-muted:#7c7b75;
 --series-1:#2a78d6;--series-1-hover:#1c5cab;
 --grid:#e3e2dd;
 --status-good:#0ca30c;--status-warning:#fab219;
 --status-critical:#d03b3b}
@media (prefers-color-scheme: dark){:root{
 --surface-1:#1a1a19;--surface-2:#262625;
 --text-primary:#ffffff;--text-secondary:#c3c2b7;--text-muted:#8f8e86;
 --series-1:#3987e5;--series-1-hover:#6da7ec;
 --grid:#3a3936}}
body{font-family:sans-serif;margin:0;display:flex;min-height:100vh;
 background:var(--surface-1);color:var(--text-primary)}
#sidebar{background:#1a73e8;color:#fff;min-width:13rem;padding:1rem}
#sidebar h1{font-size:1.1rem;margin:0 0 1rem}
#sidebar a{display:block;color:#fff;text-decoration:none;padding:0.45rem
 0.6rem;border-radius:4px;margin:0.15rem 0}
#sidebar a.active,#sidebar a:hover{background:rgba(255,255,255,0.22)}
#env-info{margin-top:1.2rem;font-size:0.78rem;opacity:0.85;
 overflow-wrap:anywhere}
.cards{display:flex;gap:0.8rem;flex-wrap:wrap;margin:0.5rem 0 1rem}
.card{background:var(--surface-2);border-radius:8px;text-decoration:none;
 color:var(--text-primary);padding:0.8rem 1.1rem;min-width:11rem;
 border:1px solid var(--grid)}
.card:hover{border-color:var(--series-1)}
.card-title{font-weight:600;color:var(--series-1)}
.card-desc{color:var(--text-secondary);font-size:0.85rem;margin-top:0.2rem}
form.inline{display:flex;gap:0.5rem;align-items:center;margin:0.6rem 0}
form.inline input,form.inline select{padding:0.35rem}
#ns-selector{width:100%;padding:0.35rem;margin-bottom:1rem}
main{flex:1;padding:1.5rem;max-width:70rem}
table{border-collapse:collapse;margin:0.5rem 0 1.5rem}
td,th{border:1px solid var(--grid);padding:0.3rem 0.8rem;text-align:left}
th{color:var(--text-secondary);font-weight:600}
nav.tabs a{margin-right:0.8rem;color:var(--series-1)}
nav.tabs a.active{font-weight:700;text-decoration:none}
.empty{color:var(--text-muted)}.error{color:var(--status-critical)}
.tiles{display:flex;gap:0.8rem;flex-wrap:wrap;margin:0.5rem 0 1rem}
.tile{background:var(--surface-2);border-radius:8px;
 padding:0.7rem 1.1rem;min-width:7rem}
.tile-label{color:var(--text-secondary);font-size:0.8rem}
.tile-value{font-weight:600;font-size:1.6rem}
.badge{white-space:nowrap}
.badge-icon{font-size:0.85em}
.badge-good{color:var(--status-good)}
.badge-running{color:var(--series-1)}
.badge-warning{color:var(--text-secondary)}
.badge-critical{color:var(--status-critical)}
.badge-neutral{color:var(--text-muted)}
button.minor{padding:0.3rem 0.8rem;border:1px solid var(--grid);
 border-radius:4px;background:var(--surface-2);
 color:var(--text-primary);cursor:pointer;margin-bottom:0.4rem}
.viz-root svg{display:block;margin:0.4rem 0 1rem}
.viz-bar{fill:var(--series-1)}
.viz-bar.hover{fill:var(--series-1-hover)}
.viz-grid{stroke:var(--grid);stroke-width:1}
.viz-label{fill:var(--text-secondary);font-size:11px}
.viz-value{fill:var(--text-primary);font-size:11px;
 font-variant-numeric:tabular-nums}
.viz-tick{fill:var(--text-muted);font-size:10px;
 font-variant-numeric:tabular-nums}
.viz-tooltip{position:absolute;display:none;pointer-events:none;
 background:var(--surface-2);color:var(--text-primary);
 border:1px solid var(--grid);border-radius:4px;
 padding:0.25rem 0.55rem;font-size:0.85rem;z-index:10}
.viz-tooltip-value{font-weight:700}
.viz-tooltip-label{color:var(--text-secondary)}
</style></head><body>
<div id="sidebar">
  <h1>Kubeflow TPU</h1>
  <select id="ns-selector" aria-label="namespace"></select>
  <a href="#/overview" data-view="overview">Overview</a>
  <a href="#/runs" data-view="runs">Runs</a>
  <a href="#/serving" data-view="serving">Serving</a>
  <a href="#/activities" data-view="activities">Activities</a>
  <a href="#/metrics" data-view="metrics">Metrics</a>
  <a href="#/notebooks" data-view="notebooks">Notebooks</a>
  <a href="#/pipelines" data-view="pipelines">Pipelines</a>
  <a href="#/studies" data-view="studies">Studies</a>
  <a href="#/experiments" data-view="experiments">Experiments</a>
  <a href="#/contributors" data-view="contributors">Contributors</a>
  <a href="/logout">Log out</a>
  <div id="env-info"></div>
</div>
<main><div id="view"></div></main>
<script src="app.js"></script>
</body></html>"""

_STATIC_DIR = os.path.join(os.path.dirname(__file__), "static")


def _read_app_js() -> str:
    with open(os.path.join(_STATIC_DIR, "dashboard.js")) as f:
        return f.read()


class MetricsService:
    """Pluggable cluster-metrics backend (metrics_service.ts interface)."""

    def query(self, metric_type: str, window_s: int) -> list[dict]:
        raise NotImplementedError


class NullMetricsService(MetricsService):
    """No metrics backend configured (the dashboard renders an empty
    chart); a Prometheus-backed impl plugs in the same way Stackdriver did."""

    def query(self, metric_type: str, window_s: int) -> list[dict]:
        return []


class ClusterMetricsService(MetricsService):
    """Derives coarse utilization from the cluster state itself: pod counts
    per node as a proxy when no timeseries backend exists."""

    def __init__(self, client: KubeClient):
        self.client = client

    def query(self, metric_type: str, window_s: int) -> list[dict]:
        pods = self.client.list("v1", "Pod")
        if metric_type in ("podcpu", "podmem"):
            bucket = "cpu" if metric_type == "podcpu" else "memory"
            out = []
            for p in pods:
                total = 0.0
                for c in p.get("spec", {}).get("containers", []) or []:
                    req = (c.get("resources", {}) or {}).get("requests") or {}
                    try:
                        total += k8s.parse_quantity(req.get(bucket, 0))
                    except (TypeError, ValueError):
                        continue
                out.append({"pod": k8s.name_of(p),
                            "namespace": k8s.namespace_of(p, "default"),
                            "value": total})
            return out
        nodes = self.client.list("v1", "Node")
        by_node: dict[str, int] = {}
        for p in pods:
            node = p.get("spec", {}).get("nodeName")
            if node:
                by_node[node] = by_node.get(node, 0) + 1
        return [{"node": k8s.name_of(n),
                 "value": by_node.get(k8s.name_of(n), 0)} for n in nodes]


def _job_phase(obj: dict) -> str:
    """Shared condition walk for CR-shaped jobs (training jobs, studies):
    the newest-wins order the runs panel and studies view BOTH use, so
    one study can never show two phases on one dashboard."""
    from ..api.trainingjob import (COND_CREATED, COND_FAILED, COND_QUEUED,
                                   COND_RUNNING, COND_SUCCEEDED)
    # Queued outranks Created/Running remnants: a preempted gang keeps
    # its Created condition but is WAITING — that is what the panel must
    # say (Running is explicitly set False on teardown)
    for cond in (COND_SUCCEEDED, COND_FAILED, COND_RUNNING, COND_QUEUED,
                 COND_CREATED):
        if k8s.condition_true(obj, cond):
            return cond
    return "Pending"


def build_dashboard_app(client: KubeClient,
                        metrics: Optional[MetricsService] = None) -> JsonApp:
    metrics = metrics or ClusterMetricsService(client)
    app = JsonApp()

    @app.route("GET", "/healthz")
    def healthz(params, query, body):
        return 200, {"ok": True}

    @app.route("GET", "/")
    def index(params, query, body):
        return 200, RawResponse(INDEX_HTML,
                                content_type="text/html; charset=utf-8")

    @app.route("GET", "/app.js")
    def app_js(params, query, body):
        return 200, RawResponse(
            _read_app_js(),
            content_type="application/javascript; charset=utf-8")

    @app.route("GET", "/api/env-info")
    def env_info(params, query, body):
        """Platform + user info (api.ts /env-info; k8s_service.ts
        getPlatformInfo): provider from Node providerID, kubeflow
        version from the Application CR when installed (the reference
        reads spec.descriptor.version the same way), user email from
        the identity header the auth ingress injects."""
        from .ingress import IAP_EMAIL_HEADER
        from ..cluster.client import KubeError
        provider = "other://"
        try:
            nodes = client.list("v1", "Node")
        except KubeError:
            # Nodes are cluster-scoped: a namespaced service account
            # (restricted RBAC) gets 403 here — degrade to the generic
            # provider instead of 500ing the whole env-info panel
            nodes = []
        for node in nodes:
            pid = node.get("spec", {}).get("providerID")
            if pid:
                provider = pid
                break
        version = ""
        try:
            from ..controllers.application import (APPLICATION_API_VERSION,
                                                   APPLICATION_KIND)
            for app_cr in client.list(APPLICATION_API_VERSION,
                                      APPLICATION_KIND):
                version = (app_cr.get("spec", {})
                           .get("descriptor", {}).get("version", ""))
                if version:
                    break
        except Exception:  # noqa: BLE001 — CRD absent is normal
            pass
        from .. import __version__
        email = app.request_headers.get(IAP_EMAIL_HEADER, "")
        # IAP prefixes the subject ("accounts.google.com:user@x")
        email = email.split(":", 1)[-1] if email else "anonymous@kubeflow.org"
        return 200, {
            "user": {"email": email},
            "platform": {"provider": provider,
                         "providerName": provider.split(":")[0],
                         "kubeflowVersion": version or __version__},
        }

    @app.route("GET", "/api/namespaces")
    def namespaces(params, query, body):
        return 200, [k8s.name_of(n)
                     for n in client.list("v1", "Namespace")]

    @app.route("GET", "/api/activities/{namespace}")
    def activities(params, query, body):
        events = client.list("v1", "Event", params["namespace"])
        events.sort(key=lambda e: e.get("lastTimestamp", ""), reverse=True)
        return 200, [{
            "reason": e.get("reason", ""),
            "message": e.get("message", ""),
            "type": e.get("type", "Normal"),
            "involvedObject": (e.get("involvedObject") or {}).get("name", ""),
            "lastTimestamp": e.get("lastTimestamp", ""),
        } for e in events]

    @app.route("GET", "/api/runs/{namespace}")
    def runs(params, query, body):
        """Training jobs + pipeline workflows in one panel — phase,
        progress, timestamps (the run-history view the reference left to
        the external pipeline-ui image)."""
        from ..api.trainingjob import API_VERSIONS, JOB_KINDS
        from ..cluster.client import KubeError
        from ..workflows.engine import (WORKFLOW_API_VERSION, WORKFLOW_KIND)
        ns = params["namespace"]
        phase_of = _job_phase

        def list_kind(api_version, kind):
            # a kind whose CRD is not installed must not 500 the whole
            # panel — the runs that DO exist still render
            try:
                return client.list(api_version, kind, ns)
            except KubeError:
                return []

        out = []
        for wf in list_kind(WORKFLOW_API_VERSION, WORKFLOW_KIND):
            st = wf.get("status", {})
            nodes = st.get("nodes") or {}
            done = sum(1 for n in nodes.values()
                       if n.get("phase") == "Succeeded")
            out.append({
                "kind": "Workflow", "name": k8s.name_of(wf),
                "phase": st.get("phase", "Pending"),
                "progress": f"{done}/{len(nodes)} steps" if nodes else "",
                "finishedAt": st.get("finishedAt", ""),
            })
        for kind in JOB_KINDS:
            for job in list_kind(API_VERSIONS[kind], kind):
                phase = phase_of(job)
                rstat = (job.get("status") or {}).get("replicaStatuses", {})
                active = sum(int(v.get("active", 0))
                             for v in rstat.values() if isinstance(v, dict))
                # active kernel tier (spec.kernels, ISSUE 16): compact
                # "attn:flash opt:fused_adam srv:int8" — blank when the
                # job runs stock everywhere
                kern = (job.get("spec") or {}).get("kernels") or {}
                kernels = " ".join(
                    f"{short}:{kern[key]}"
                    for short, key in (("attn", "attention"),
                                       ("opt", "optimizer"),
                                       ("srv", "serving"))
                    if kern.get(key))
                out.append({
                    "kind": kind, "name": k8s.name_of(job), "phase": phase,
                    "progress": f"{active} active" if active else "",
                    "kernels": kernels,
                    "finishedAt": "",
                })
        from ..katib.studyjob import STUDYJOB_API_VERSION, STUDYJOB_KIND
        for study in list_kind(STUDYJOB_API_VERSION, STUDYJOB_KIND):
            st = study.get("status") or {}
            phase = phase_of(study)
            best = st.get("bestTrial") or {}
            progress = ""
            if st.get("trialsTotal"):
                progress = (f"{st.get('trialsSucceeded', 0)}/"
                            f"{st['trialsTotal']} trials")
                if best.get("objective") is not None:
                    progress += f", best {round(best['objective'], 4)}"
            out.append({
                "kind": STUDYJOB_KIND, "name": k8s.name_of(study),
                "phase": phase, "progress": progress, "finishedAt": "",
            })
        out.sort(key=lambda r: (r["kind"], r["name"]))
        return 200, out

    @app.route("GET", "/api/studies/{namespace}")
    def studies(params, query, body):
        """Katib study detail for the dashboard's studies view: per-study
        phase, objective config, best trial, and the full per-trial
        objective series (the kubebench-dashboard/katib-UI role served
        from the StudyJob status the controller maintains)."""
        from ..cluster.client import KubeError
        from ..katib.studyjob import STUDYJOB_API_VERSION, STUDYJOB_KIND
        try:
            studyjobs = client.list(STUDYJOB_API_VERSION, STUDYJOB_KIND,
                                    params["namespace"])
        except KubeError:
            return 200, []
        out = []
        for sj in studyjobs:
            spec, st = sj.get("spec", {}), sj.get("status") or {}
            out.append({
                "name": k8s.name_of(sj),
                "phase": _job_phase(sj),
                "objectiveName": spec.get("objectivevaluename", "loss"),
                "optimization": spec.get("optimizationtype", "minimize"),
                "trialsTotal": st.get("trialsTotal", 0),
                "trialsSucceeded": st.get("trialsSucceeded", 0),
                "trialsFailed": st.get("trialsFailed", 0),
                "bestTrial": st.get("bestTrial"),
                "trials": [{
                    "name": t.get("name", ""),
                    "status": t.get("status", ""),
                    "objective": t.get("objective"),
                    "parameters": t.get("parameters", {}),
                } for t in (st.get("trials") or [])],
            })
        out.sort(key=lambda s: s["name"])
        return 200, out

    def _experiment_summary(exp):
        spec, st = exp.get("spec", {}), exp.get("status") or {}
        obj = spec.get("objective") or {}
        alg = spec.get("algorithm") or {}
        if isinstance(alg, str):  # admission shorthand: algorithm: random
            alg = {"name": alg}
        return {
            "namespace": k8s.namespace_of(exp, "default"),
            "name": k8s.name_of(exp),
            "phase": _job_phase(exp),
            "algorithm": alg.get("name", ""),
            "objectiveMetric": obj.get("metric", ""),
            "optimization": obj.get("type", ""),
            "trialsTotal": st.get("trialsTotal", 0),
            "trialsRunning": st.get("trialsRunning", 0),
            "trialsSucceeded": st.get("trialsSucceeded", 0),
            "trialsFailed": st.get("trialsFailed", 0),
            "trialsStopped": st.get("trialsStopped", 0),
            "bestTrial": st.get("bestTrial"),
            "trialsPerHour": st.get("trialsPerHour"),
            "chipHours": st.get("chipHours"),
            "warmStartFraction": st.get("warmStartFraction"),
        }

    @app.route("GET", "/api/katib/experiments")
    def experiments(params, query, body):
        """Fleet-wide Experiment rollup: one row per search with the
        throughput/goodput economics the reconciler maintains
        (trials/hour, chip-hours by category, warm-start fraction)."""
        from ..api.experiment import (EXPERIMENT_API_VERSION,
                                      EXPERIMENT_KIND)
        from ..cluster.client import KubeError
        try:
            exps = client.list(EXPERIMENT_API_VERSION, EXPERIMENT_KIND)
        except KubeError:
            return 200, []
        out = [_experiment_summary(e) for e in exps]
        out.sort(key=lambda e: (e["namespace"], e["name"]))
        return 200, out

    @app.route("GET", "/api/katib/experiments/{namespace}/{name}")
    def experiment_detail(params, query, body):
        """One Experiment with its full trial table: phase, objective,
        chips, warm/cold start kind, stopped-early flag."""
        from ..api.experiment import (EXPERIMENT_API_VERSION,
                                      EXPERIMENT_KIND)
        from ..cluster.client import KubeError, NotFoundError
        try:
            exp = client.get(EXPERIMENT_API_VERSION, EXPERIMENT_KIND,
                             params["namespace"], params["name"])
        except (KubeError, NotFoundError):
            raise ApiError(404, f"experiment {params['namespace']}/"
                                f"{params['name']} not found")
        st = exp.get("status") or {}
        detail = _experiment_summary(exp)
        detail["parameters"] = (exp.get("spec") or {}).get("parameters", [])
        detail["trials"] = [{
            "name": t.get("name", ""),
            "status": t.get("status", ""),
            "objective": t.get("objective"),
            "parameters": t.get("parameters", {}),
            "chips": t.get("chips", 0),
            "startKind": t.get("startKind", "unknown"),
            "stoppedEarly": bool(t.get("stoppedEarly")),
            "generation": t.get("generation", 0),
            "parent": t.get("parent"),
        } for t in (st.get("trials") or [])]
        return 200, detail

    @app.route("GET", "/api/metrics/{mtype}")
    def metrics_route(params, query, body):
        mtype = params["mtype"]
        if mtype not in METRIC_TYPES:
            raise ApiError(400, f"metric type {mtype!r} not in "
                                f"{METRIC_TYPES}")
        try:
            window = int(query.get("window", 900))
        except ValueError:
            raise ApiError(400, f"window must be an integer, got "
                                f"{query.get('window')!r}")
        return 200, metrics.query(mtype, window)

    @app.route("GET", "/metrics")
    def metrics_exposition(params, query, body):
        """This process's shared-registry exposition (obs/registry.py) —
        the dashboard is a scrape target like every other component."""
        from ..obs.registry import default_registry
        return 200, RawResponse(default_registry().render())

    def _find_training_job(ns: str, name: str) -> dict:
        """The job-scoped obs endpoints' shared lookup: the named
        training job under ANY of the job kinds, or a 404."""
        from ..api.trainingjob import API_VERSIONS, JOB_KINDS
        for kind in JOB_KINDS:
            manifest = client.get_or_none(API_VERSIONS[kind], kind, ns,
                                          name)
            if manifest is not None:
                return manifest
        raise ApiError(404, f"no training job {ns}/{name}")

    @app.route("GET", "/api/obs/jobs/{namespace}/{name}")
    def job_timeline(params, query, body):
        """One job's end-to-end trace timeline, reconstructed from the
        JSONL span sink alone (obs/trace.py): queued → bound →
        pod-start → running → per-window spans → done, each with
        component + duration — the queue-wait/startup/throughput
        attribution the obs layer exists for. The sink location comes
        from this process's KFTPU_SPAN_PATH (the same contract the
        operator renders into workers)."""
        from ..obs.trace import (SPAN_PATH_ENV, TRACE_ID_ANNOTATION,
                                 reconstruct)
        ns, name = params["namespace"], params["name"]
        manifest = _find_training_job(ns, name)
        trace_id = k8s.annotations_of(manifest).get(TRACE_ID_ANNOTATION)
        out = {"namespace": ns, "name": name, "phase": _job_phase(manifest),
               "traceId": trace_id, "events": [], "wallSeconds": 0.0}
        span_path = os.environ.get(SPAN_PATH_ENV)
        if not trace_id:
            out["note"] = "no trace id minted yet (control plane has " \
                          "not touched this job)"
            return 200, out
        if not span_path:
            out["note"] = f"no span sink configured ({SPAN_PATH_ENV} unset)"
            return 200, out
        out.update(reconstruct(span_path, trace_id))
        return 200, out

    @app.route("GET", "/api/obs/goodput/{namespace}/{name}")
    def job_goodput(params, query, body):
        """One job's goodput ledger (obs/goodput.py): wall-clock
        decomposed into goodput vs the named badput categories,
        reconstructed live from the span sink. A finished job whose
        spans have rotated away still answers from the final-ledger
        annotation the operator stamped at completion."""
        from ..obs.goodput import GOODPUT_ANNOTATION, ledger_for
        from ..obs.trace import SPAN_PATH_ENV, TRACE_ID_ANNOTATION
        ns, name = params["namespace"], params["name"]
        manifest = _find_training_job(ns, name)
        anns = k8s.annotations_of(manifest)
        trace_id = anns.get(TRACE_ID_ANNOTATION)
        out = {"namespace": ns, "name": name,
               "phase": _job_phase(manifest), "traceId": trace_id}
        span_path = os.environ.get(SPAN_PATH_ENV)
        ledger = ledger_for(span_path, trace_id) \
            if (span_path and trace_id) else None
        if ledger is not None and ledger["wallSeconds"]:
            out["ledger"] = ledger
            out["source"] = "spans"
            return 200, out
        final = anns.get(GOODPUT_ANNOTATION)
        if final:
            try:
                out["ledger"] = json.loads(final)
                out["source"] = "annotation"
                return 200, out
            except ValueError:
                pass
        out["note"] = ("no spans for this job"
                       if span_path and trace_id else
                       "no trace id minted yet" if span_path else
                       f"no span sink configured ({SPAN_PATH_ENV} unset)")
        return 200, out

    @app.route("GET", "/api/obs/anomalies/{namespace}/{name}")
    def job_anomalies(params, query, body):
        """One job's numeric-integrity panel (docs/operations.md
        "Numeric integrity"): the rollback budget and how much of it is
        spent, the active rollback directive (LKG pin + armed replay
        range) if any, and the anomaly / bisection-verdict spans from
        the sink — the evidence trail from detection through LKG
        rollback to the per-host verdict."""
        from ..api.trainingjob import (ANOMALY_COUNT_ANNOTATION,
                                       ANOMALY_ROLLBACK_ANNOTATION,
                                       TrainingJob)
        from ..obs.goodput import SPAN_ANOMALY
        from ..obs.trace import (SPAN_PATH_ENV, TRACE_ID_ANNOTATION,
                                 load_spans)
        ns, name = params["namespace"], params["name"]
        manifest = _find_training_job(ns, name)
        anns = k8s.annotations_of(manifest)
        try:
            budget = TrainingJob.from_manifest(
                manifest).run_policy.max_anomaly_rollbacks
        except (ValueError, KeyError, TypeError):
            budget = None
        out = {"namespace": ns, "name": name,
               "phase": _job_phase(manifest),
               "rollbacks": int(anns.get(ANOMALY_COUNT_ANNOTATION, "0")),
               "maxAnomalyRollbacks": budget,
               "rollback": None, "anomalies": [], "bisection": []}
        raw = anns.get(ANOMALY_ROLLBACK_ANNOTATION)
        if raw:
            try:
                out["rollback"] = json.loads(raw)
            except ValueError:
                pass
        span_path = os.environ.get(SPAN_PATH_ENV)
        trace_id = anns.get(TRACE_ID_ANNOTATION)
        if span_path and trace_id:
            for s in load_spans(span_path, trace_id=trace_id):
                if s.get("name") == SPAN_ANOMALY:
                    out["anomalies"].append(s.get("attrs", {}))
                elif s.get("name") == "anomaly-bisection":
                    out["bisection"].append(s.get("attrs", {}))
        elif not span_path:
            out["note"] = f"no span sink configured ({SPAN_PATH_ENV} unset)"
        return 200, out

    @app.route("GET", "/api/obs/goodput")
    def cluster_goodput(params, query, body):
        """The cluster-level chip-hour rollup: every trace in the span
        sink, each job's decomposition weighted by its bound gang
        width (obs/goodput.py cluster_rollup)."""
        from ..obs.goodput import cluster_rollup
        from ..obs.trace import SPAN_PATH_ENV
        span_path = os.environ.get(SPAN_PATH_ENV)
        if not span_path:
            return 200, {"note": f"no span sink configured "
                                 f"({SPAN_PATH_ENV} unset)"}
        return 200, cluster_rollup(span_path)

    @app.route("GET", "/api/obs/serving")
    def serving_obs(params, query, body):
        """The serving-plane rollup (obs/goodput.py serving_rollup):
        every ``serving-request`` summary span in the sink folded into
        per-(model, role) rows — request/error/shed counts,
        p50/p99/p99.9, batch fill, goodput ratio vs the serving badput
        categories, SLO over-target fraction, and the slowest request
        ids (each reconstructible stage-by-stage via
        /api/obs/jobs-style span reads). Shadow traffic reports under
        its own role row, never folded into the primary's."""
        from ..obs.goodput import serving_rollup
        from ..obs.trace import SPAN_PATH_ENV
        span_path = os.environ.get(SPAN_PATH_ENV)
        if not span_path:
            return 200, {"note": f"no span sink configured "
                                 f"({SPAN_PATH_ENV} unset)",
                         "models": [], "requests": 0}
        return 200, serving_rollup(span_path)

    @app.route("GET", "/api/obs/fleet")
    def fleet_obs(params, query, body):
        """The fleet-router rollup (obs/goodput.py fleet_rollup):
        every ``fleet-request`` summary span folded into one table —
        routed-request outcomes, attempt/retry/hedge totals,
        p50/p99/p99.9 client latency, the fleet badput sums (retry /
        hedge_waste / other), and per-replica win counts (ISSUE 12)."""
        from ..obs.goodput import fleet_rollup
        from ..obs.trace import SPAN_PATH_ENV
        span_path = os.environ.get(SPAN_PATH_ENV)
        if not span_path:
            return 200, {"note": f"no span sink configured "
                                 f"({SPAN_PATH_ENV} unset)",
                         "requests": 0}
        return 200, fleet_rollup(span_path)

    @app.route("GET", "/api/obs/controlplane")
    def controlplane_obs(params, query, body):
        """Control-plane HA state + telemetry. HA (cluster/lease.py):
        every Lease in the cluster — current holder, lease age (now −
        renewTime), duration, expired flag, and the transitions count
        (the fencing token; each increment is one failover). Telemetry
        (obs/controlplane.py): per-component pass statistics (no-op
        fraction, p50/p99 pass latency, write amplification, relists),
        the server-side audit rollup when the apiserver ledger is
        in-process (FakeCluster / the sim — absent over a remote
        apiserver), and the metric-series cardinality self-audit. The
        panel operators read when "is anything leading the scheduler
        right now" or "what is hammering the apiserver" is the
        question (docs/operations.md "Control-plane telemetry")."""
        import time as _time

        from ..cluster.client import KubeError
        from ..cluster.lease import (LEASE_API_VERSION, LEASE_KIND,
                                     lease_record)
        from ..obs import controlplane as ctrlobs
        from ..obs.registry import export_series_totals
        now = _time.time()
        leases = []
        try:
            objs = client.list(LEASE_API_VERSION, LEASE_KIND)
        except KubeError:
            objs = []
        for obj in objs:
            rec = lease_record(obj)
            leases.append({
                "namespace": k8s.namespace_of(obj, "default"),
                "name": k8s.name_of(obj),
                "holder": rec.holder,
                "ageSeconds": round(max(0.0, now - rec.renew_time), 3)
                if rec.renew_time else None,
                "durationSeconds": rec.duration_s,
                "transitions": rec.transitions,
                "expired": rec.expired(now),
            })
        # server-side ledger: the raw client may be wrapped (audit /
        # chaos / recording stacks) — walk .inner to the backend
        server = None
        backend = client
        while backend is not None and not hasattr(backend, "audit"):
            backend = getattr(backend, "inner", None)
        audit = getattr(backend, "audit", None)
        if audit is not None:
            totals = audit.totals()
            by_verb: dict = {}
            for (_c, verb, _k), n in totals["requests"].items():
                by_verb[verb] = by_verb.get(verb, 0) + n
            server = {
                "requests": sum(totals["requests"].values()),
                "byVerb": dict(sorted(by_verb.items())),
                "listObjects": sum(totals["list_objects"].values()),
                "listBytes": sum(totals["list_bytes"].values()),
                "watchFanout": round(audit.fanout(), 3),
            }
        series = export_series_totals()
        return 200, {
            "leases": sorted(leases, key=lambda r: (r["namespace"],
                                                    r["name"])),
            "passes": ctrlobs.pass_stats(),
            "server": server,
            "series": {
                "families": len(series),
                "total": sum(series.values()),
                "top": dict(sorted(series.items(),
                                   key=lambda kv: -kv[1])[:10]),
            },
        }

    @app.route("GET", "/api/obs/comm/{namespace}/{name}")
    def comm_obs(params, query, body):
        """One job's communication profile (obs/collectives.py): the
        worker emits a ``comm-profile`` span at its first step with the
        compiled train step's per-link collective accounting — DCN vs
        ICI bytes/step, the per-(link, op) mix, modeled seconds at the
        configured bandwidths, and the full-reshard verdict (the
        MULTICHIP_r05 red flag as data). The newest profile span on the
        job's trace wins (a resize/restart recompiles and re-emits)."""
        from ..obs.collectives import COMM_PROFILE_SPAN
        from ..obs.trace import (SPAN_PATH_ENV, TRACE_ID_ANNOTATION,
                                 load_spans)
        ns, name = params["namespace"], params["name"]
        manifest = _find_training_job(ns, name)
        trace_id = k8s.annotations_of(manifest).get(TRACE_ID_ANNOTATION)
        out = {"namespace": ns, "name": name, "traceId": trace_id,
               "profile": None}
        span_path = os.environ.get(SPAN_PATH_ENV)
        if not trace_id:
            out["note"] = "no trace id minted yet"
            return 200, out
        if not span_path:
            out["note"] = f"no span sink configured ({SPAN_PATH_ENV} unset)"
            return 200, out
        newest = None
        for span in load_spans(span_path, trace_id):
            if span.get("name") == COMM_PROFILE_SPAN:
                newest = span
        if newest is None:
            out["note"] = "no comm-profile span yet (worker has not " \
                          "completed its first step, or profiling is off)"
            return 200, out
        attrs = newest.get("attrs") or {}
        out["profile"] = attrs.get("profile")
        out["step"] = attrs.get("step")
        return 200, out

    @app.route("GET", "/api/sched/queues")
    def sched_queues(params, query, body):
        """Gang-scheduler queue state: per-queue depth, bound capacity,
        and per-job scheduling status — the operator's view of why a job
        is (not) running, fed by the scheduler's state/reason
        annotations (scheduler/core.py) without touching the scheduler
        process itself."""
        from ..api.trainingjob import (DEFAULT_QUEUE,
                                       PREEMPTED_COUNT_ANNOTATION,
                                       SCHED_REASON_ANNOTATION,
                                       SCHED_STATE_ANNOTATION,
                                       TPU_API_VERSION, TrainingJob)
        from ..cluster.client import KubeError
        from ..scheduler import health as sched_health
        from ..scheduler.queue import binding_of, resize_history
        try:
            manifests = client.list(TPU_API_VERSION, "TPUJob")
        except KubeError:
            return 200, []
        # the Quarantined column: hosts the health loop is holding out
        # of placement right now — the cluster-wide context for "why is
        # my queue not draining" (detail under /api/sched/nodes)
        try:
            quarantined_hosts = sum(
                1 for n in client.list("v1", "Node")
                if sched_health.is_quarantined(n))
        except KubeError:
            quarantined_hosts = 0
        queues: dict[str, dict] = {}
        for m in manifests:
            try:
                job = TrainingJob.from_manifest(m)
            except ValueError:
                continue
            policy = job.scheduling_policy
            tpu = job.tpu_spec
            if policy is None or tpu is None or tpu.topology is None:
                continue
            anns = k8s.annotations_of(m)
            placement = binding_of(m)
            bound = placement is not None
            chips = tpu.topology.num_chips * tpu.num_slices
            # ACTUAL bound width vs the spec's nominal: an elastic gang
            # the scheduler shrank/grew runs at its binding's size
            current = placement.chips if placement else 0
            q = queues.setdefault(policy.queue or DEFAULT_QUEUE, {
                "queue": policy.queue or DEFAULT_QUEUE,
                "queued": 0, "bound": 0, "chipsBound": 0,
                "chipsQueued": 0, "preemptions": 0, "resizes": 0,
                "quarantinedHosts": quarantined_hosts, "jobs": []})
            finished = _job_phase(m) in ("Succeeded", "Failed")
            resizes = resize_history(m)
            if not finished:
                q["bound" if bound else "queued"] += 1
                if bound:
                    q["chipsBound"] += current
                else:
                    q["chipsQueued"] += chips
            q["preemptions"] += int(anns.get(
                PREEMPTED_COUNT_ANNOTATION, "0"))
            q["resizes"] += len(resizes)
            q["jobs"].append({
                "name": job.name, "namespace": job.namespace,
                "priority": policy.priority,
                "preemptible": policy.preemptible,
                "chips": chips, "phase": _job_phase(m),
                # elastic-resize surface: the gang's live width, its
                # allowed envelope, and the audit trail of applied
                # resizes (scheduling.kubeflow.org/resize-history)
                "currentChips": current,
                "minChips": policy.min_chips,
                "maxChips": policy.max_chips,
                "resizeHistory": resizes,
                "state": anns.get(SCHED_STATE_ANNOTATION,
                                  "bound" if bound else "queued"),
                "reason": anns.get(SCHED_REASON_ANNOTATION, ""),
                # the host this job's last teardown was pinned on (its
                # next placement excludes it; scheduler/health.py)
                "suspect": sched_health.suspect_of(m) or "",
            })
        for q in queues.values():
            q["jobs"].sort(key=lambda j: (-j["priority"],
                                          j["namespace"], j["name"]))
        return 200, sorted(queues.values(), key=lambda q: q["queue"])

    @app.route("GET", "/api/sched/nodes")
    def sched_nodes(params, query, body):
        """Per-host node health: decayed failure score, quarantine
        state/reason/expiry, and the gangs currently bound onto the
        host — the operator's first stop for "which host is the health
        loop avoiding, and why". Reads the same annotation contracts
        the scheduler writes (scheduler/health.py), no scheduler-process
        access needed."""
        import time as _time

        from ..cluster.client import KubeError
        from ..scheduler import health as sched_health
        from ..scheduler.inventory import POOL_LABEL
        now = _time.time()
        try:
            nodes = client.list("v1", "Node")
            pods = client.list("v1", "Pod")
        except KubeError:
            return 200, []
        # gangs per host, off the pods' own job labels
        gangs: dict[str, set] = {}
        for p in pods:
            node = p.get("spec", {}).get("nodeName")
            jname = k8s.labels_of(p).get("kubeflow.org/job-name")
            if node and jname and \
                    p.get("status", {}).get("phase") in ("Pending",
                                                         "Running"):
                gangs.setdefault(node, set()).add(
                    f"{k8s.namespace_of(p, 'default')}/{jname}")
        rows = []
        for node in nodes:
            labels = k8s.labels_of(node)
            pool = labels.get(POOL_LABEL)
            if not pool:
                continue
            name = k8s.name_of(node)
            rec = sched_health.health_of(node)
            quarantine = sched_health.quarantine_of(node)
            rows.append({
                "node": name,
                "pool": pool,
                "topology": labels.get(
                    "cloud.google.com/gke-tpu-topology", ""),
                "ready": k8s.condition_true(node, "Ready"),
                "healthScore": round(
                    sched_health.decayed_score(node, now), 4),
                "healthEvents": rec["events"],
                "lastEvent": rec["last"],
                "quarantined": quarantine is not None,
                "quarantineReason": (quarantine or {}).get("reason", ""),
                "quarantineExpiry": (quarantine or {}).get("until"),
                "gangs": sorted(gangs.get(name, ())),
            })
        return 200, sorted(rows, key=lambda r: (r["pool"], r["node"]))

    @app.route("GET", "/api/tpu/slices")
    def tpu_slices(params, query, body):
        pools: dict[str, dict] = {}
        for node in client.list("v1", "Node"):
            labels = k8s.labels_of(node)
            topo = labels.get("cloud.google.com/gke-tpu-topology")
            if not topo:
                continue
            alloc = node.get("status", {}).get("allocatable", {}) or {}
            pool = pools.setdefault(topo, {
                "topology": topo,
                "accelerator": labels.get(
                    "cloud.google.com/gke-tpu-accelerator", ""),
                "hosts": 0, "chips": 0, "ready": 0})
            pool["hosts"] += 1
            pool["chips"] += int(float(alloc.get("google.com/tpu", 0)))
            if k8s.condition_true(node, "Ready"):
                pool["ready"] += 1
        return 200, sorted(pools.values(), key=lambda p: p["topology"])

    return app


class DashboardServer(JsonServer):
    def __init__(self, client: KubeClient,
                 metrics: Optional[MetricsService] = None, **kw):
        super().__init__(build_dashboard_app(client, metrics),
                         name="centraldashboard", **kw)
