/* Click-to-deploy UI (components/gcp-click-to-deploy/src/DeployForm.tsx
 * analog, no build infra): a form over the bootstrap REST service —
 * component picker from /kfctl/components, POST /kfctl/e2eDeploy, then
 * poll /kfctl/apps/{name} until conditions report Available (the React
 * UI's DeployProgress), an app table with per-app delete, and the IAM
 * panel driving /kfctl/iam/apply + /kfctl/initProject (the reference
 * UI's "Set up project" step). */
(function () {
  "use strict";

  function esc(v) {
    return String(v).replace(/[&<>"']/g, (ch) => ({
      "&": "&amp;", "<": "&lt;", ">": "&gt;",
      '"': "&quot;", "'": "&#39;",
    }[ch]));
  }

  async function post(path, payload) {
    const resp = await fetch(path, {
      method: "POST",
      headers: { "Content-Type": "application/json" },
      body: JSON.stringify(payload),
    });
    const body = await resp.json();
    if (!resp.ok) throw new Error(body.error || `HTTP ${resp.status}`);
    return body;
  }

  async function get(path) {
    const resp = await fetch(path);
    const body = await resp.json();
    if (!resp.ok) throw new Error(body.error || `HTTP ${resp.status}`);
    return body;
  }

  function logLine(text, cls) {
    const el = document.getElementById("deploy-log");
    el.innerHTML += `<div class="${cls || ""}">${esc(text)}</div>`;
    el.scrollTop = el.scrollHeight;
  }

  // -- component picker (GET /kfctl/components → multi-select) ---------------

  async function loadComponents() {
    const sel = document.getElementById("components");
    if (!sel) return;
    try {
      const { components } = await get("/kfctl/components");
      sel.innerHTML = components.map((c) =>
        `<option value="${esc(c)}">${esc(c)}</option>`).join("");
    } catch (err) {
      logLine(`component list unavailable: ${err.message}`, "error");
    }
  }

  function selectedComponents() {
    const sel = document.getElementById("components");
    if (!sel) return [];
    return Array.from(sel.selectedOptions).map((o) => o.value);
  }

  // -- app table -------------------------------------------------------------

  async function deleteApp(name) {
    logLine(`deleting ${name}…`);
    try {
      await post("/kfctl/apps/delete", { name });
      logLine(`deleted ${name}`, "ok");
    } catch (err) {
      logLine(`delete failed: ${err.message}`, "error");
    }
    refreshApps();
  }

  async function refreshApps() {
    const apps = (await get("/kfctl/apps")).apps;
    const el = document.getElementById("apps");
    el.innerHTML = apps.length
      ? apps.map((a) =>
          `<li><b>${esc(a.name)}</b> — ${esc(a.platform || "existing")}` +
          ` (${esc((a.conditions || []).slice(-1)[0] || "created")})` +
          ` <button type="button" data-del="${esc(a.name)}">delete` +
          "</button></li>").join("")
      : "<li class=empty>no deployments yet</li>";
    el.querySelectorAll("button[data-del]").forEach((b) => {
      b.onclick = () => deleteApp(b.dataset.del);
    });
  }

  // -- deploy with progress polling ------------------------------------------

  async function pollUntilAvailable(name, tries) {
    // DeployProgress: re-show the app until Available lands (apply is
    // synchronous here, but a slow controller may converge afterwards)
    for (let i = 0; i < (tries || 10); i++) {
      const show = await get(`/kfctl/apps/${encodeURIComponent(name)}`);
      const conds = show.conditions || [];
      conds.forEach((c) => logLine(`condition: ${c}`));
      if (conds.some((c) => String(c).startsWith("Available=True"))) {
        return true;
      }
      await new Promise((r) => setTimeout(r, 1000));
    }
    return false;
  }

  async function deploy(ev) {
    ev.preventDefault();
    const form = ev.target;
    const name = form.appname.value.trim();
    const payload = {
      name: name,
      platform: form.platform.value,
      namespace: form.namespace.value.trim() || "kubeflow",
    };
    if (form.project.value.trim()) payload.project = form.project.value.trim();
    if (form.zone && form.zone.value.trim()) {
      payload.zone = form.zone.value.trim();
    }
    if (form.flavor.value) payload.flavor = form.flavor.value;
    const components = selectedComponents();
    if (components.length) payload.components = components;
    const button = form.querySelector("button[type=submit]");
    button.disabled = true;
    logLine(`deploying ${name}…`);
    try {
      const result = await post("/kfctl/e2eDeploy", payload);
      logLine(`applied ${result.applied} objects`, "ok");
      if ((result.failed || []).length) {
        logLine(`failed: ${result.failed.join(", ")}`, "error");
      }
      const ok = await pollUntilAvailable(name, 5);
      logLine(ok ? `${name} is Available` : `${name} not Available yet`,
              ok ? "ok" : "error");
    } catch (err) {
      logLine(`deploy failed: ${err.message}`, "error");
    } finally {
      button.disabled = false;
      refreshApps();
    }
  }

  // -- project IAM (POST /kfctl/iam/apply + /kfctl/initProject) --------------

  async function applyIam(ev) {
    ev.preventDefault();
    const form = ev.target;
    const project = form.iamProject.value.trim();
    const payload = {
      project: project,
      cluster: form.iamCluster.value.trim(),
      email: form.iamEmail.value.trim(),
      action: form.iamAction.value,
    };
    try {
      if (form.iamNumber.value.trim()) {
        await post("/kfctl/initProject", {
          project: project, projectNumber: form.iamNumber.value.trim() });
        logLine(`initProject ${project} ok`, "ok");
      }
      const out = await post("/kfctl/iam/apply", payload);
      logLine(`iam ${out.action} applied on ${out.project}`, "ok");
    } catch (err) {
      logLine(`iam failed: ${err.message}`, "error");
    }
  }

  function main() {
    document.getElementById("deploy-form")
      .addEventListener("submit", deploy);
    const iam = document.getElementById("iam-form");
    if (iam) iam.addEventListener("submit", applyIam);
    loadComponents();
    refreshApps();
  }

  document.readyState === "loading"
    ? document.addEventListener("DOMContentLoaded", main)
    : main();
})();
