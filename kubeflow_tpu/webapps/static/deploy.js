/* Click-to-deploy UI (components/gcp-click-to-deploy/src/DeployForm.tsx
 * analog, no build infra): a form over the bootstrap REST service —
 * POST /kfctl/e2eDeploy, then poll /kfctl/apps/show until conditions
 * report Available, rendering deploy progress like the React UI's
 * DeployProgress. */
(function () {
  "use strict";

  function esc(v) {
    return String(v).replace(/[&<>"']/g, (ch) => ({
      "&": "&amp;", "<": "&lt;", ">": "&gt;",
      '"': "&quot;", "'": "&#39;",
    }[ch]));
  }

  async function post(path, payload) {
    const resp = await fetch(path, {
      method: "POST",
      headers: { "Content-Type": "application/json" },
      body: JSON.stringify(payload),
    });
    const body = await resp.json();
    if (!resp.ok) throw new Error(body.error || `HTTP ${resp.status}`);
    return body;
  }

  async function get(path) {
    const resp = await fetch(path);
    const body = await resp.json();
    if (!resp.ok) throw new Error(body.error || `HTTP ${resp.status}`);
    return body;
  }

  function logLine(text, cls) {
    const el = document.getElementById("deploy-log");
    el.innerHTML += `<div class="${cls || ""}">${esc(text)}</div>`;
    el.scrollTop = el.scrollHeight;
  }

  async function refreshApps() {
    const apps = (await get("/kfctl/apps")).apps;
    const el = document.getElementById("apps");
    el.innerHTML = apps.length
      ? apps.map((a) =>
          `<li><b>${esc(a.name)}</b> — ${esc(a.platform || "existing")}` +
          ` (${esc((a.conditions || []).slice(-1)[0] || "created")})</li>`)
        .join("")
      : "<li class=empty>no deployments yet</li>";
  }

  async function deploy(ev) {
    ev.preventDefault();
    const form = ev.target;
    const name = form.appname.value.trim();
    const payload = {
      name: name,
      platform: form.platform.value,
      namespace: form.namespace.value.trim() || "kubeflow",
    };
    if (form.project.value.trim()) payload.project = form.project.value.trim();
    if (form.flavor.value) payload.flavor = form.flavor.value;
    const button = form.querySelector("button");
    button.disabled = true;
    logLine(`deploying ${name}…`);
    try {
      const result = await post("/kfctl/e2eDeploy", payload);
      logLine(`applied ${result.applied} objects`, "ok");
      const show = await get(`/kfctl/apps/${encodeURIComponent(name)}`);
      (show.conditions || []).forEach((c) => logLine(`condition: ${c}`));
    } catch (err) {
      logLine(`deploy failed: ${err.message}`, "error");
    } finally {
      button.disabled = false;
      refreshApps();
    }
  }

  function main() {
    document.getElementById("deploy-form")
      .addEventListener("submit", deploy);
    refreshApps();
  }

  document.readyState === "loading"
    ? document.addEventListener("DOMContentLoaded", main)
    : main();
})();
