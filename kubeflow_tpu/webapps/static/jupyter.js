/* Jupyter spawner SPA (the reference jupyter-web-app's spawner UI,
 * components/jupyter-web-app/kubeflow_jupyter/default/static — form +
 * notebook/volume tables over the JSON API in webapps/jupyter.py):
 *  - spawner form fed from /api/config (images, TPU slice shapes)
 *  - workspace volume modes (create / existing PVC / none) and dynamic
 *    data-volume rows, the reference's volume editor
 *  - notebook table with status, connect link, delete
 *  - PVC table; every API 401 bounces to the gatekeeper login
 */
(function () {
  "use strict";

  const LOGIN_PATH = "/login";

  function esc(v) {
    return String(v).replace(/[&<>"']/g, (ch) => ({
      "&": "&amp;", "<": "&lt;", ">": "&gt;",
      '"': "&quot;", "'": "&#39;",
    }[ch]));
  }

  async function api(path, opts) {
    const resp = await fetch(path, Object.assign(
      { credentials: "same-origin" }, opts));
    if (resp.status === 401) {
      window.location.assign(LOGIN_PATH);
      throw new Error("unauthenticated");
    }
    let body = {};
    try { body = await resp.json(); } catch (e) { /* non-JSON error */ }
    if (!resp.ok) throw new Error(body.error || `${path}: HTTP ${resp.status}`);
    return body;
  }

  const post = (path, payload) => api(path, {
    method: "POST",
    headers: { "Content-Type": "application/json" },
    body: JSON.stringify(payload),
  });

  function message(text, cls) {
    document.getElementById("message").innerHTML =
      text ? `<span class="${cls || ""}">${esc(text)}</span>` : "";
  }

  function namespace() {
    return document.getElementById("ns").value.trim() || "kubeflow";
  }

  // -- config-driven selects -------------------------------------------------

  async function loadConfig() {
    const cfg = await api("api/config");
    const imageSel = document.querySelector("select[name=image]");
    imageSel.innerHTML = cfg.images.map((i) =>
      `<option value="${esc(i)}">${esc(i)}</option>`).join("");
    const tpuSel = document.querySelector("select[name=tpu]");
    tpuSel.innerHTML = cfg.tpuShapes.map((s) =>
      `<option value="${esc(s)}">${esc(s || "none")}</option>`).join("");
    const wsSize = document.querySelector("input[name=wsSize]");
    if (cfg.defaultWorkspaceSize) wsSize.value = cfg.defaultWorkspaceSize;
    // the snapshot skin (reference rok-UI analog) reveals the
    // workspace-seed URI field
    if (cfg.skin === "snapshot") {
      document.querySelectorAll("[data-skin=snapshot]").forEach((n) => {
        n.hidden = false;
      });
    }
  }

  // -- dynamic data-volume rows ----------------------------------------------

  let volSeq = 0;

  function addVolumeRow() {
    const row = document.createElement("div");
    row.className = "volrow";
    const id = volSeq++;
    row.innerHTML =
      `<input placeholder="pvc name" data-vol="name-${id}">` +
      `<input placeholder="/data/${id}" data-vol="path-${id}">` +
      '<button type="button" class="minor">remove</button>';
    row.querySelector("button").onclick = () => row.remove();
    document.getElementById("data-volumes").appendChild(row);
  }

  function collectDataVolumes() {
    return Array.from(
      document.querySelectorAll("#data-volumes .volrow")).map((row) => {
      const inputs = row.querySelectorAll("input");
      return { name: inputs[0].value.trim(), path: inputs[1].value.trim() };
    }).filter((v) => v.name);
  }

  // -- tables ----------------------------------------------------------------

  async function refreshNotebooks() {
    const ns = namespace();
    const data = await api(`api/namespaces/${encodeURIComponent(ns)}/notebooks`);
    const el = document.getElementById("notebooks");
    if (!data.notebooks.length) {
      el.innerHTML = "<p class=empty>No notebook servers yet.</p>";
      return;
    }
    el.innerHTML = "<table><tr><th>name</th><th>image</th><th>CPU</th>" +
      "<th>memory</th><th>TPU</th><th>status</th><th></th></tr>" +
      data.notebooks.map((nb) =>
        `<tr><td>${esc(nb.name)}</td><td>${esc(nb.image)}</td>` +
        `<td>${esc(nb.cpu)}</td><td>${esc(nb.memory)}</td>` +
        `<td>${esc(nb.tpu || "")}</td>` +
        `<td class="status-${esc(nb.status)}">${esc(nb.status)}</td>` +
        `<td><a href="/notebook/${encodeURIComponent(nb.namespace)}/` +
        `${encodeURIComponent(nb.name)}/">connect</a> ` +
        `<button class="minor" data-delete="${esc(nb.name)}">delete` +
        "</button></td></tr>").join("") + "</table>";
    el.querySelectorAll("button[data-delete]").forEach((b) => {
      b.onclick = async () => {
        b.disabled = true;
        try {
          await api(`api/namespaces/${encodeURIComponent(ns)}/notebooks/` +
            encodeURIComponent(b.dataset.delete), { method: "DELETE" });
          message(`deleted ${b.dataset.delete}`, "ok");
        } catch (err) {
          message(err.message, "error");
        }
        refreshNotebooks();
      };
    });
  }

  async function refreshPvcs() {
    const ns = namespace();
    const data = await api(`api/namespaces/${encodeURIComponent(ns)}/pvcs`);
    document.getElementById("pvcs").innerHTML = data.pvcs.length
      ? "<table><tr><th>name</th><th>size</th><th>mode</th></tr>" +
        data.pvcs.map((p) =>
          `<tr><td>${esc(p.name)}</td><td>${esc(p.size)}</td>` +
          `<td>${esc(p.mode)}</td></tr>`).join("") + "</table>"
      : "<p class=empty>No volumes in this namespace.</p>";
  }

  const refresh = () => Promise.all([refreshNotebooks(), refreshPvcs()])
    .catch((err) => {
      if (err.message !== "unauthenticated") message(err.message, "error");
    });

  // -- spawn -----------------------------------------------------------------

  async function spawn(ev) {
    ev.preventDefault();
    const form = ev.target;
    const payload = {
      name: form.name.value.trim(),
      image: form.customImage.value.trim() || form.image.value,
      cpu: form.cpu.value.trim(),
      memory: form.memory.value.trim(),
      tpu: form.tpu.value,
      dataVolumes: collectDataVolumes(),
    };
    const wsMode = form.wsMode.value;
    if (wsMode !== "none") {
      payload.workspaceVolume = {
        size: form.wsSize.value.trim() || "10Gi",
        create: wsMode === "create",
      };
    }
    if (!form.snapshotUri.hidden && form.snapshotUri.value.trim()) {
      payload.snapshotUri = form.snapshotUri.value.trim();
    }
    const button = form.querySelector("button[type=submit]");
    button.disabled = true;
    message(`spawning ${payload.name}…`);
    try {
      const out = await post(
        `api/namespaces/${encodeURIComponent(namespace())}/notebooks`,
        payload);
      message(`notebook ${out.notebook.name} created`, "ok");
      form.name.value = "";
    } catch (err) {
      if (err.message !== "unauthenticated") message(err.message, "error");
    } finally {
      button.disabled = false;
      refresh();
    }
  }

  function main() {
    document.getElementById("spawn-form").addEventListener("submit", spawn);
    document.getElementById("add-volume").onclick = addVolumeRow;
    document.getElementById("ns").addEventListener("change", refresh);
    loadConfig().then(refresh).catch((err) => {
      if (err.message !== "unauthenticated") message(err.message, "error");
    });
  }

  document.readyState === "loading"
    ? document.addEventListener("DOMContentLoaded", main)
    : main();
})();
