/* Kubeflow TPU central dashboard SPA.
 *
 * The Polymer main-page.js / namespace-selector.js analog rendered
 * client-side from the dashboard JSON API (webapps/dashboard.py), with:
 *  - namespace selector (persisted in localStorage) driving activities
 *  - overview stat tiles + TPU slice inventory
 *  - cluster metrics as single-hue SVG bar charts with per-mark hover
 *    tooltips and a table toggle (the resource-chart.js analog)
 *  - runs panel with status badges (icon + label, never color alone)
 *  - hash routing (#/overview, #/runs, #/activities, #/metrics,
 *    #/notebooks); the notebooks view iframes the jupyter web app, the
 *    reference's iframe-embedding pattern (main-page.js)
 *  - every API 401 redirects to the gatekeeper login page
 *
 * All data-driven DOM is built with textContent (API values are
 * untrusted); colors live in CSS custom properties set per color-scheme
 * in the page shell.
 */
(function () {
  "use strict";

  const LOGIN_PATH = "/login";
  const JUPYTER_PATH = "/jupyter/";
  const NS_KEY = "kftpu.namespace";

  async function api(path, opts) {
    const init = { credentials: "same-origin" };
    if (opts && opts.method) init.method = opts.method;
    if (opts && opts.body !== undefined) {
      init.body = JSON.stringify(opts.body);
      init.headers = { "Content-Type": "application/json" };
    }
    const resp = await fetch(path, init);
    if (resp.status === 401) {
      // unauthenticated: bounce through the gatekeeper login page
      window.location.assign(LOGIN_PATH);
      throw new Error("unauthenticated");
    }
    if (!resp.ok) {
      let detail = "";
      try { detail = (await resp.json()).error || ""; } catch (e) { /* raw */ }
      throw new Error(detail || `${path}: HTTP ${resp.status}`);
    }
    return resp.json();
  }

  // -- DOM helpers (textContent only: API strings are untrusted) -------------

  function el(tag, attrs, children) {
    const node = tag === "svg" || tag === "rect" || tag === "line" ||
      tag === "text" || tag === "g"
      ? document.createElementNS("http://www.w3.org/2000/svg", tag)
      : document.createElement(tag);
    Object.entries(attrs || {}).forEach(([k, v]) => {
      if (k === "text") node.textContent = v;
      else if (k.startsWith("on")) node[k] = v;
      else node.setAttribute(k, v);
    });
    (children || []).forEach((c) => node.appendChild(c));
    return node;
  }

  function table(rows, cols, renderCell) {
    const t = el("table");
    t.appendChild(el("tr", {}, cols.map((c) => el("th", { text: c }))));
    rows.forEach((r) => {
      t.appendChild(el("tr", {}, cols.map((c) => {
        const td = el("td");
        if (renderCell && renderCell(c, r, td)) return td;
        td.textContent = r[c] ?? "";
        return td;
      })));
    });
    return t;
  }

  // -- status badges (fixed status palette; icon + label, never color
  //    alone) ----------------------------------------------------------------

  const PHASE_STATUS = {
    Succeeded: ["good", "✓"],      // ✓
    Running: ["running", "▶"],     // ▶
    Created: ["running", "▶"],
    Failed: ["critical", "✗"],     // ✗
    Error: ["critical", "✗"],
    Pending: ["warning", "⏳"],     // ⏳
  };

  function badge(cls, icon, label) {
    return el("span", { class: `badge badge-${cls}` }, [
      el("span", { class: "badge-icon", text: icon, "aria-hidden": "true" }),
      el("span", { text: " " + label }),
    ]);
  }

  function statusBadge(phase) {
    const [cls, icon] = PHASE_STATUS[phase] || ["neutral", "•"];
    return badge(cls, icon, phase);
  }

  // -- stat tiles ------------------------------------------------------------

  function compact(n) {
    if (n >= 1e6) return (n / 1e6).toFixed(1) + "M";
    if (n >= 1e4) return (n / 1e3).toFixed(1) + "K";
    return String(n);
  }

  function statTile(label, value) {
    return el("div", { class: "tile" }, [
      el("div", { class: "tile-label", text: label }),
      el("div", { class: "tile-value", text: compact(value) }),
    ]);
  }

  // -- bar chart (single series, one hue; marks-and-anatomy specs) -----------

  let tooltip = null;

  function showTooltip(evt, label, value) {
    if (!tooltip) {
      tooltip = el("div", { class: "viz-tooltip", role: "status" });
      document.body.appendChild(tooltip);
    }
    tooltip.replaceChildren(
      el("span", { class: "viz-tooltip-value", text: String(value) }),
      el("span", { class: "viz-tooltip-label", text: " " + label }));
    tooltip.style.display = "block";
    const pad = 12;
    tooltip.style.left = `${evt.pageX + pad}px`;
    tooltip.style.top = `${evt.pageY + pad}px`;
  }

  function hideTooltip() {
    if (tooltip) tooltip.style.display = "none";
  }

  function barChart(rows, { labelKey, valueKey, maxBars = 20, unit = "" }) {
    // magnitude → horizontal bars, sorted desc; overflow folds to "Other"
    const sorted = rows.slice().sort((a, b) => b[valueKey] - a[valueKey]);
    const shown = sorted.slice(0, maxBars);
    const rest = sorted.slice(maxBars);
    if (rest.length) {
      shown.push({
        [labelKey]: `Other (${rest.length})`,
        [valueKey]: rest.reduce((s, r) => s + (r[valueKey] || 0), 0),
      });
    }
    const barH = 18, gap = 8, labelW = 180, valueW = 56;
    const plotW = 420;
    const width = labelW + plotW + valueW;
    const height = shown.length * (barH + gap) + 24;
    const max = Math.max(...shown.map((r) => r[valueKey]), 1e-9);
    const svg = el("svg", {
      viewBox: `0 0 ${width} ${height}`, width: "100%",
      style: `max-width:${width}px`, role: "img",
      "aria-label": "bar chart",
    });
    // recessive hairline gridlines at 0/25/50/75/100%
    for (let i = 0; i <= 4; i++) {
      const x = labelW + (plotW * i) / 4;
      svg.appendChild(el("line", {
        x1: x, y1: 0, x2: x, y2: height - 20, class: "viz-grid",
      }));
      svg.appendChild(el("text", {
        x, y: height - 6, class: "viz-tick", "text-anchor": "middle",
        text: compact((max * i) / 4),
      }));
    }
    shown.forEach((r, i) => {
      const y = i * (barH + gap);
      const w = Math.max((r[valueKey] / max) * plotW, r[valueKey] > 0 ? 2 : 0);
      const label = String(r[labelKey]);
      const value = r[valueKey];
      svg.appendChild(el("text", {
        x: labelW - 8, y: y + barH - 5, class: "viz-label",
        "text-anchor": "end",
        text: label.length > 26 ? label.slice(0, 25) + "…" : label,
      }));
      // 4px rounded data-end, square baseline: round rect clipped at the
      // baseline by a square patch
      const bar = el("rect", {
        x: labelW, y, width: w, height: barH, rx: 4, class: "viz-bar",
      });
      const patch = w > 8 ? el("rect", {
        x: labelW, y, width: Math.min(4, w / 2), height: barH,
        class: "viz-bar", "aria-hidden": "true",
      }) : null;
      if (patch) svg.appendChild(patch);
      svg.appendChild(bar);
      svg.appendChild(el("text", {
        x: labelW + w + 6, y: y + barH - 5, class: "viz-value",
        text: compact(Math.round(value * 100) / 100) + unit,
      }));
      // hit target bigger than the mark: a transparent full-row rect
      // carries pointer AND keyboard focus; the mark lifts via a class
      // toggled here (the hit rect sits on top, so CSS :hover on the
      // bar itself would never fire)
      const lift = (on) => {
        bar.classList.toggle("hover", on);
        if (patch) patch.classList.toggle("hover", on);
      };
      const hit = el("rect", {
        x: 0, y: y - gap / 2, width, height: barH + gap,
        fill: "transparent", tabindex: "0",
        onpointermove: (evt) => {
          lift(true);
          showTooltip(evt, label, value + unit);
        },
        onpointerleave: () => { lift(false); hideTooltip(); },
        onfocus: (evt) => {
          lift(true);
          const b = evt.target.getBoundingClientRect();
          showTooltip({ pageX: b.left + scrollX, pageY: b.top + scrollY },
            label, value + unit);
        },
        onblur: () => { lift(false); hideTooltip(); },
      });
      svg.appendChild(hit);
    });
    return svg;
  }

  function chartWithTable(rows, opts, cols) {
    const wrap = el("div", { class: "viz-root" });
    if (!rows.length) {
      wrap.appendChild(el("p", { class: "empty", text: "No data." }));
      return wrap;
    }
    const chart = barChart(rows, opts);
    const tbl = table(rows, cols);
    tbl.style.display = "none";
    const toggle = el("button", {
      class: "minor", text: "table view",
      onclick: () => {
        const showTable = tbl.style.display === "none";
        tbl.style.display = showTable ? "" : "none";
        chart.style.display = showTable ? "none" : "";
        toggle.textContent = showTable ? "chart view" : "table view";
      },
    });
    wrap.appendChild(toggle);
    wrap.appendChild(chart);
    wrap.appendChild(tbl);
    return wrap;
  }

  // -- namespace selector ----------------------------------------------------

  async function renderNamespaceSelector() {
    const namespaces = await api("api/namespaces");
    const current = localStorage.getItem(NS_KEY) || namespaces[0] || "default";
    const sel = document.getElementById("ns-selector");
    sel.replaceChildren(...namespaces.map((n) => {
      const o = el("option", { value: n, text: n });
      if (n === current) o.selected = true;
      return o;
    }));
    sel.onchange = () => {
      localStorage.setItem(NS_KEY, sel.value);
      render();  // re-render the active view in the new namespace
    };
    return current;
  }

  function selectedNamespace() {
    const sel = document.getElementById("ns-selector");
    return (sel && sel.value) || localStorage.getItem(NS_KEY) || "default";
  }

  // -- views -----------------------------------------------------------------

  // quick shortcuts, the dashboard-view.js card row analog
  const SHORTCUTS = [
    ["#/notebooks", "Spawn a notebook",
      "JupyterLab on TPU node pools via the notebook controller"],
    ["#/runs", "Run history",
      "Training jobs, workflows and Katib studies in this namespace"],
    ["#/contributors", "Manage contributors",
      "Grant namespace access through the profile access API"],
    ["#/metrics", "Cluster metrics",
      "Pod resource requests and per-node scheduling pressure"],
  ];

  // control-plane HA panel (/api/obs/controlplane — the ISSUE 14
  // panel): one row per lease — who leads each controller deployment,
  // how fresh its claim is, and how many failovers (transitions) the
  // lease has seen. An EXPIRED lease is the "nothing is leading the
  // scheduler" alarm, flagged with a badge, never color alone.
  function controlPlanePanel(data) {
    const leases = (data && data.leases) || [];
    const passes = (data && data.passes) || {};
    const server = data && data.server;
    const series = data && data.series;
    const out = [];
    if (leases.length) {
      const rows = leases.map((l) => ({
        lease: `${l.namespace}/${l.name}`,
        leader: l.holder || "(none)",
        "lease age": l.ageSeconds == null ? "" : `${l.ageSeconds}s`,
        duration: `${l.durationSeconds}s`,
        failovers: Math.max(0, (l.transitions || 1) - 1),
        state: l.expired ? "✗ expired — no leader" : "✓ held",
      }));
      out.push(
        el("h2", { text: "Control plane" }),
        table(rows, ["lease", "leader", "lease age", "duration",
                     "failovers", "state"]));
    }
    // telemetry tiles (ISSUE 20): apiserver pressure + series
    // cardinality at a glance; per-component pass stats as a table
    const comps = Object.keys(passes).sort();
    if (server || series || comps.length) {
      if (!leases.length) out.push(el("h2", { text: "Control plane" }));
      const tiles = [];
      if (server) {
        tiles.push(
          statTile("API requests", server.requests),
          statTile("List objects", server.listObjects),
          statTile("Watch fan-out", server.watchFanout));
      }
      if (series) tiles.push(statTile("Metric series", series.total));
      if (tiles.length) out.push(el("div", { class: "tiles" }, tiles));
    }
    if (comps.length) {
      const rows = comps.map((c) => {
        const p = passes[c];
        return {
          component: c,
          passes: p.passes,
          "no-op %": `${Math.round(p.noopFraction * 100)}%`,
          "pass p50": `${Math.round(p.p50Seconds * 1e3)}ms`,
          "pass p99": `${Math.round(p.p99Seconds * 1e3)}ms`,
          "write amp": p.writeAmplification || "",
          relists: p.relists,
        };
      });
      out.push(
        el("h3", { text: "Reconcile passes" }),
        table(rows, ["component", "passes", "no-op %", "pass p50",
                     "pass p99", "write amp", "relists"]));
    }
    return out;
  }

  async function viewOverview(root) {
    const [slices, nodes, runs, controlplane] = await Promise.all([
      api("api/tpu/slices"), api("api/metrics/node"),
      api(`api/runs/${encodeURIComponent(selectedNamespace())}`),
      api("api/obs/controlplane").catch(() => ({ leases: [] })),
    ]);
    const chips = slices.reduce((s, p) => s + p.chips, 0);
    const hosts = slices.reduce((s, p) => s + p.hosts, 0);
    const active = runs.filter((r) =>
      r.phase === "Running" || r.phase === "Created").length;
    root.replaceChildren(
      el("div", { class: "cards" }, SHORTCUTS.map(([href, title, desc]) =>
        el("a", { class: "card", href }, [
          el("div", { class: "card-title", text: title }),
          el("div", { class: "card-desc", text: desc }),
        ]))),
      el("div", { class: "tiles" }, [
        statTile("TPU chips", chips),
        statTile("TPU hosts", hosts),
        statTile("Slice pools", slices.length),
        statTile("Cluster nodes", nodes.length),
        statTile("Active runs", active),
      ]),
      ...controlPlanePanel(controlplane),
      el("h2", { text: "TPU slices" }),
      slices.length
        ? table(slices, ["topology", "accelerator", "hosts", "chips",
                         "ready"])
        : el("p", { class: "empty",
                    text: "No TPU slices in this cluster." }),
      el("h2", { text: "Pods per node" }),
      chartWithTable(nodes, { labelKey: "node", valueKey: "value" },
        ["node", "value"]));
  }

  function relativeTime(iso) {
    // "3m ago" with the absolute timestamp on hover (activities-list.js
    // formatting role); empty/unparseable timestamps pass through
    const t = Date.parse(iso);
    if (!iso || Number.isNaN(t)) return el("span", { text: iso || "" });
    const s = Math.max(0, (Date.now() - t) / 1000);
    const label = s < 90 ? `${Math.round(s)}s ago`
      : s < 5400 ? `${Math.round(s / 60)}m ago`
      : s < 129600 ? `${Math.round(s / 3600)}h ago`
      : `${Math.round(s / 86400)}d ago`;
    return el("span", { title: iso, text: label });
  }

  const EVENT_ICONS = {
    Normal: ["neutral", "ℹ"],
    Warning: ["warning", "⚠"],
    Error: ["critical", "✗"],
  };

  async function viewActivities(root) {
    const ns = selectedNamespace();
    const acts = await api(`api/activities/${encodeURIComponent(ns)}`);
    root.replaceChildren(
      el("h2", { text: `Activities in ${ns}` }),
      acts.length
        ? table(acts, ["type", "reason", "involvedObject", "message",
                       "lastTimestamp"], (col, row, td) => {
            if (col === "type") {
              const [cls, icon] = EVENT_ICONS[row.type] ||
                EVENT_ICONS.Normal;
              td.appendChild(badge(cls, icon, row.type));
              return true;
            }
            if (col === "lastTimestamp") {
              td.appendChild(relativeTime(row.lastTimestamp));
              return true;
            }
            return false;
          })
        : el("p", { class: "empty", text: "No recent events." }));
  }

  const METRIC_TABS = [
    ["podcpu", "CPU requests per pod", "podcpu"],
    ["podmem", "Memory requests per pod", "podmem"],
    ["node", "Pods per node", "node"],
  ];

  async function viewMetrics(root) {
    const kind = (location.hash.split("/")[2]) || "podcpu";
    const rows = await api(`api/metrics/${encodeURIComponent(kind)}`);
    const tabs = el("nav", { class: "tabs" }, METRIC_TABS.map(([k]) =>
      el("a", {
        href: `#/metrics/${k}`, text: k,
        class: k === kind ? "active" : "",
      })));
    const title = (METRIC_TABS.find(([k]) => k === kind) || [])[1] || kind;
    const labelKey = kind === "node" ? "node" : "pod";
    const cols = kind === "node" ? ["node", "value"]
      : ["namespace", "pod", "value"];
    root.replaceChildren(
      el("h2", { text: title }), tabs,
      chartWithTable(rows, { labelKey, valueKey: "value" }, cols));
  }

  // one job's communication profile (/api/obs/comm — the ISSUE 13
  // panel): DCN vs ICI bytes/step, the per-(link, op) collective mix,
  // and the full-reshard red flag as a badge
  function commDetail(ns, name, data) {
    const blocks = [el("h3", { text: `Comm profile of ${name}` })];
    if (!data.profile) {
      blocks.push(el("p", { class: "empty",
                            text: data.note || "no profile yet" }));
      return el("div", {}, blocks);
    }
    const p = data.profile;
    const reshard = (p.dcnFullReshard || {}).flagged;
    blocks.push(el("div", { class: "tiles" }, [
      statTile("DCN bytes/step", p.dcnBytesPerStep),
      statTile("ICI bytes/step", p.iciBytesPerStep),
      statTile("DCN collectives",
        (p.collectivesPerStep || {}).dcn ?? 0),
      statTile("Full reshard", reshard ? "FLAGGED" : "clean"),
    ]));
    if (reshard) {
      blocks.push(el("p", { class: "error",
                            text: (p.dcnFullReshard || {}).reason || "" }));
    }
    const rows = Object.entries(p.byLinkOp || {}).map(([k, v]) => ({
      "link/op": k, count: v.count, bytes: v.bytes,
    }));
    if (rows.length) {
      blocks.push(table(rows, ["link/op", "count", "bytes"]));
    }
    return el("div", {}, blocks);
  }

  // which run's comm detail is open — survives the live re-render
  let openCommRun = null;

  async function viewRuns(root) {
    const ns = selectedNamespace();
    const runs = await api(`api/runs/${encodeURIComponent(ns)}`);
    const phases = ["all", ...new Set(runs.map((r) => r.phase))];
    const current = (location.hash.split("/")[2]) || "all";
    const filter = el("nav", { class: "tabs" }, phases.map((p) =>
      el("a", {
        href: `#/runs/${p}`, text: p,
        class: p === current ? "active" : "",
      })));
    const visible = current === "all" ? runs
      : runs.filter((r) => r.phase === current);
    // a namespace switch (or a deleted run) must not leave the panel
    // fetching a run that no longer exists here
    if (openCommRun && !runs.some((r) => r.name === openCommRun)) {
      openCommRun = null;
    }
    const detail = el("div");
    if (openCommRun) {
      api(`api/obs/comm/${encodeURIComponent(ns)}/` +
          encodeURIComponent(openCommRun))
        .then((d) => detail.replaceChildren(commDetail(ns, openCommRun, d)))
        .catch((e) => detail.replaceChildren(
          el("p", { class: "error", text: e.message })));
    }
    root.replaceChildren(
      el("h2", { text: `Runs in ${ns}` }), filter,
      visible.length
        ? table(visible, ["kind", "name", "phase", "progress",
                          "kernels", "finishedAt", "comm"],
            (col, row, td) => {
              if (col === "phase") {
                td.appendChild(statusBadge(row.phase));
                return true;
              }
              if (col === "comm") {
                td.appendChild(el("button", {
                  class: "minor",
                  text: openCommRun === row.name ? "hide" : "comm",
                  onclick: () => {
                    openCommRun = openCommRun === row.name
                      ? null : row.name;
                    render();
                  },
                }));
                return true;
              }
              return false;
            })
        : el("p", { class: "empty",
                    text: "No training jobs or workflow runs." }),
      detail);
  }

  // -- pipelines (runs + scheduled jobs over the pipeline apiserver,
  //    ingress-mounted at /pipeline/) ---------------------------------------

  const PIPELINE_API = "pipeline/apis/v1beta1";

  // which run's step detail is open — survives the 15s live re-render
  let openStepsRun = null;

  function stepsDetail(row) {
    return el("div", {}, [
      el("h3", { text: `Steps of ${row.name}` }),
      table(row._nodes.map((n) => ({
        step: n.displayName || n.name || n.id || "",
        phase: n.phase || "",
        message: n.message || "",
      })), ["step", "phase", "message"], (c, r2, td2) => {
        if (c !== "phase") return false;
        td2.appendChild(statusBadge(r2.phase));
        return true;
      }),
    ]);
  }

  async function viewPipelines(root) {
    const ns = selectedNamespace();
    const err = el("p", { class: "error" });
    // errors propagate to renderInto: readable on navigation, and a
    // failed background poll keeps the last good content (its contract)
    const [runs, jobs] = await Promise.all([
      api(`${PIPELINE_API}/runs?namespace=${encodeURIComponent(ns)}`)
        .then((r) => r.runs),
      api(`${PIPELINE_API}/jobs?namespace=${encodeURIComponent(ns)}`)
        .then((r) => r.jobs || []),
    ]);
    const runRows = runs.map((r) => {
      const nodes = Object.values(r.nodes || {});
      const done = nodes.filter((n) => n.phase === "Succeeded").length;
      return {
        name: r.name, phase: r.phase,
        steps: nodes.length ? `${done}/${nodes.length}` : "",
        schedule: r.schedule || "",
        _nodes: nodes,
      };
    });
    const blocks = [
      el("h2", { text: `Pipeline runs in ${ns}` }), err,
      runRows.length
        ? table(runRows, ["name", "phase", "steps", "schedule", ""],
            (col, row, td) => {
              if (col === "phase") {
                td.appendChild(statusBadge(row.phase));
                return true;
              }
              if (col !== "") return false;
              if (!row._nodes.length) return true;
              td.appendChild(el("button", {
                class: "minor", text: "steps",
                onclick: () => {
                  openStepsRun = `${ns}/${row.name}`;
                  const detail = document.getElementById("run-steps");
                  detail.replaceChildren(stepsDetail(row));
                },
              }));
              return true;
            })
        : el("p", { class: "empty", text: "No pipeline runs yet." }),
    ];
    // re-populate the open step detail across live re-renders (keyed by
    // ns/name so a same-named run in another namespace never auto-opens)
    const open = runRows.find((r) => `${ns}/${r.name}` === openStepsRun);
    blocks.push(el("div", { id: "run-steps" },
                   open ? [stepsDetail(open)] : []));
    blocks.push(el("h2", { text: "Scheduled jobs" }));
    const jobRows = jobs.map((j) => {
        const t = j.trigger || {};
        const schedule = (t.cronSchedule && t.cronSchedule.cron) ||
          (t.periodicSchedule &&
            `every ${t.periodicSchedule.intervalSecond}s`) || "";
        return { name: j.name, namespace: j.namespace, schedule,
                 enabled: String(j.enabled), _enabled: j.enabled };
      });
    blocks.push(jobRows.length
      ? table(jobRows, ["name", "schedule", "enabled", ""],
          (col, row, td) => {
            if (col !== "") return false;
            const verb = row._enabled ? "disable" : "enable";
            td.appendChild(el("button", {
              class: "minor", text: verb,
              onclick: async () => {
                try {
                  await api(`${PIPELINE_API}/jobs/` +
                    `${encodeURIComponent(row.namespace || ns)}/` +
                    `${encodeURIComponent(row.name)}:${verb}`,
                    { method: "POST" });
                  render();
                } catch (e) { err.textContent = e.message; }
              },
            }));
            return true;
          })
      : el("p", { class: "empty", text: "No scheduled jobs." }));
    root.replaceChildren(...blocks);
  }

  // -- katib studies (per-trial objective series over /api/studies) ---------

  function trialObjectiveChart(trials, best) {
    // a SERIES chart, not a magnitude chart: bars stay in trial order
    // (the search trajectory), widths scale min→max so negative
    // objectives work, nothing is sorted or folded — overflow past 40
    // trials is cut with an explicit note, and the best trial is
    // badged. barChart's desc-sort + summed-Other semantics would be
    // wrong on objectives (a sum of losses is not a loss).
    const MAX = 40;
    const shown = trials.slice(0, MAX);
    const vals = shown.map((t) => t.objective);
    const min = Math.min(...vals), max = Math.max(...vals);
    const span = max - min || Math.abs(max) || 1;
    const barH = 18, gap = 8, labelW = 170, valueW = 80, plotW = 380;
    const width = labelW + plotW + valueW;
    const height = shown.length * (barH + gap) + 4;
    const svg = el("svg", {
      viewBox: `0 0 ${width} ${height}`, width: "100%",
      style: `max-width:${width}px`, role: "img",
      "aria-label": "trial objectives in run order",
    });
    shown.forEach((t, i) => {
      const y = i * (barH + gap);
      // floor at 8px so the minimum bar is still visible/hoverable
      const w = 8 + ((t.objective - min) / span) * (plotW - 8);
      const name = t.trial + (t.trial === best ? " ★" : "");
      svg.appendChild(el("text", {
        x: labelW - 8, y: y + barH - 5, class: "viz-label",
        "text-anchor": "end",
        text: name.length > 24 ? name.slice(0, 23) + "…" : name,
      }));
      svg.appendChild(el("rect", {
        x: labelW, y, width: w, height: barH, rx: 4, class: "viz-bar",
      }));
      svg.appendChild(el("text", {
        x: labelW + w + 6, y: y + barH - 5, class: "viz-value",
        text: String(t.objective),
      }));
    });
    const wrap = el("div", { class: "viz-root" }, [svg]);
    if (trials.length > MAX) {
      wrap.appendChild(el("p", {
        class: "empty",
        text: `Showing first ${MAX} of ${trials.length} trials — ` +
          "see the table for the rest.",
      }));
    }
    return wrap;
  }

  async function viewStudies(root) {
    const ns = selectedNamespace();
    const studies = await api(`api/studies/${encodeURIComponent(ns)}`);
    const blocks = [el("h2", { text: `Katib studies in ${ns}` })];
    if (!studies.length) {
      blocks.push(el("p", { class: "empty",
                            text: "No studies in this namespace." }));
    }
    studies.forEach((s) => {
      blocks.push(el("h3", {}, [
        el("span", { text: s.name + " " }), statusBadge(s.phase),
      ]));
      const tiles = [
        statTile("Trials", s.trialsTotal),
        statTile("Succeeded", s.trialsSucceeded),
        statTile("Failed", s.trialsFailed),
      ];
      if (s.bestTrial && s.bestTrial.objective != null) {
        tiles.push(statTile(
          `Best ${s.objectiveName} (${s.optimization})`,
          Math.round(s.bestTrial.objective * 1e4) / 1e4));
      }
      blocks.push(el("div", { class: "tiles" }, tiles));
      const done = s.trials.filter((t) => t.objective != null).map((t) => ({
        trial: t.name,
        objective: Math.round(t.objective * 1e4) / 1e4,
        status: t.status,
        parameters: JSON.stringify(t.parameters),
      }));
      if (done.length) {
        blocks.push(trialObjectiveChart(
          done, s.bestTrial && s.bestTrial.name));
        blocks.push(table(done,
          ["trial", "objective", "status", "parameters"]));
      } else {
        blocks.push(el("p", { class: "empty",
                              text: "No finished trials yet." }));
      }
    });
    root.replaceChildren(...blocks);
  }

  // -- experiments (the Experiment CRD rollup over /api/katib/experiments) --

  async function viewExperiments(root) {
    const exps = await api("api/katib/experiments");
    const blocks = [el("h2", { text: "Experiments" })];
    if (!exps.length) {
      blocks.push(el("p", { class: "empty", text: "No experiments." }));
    }
    for (const e of exps) {
      blocks.push(el("h3", {}, [
        el("span", { text: `${e.namespace}/${e.name} ` }),
        statusBadge(e.phase),
      ]));
      const tiles = [
        statTile("Algorithm", e.algorithm || "—"),
        statTile("Trials", `${e.trialsSucceeded + e.trialsStopped}/` +
          `${e.trialsTotal}`),
        statTile("Trials/hour", e.trialsPerHour != null
          ? Math.round(e.trialsPerHour * 100) / 100 : "—"),
        statTile("Warm-start", e.warmStartFraction != null
          ? `${Math.round(e.warmStartFraction * 100)}%` : "—"),
      ];
      if (e.bestTrial && e.bestTrial.objective != null) {
        tiles.push(statTile(
          `Best ${e.objectiveMetric} (${e.optimization})`,
          Math.round(e.bestTrial.objective * 1e4) / 1e4));
      }
      if (e.chipHours && e.chipHours.total != null) {
        tiles.push(statTile("Chip-hours",
          Math.round(e.chipHours.total * 100) / 100));
        if (e.chipHours.saved) {
          tiles.push(statTile("Saved (early stop)",
            Math.round(e.chipHours.saved * 100) / 100));
        }
      }
      blocks.push(el("div", { class: "tiles" }, tiles));
      const detail = await api("api/katib/experiments/" +
        `${encodeURIComponent(e.namespace)}/${encodeURIComponent(e.name)}`);
      const rows = detail.trials.map((t) => ({
        trial: t.name,
        status: t.status + (t.stoppedEarly ? " (early stop)" : ""),
        objective: t.objective != null
          ? Math.round(t.objective * 1e4) / 1e4 : "—",
        chips: t.chips,
        start: t.startKind,
        parameters: JSON.stringify(t.parameters),
      }));
      if (rows.length) {
        blocks.push(table(rows, ["trial", "status", "objective", "chips",
                                 "start", "parameters"]));
      } else {
        blocks.push(el("p", { class: "empty", text: "No trials yet." }));
      }
    }
    root.replaceChildren(...blocks);
  }

  // -- contributors (the manage-users surface over the KFAM API) ------------

  const KFAM_ROLES = ["kubeflow-view", "kubeflow-edit", "kubeflow-admin"];

  async function viewContributors(root) {
    const ns = selectedNamespace();
    const data = await api(
      `kfam/v1/bindings?namespace=${encodeURIComponent(ns)}`);
    const rows = data.bindings.map((b) => ({
      user: b.user.name,
      kind: b.user.kind,
      role: (b.roleRef || {}).name || "",
    }));

    const email = el("input", {
      type: "email", placeholder: "user@example.com", required: "required",
      "aria-label": "contributor email",
    });
    const role = el("select", { "aria-label": "role" },
      KFAM_ROLES.map((r) => el("option", { value: r, text: r })));
    const err = el("p", { class: "error" });
    const form = el("form", {
      class: "inline",
      onsubmit: async (evt) => {
        evt.preventDefault();
        if (!email.value) return;
        try {
          await api("kfam/v1/bindings", { method: "POST", body: {
            user: { kind: "User", name: email.value },
            referredNamespace: ns,
            roleRef: { kind: "ClusterRole", name: role.value },
          } });
          render();
        } catch (e) { err.textContent = e.message; }
      },
    }, [email, role, el("button", { class: "minor", text: "Add" })]);

    root.replaceChildren(
      el("h2", { text: `Contributors to ${ns}` }),
      form, err,
      rows.length
        ? table(rows, ["user", "kind", "role", ""], (col, row, td) => {
            if (col !== "") return false;
            td.appendChild(el("button", {
              class: "minor", text: "Remove",
              onclick: async () => {
                try {
                  await api("kfam/v1/bindings", { method: "DELETE", body: {
                    user: { kind: row.kind, name: row.user },
                    referredNamespace: ns,
                    roleRef: { kind: "ClusterRole", name: row.role },
                  } });
                  render();
                } catch (e) { err.textContent = e.message; }
              },
            }));
            return true;
          })
        : el("p", { class: "empty",
                    text: "No contributors in this namespace." }));
  }

  // -- serving observability (per-model ledger rollup + SLO over
  //    /api/obs/serving — the ISSUE 11 panel) -------------------------------

  async function viewServing(root) {
    const data = await api("api/obs/serving");
    const blocks = [el("h2", { text: "Serving observability" })];
    if (data.note) {
      blocks.push(el("p", { class: "empty", text: data.note }));
    }
    const models = data.models || [];
    if (!models.length) {
      blocks.push(el("p", { class: "empty",
                            text: "No serving requests traced yet." }));
      root.replaceChildren(...blocks);
      return;
    }
    const primary = models.filter((m) => m.role === "primary");
    blocks.push(el("div", { class: "tiles" }, [
      statTile("Requests", data.requests || 0),
      statTile("Models", primary.length),
      statTile("Errors",
        models.reduce((s, m) => s + (m.errors || 0), 0)),
      statTile("Shed (429)",
        models.reduce((s, m) => s + (m.shed || 0), 0)),
    ]));
    const rows = models.map((m) => ({
      model: m.model, role: m.role, requests: m.requests,
      "p50 ms": m.p50Ms, "p99 ms": m.p99Ms, "p99.9 ms": m.p999Ms,
      "goodput": m.goodputRatio, "fill": m.meanFill ?? "",
      errors: m.errors, shed: m.shed,
      slo: m.slo
        ? `${m.slo.compliant ? "✓" : "✗"} p99<${m.slo.targetP99Ms}ms`
        : "",
      // int8 kernel tier's ledgered accuracy delta (parity gate) —
      // shown beside the SLO badge, blank for float-serving models
      "quant Δ": m.quantDelta != null ? String(m.quantDelta) : "",
    }));
    blocks.push(table(rows, ["model", "role", "requests", "p50 ms",
                             "p99 ms", "p99.9 ms", "goodput", "fill",
                             "errors", "shed", "slo", "quant Δ"]));
    // where the non-goodput time goes, per primary model (the serving
    // badput categories — one bar row per category with seconds)
    primary.forEach((m) => {
      const bad = Object.entries(m.badputSeconds || {})
        .map(([category, seconds]) => ({ category, seconds }))
        .filter((r) => r.seconds > 0);
      if (!bad.length) return;
      blocks.push(el("h3", { text: `${m.model}: badput seconds` }));
      blocks.push(chartWithTable(bad,
        { labelKey: "category", valueKey: "seconds", unit: "s" },
        ["category", "seconds"]));
    });
    root.replaceChildren(...blocks);
  }

  function viewNotebooks(root) {
    // iframe-embedding, the reference dashboard's integration pattern
    const frame = el("iframe", {
      id: "jupyter-frame", src: JUPYTER_PATH,
      style: "width:100%;height:70vh;border:1px solid #ccc",
    });
    root.replaceChildren(el("h2", { text: "Notebooks" }), frame);
  }

  const VIEWS = {
    overview: viewOverview,
    runs: viewRuns,
    serving: viewServing,
    activities: viewActivities,
    metrics: viewMetrics,
    notebooks: viewNotebooks,
    pipelines: viewPipelines,
    studies: viewStudies,
    experiments: viewExperiments,
    contributors: viewContributors,
  };

  // -- env-info footer (user identity + platform, api.ts /env-info) ---------

  async function renderEnvInfo() {
    try {
      const info = await api("api/env-info");
      const footer = document.getElementById("env-info");
      if (!footer) return;
      footer.replaceChildren(
        el("div", { text: info.user.email }),
        el("div", {
          text: `${info.platform.providerName} · v` +
            info.platform.kubeflowVersion,
        }));
    } catch (e) { /* footer is decorative; views surface real errors */ }
  }

  function activeView() {
    const name = (location.hash.replace(/^#\//, "") || "overview").split("/")[0];
    return VIEWS[name] ? name : "overview";
  }

  // Views render into a DETACHED container that is swapped in only on
  // success AND only if no newer render started meanwhile (generation
  // token): a slow in-flight poll can never clobber a view the reader
  // navigated away to, and background refreshes never blank the page
  // (no Loading… flash, no scroll-to-top every poll).
  let renderGen = 0;

  async function renderInto(showLoading) {
    hideTooltip();
    const gen = ++renderGen;
    const name = activeView();
    document.querySelectorAll("#sidebar a").forEach((a) => {
      a.classList.toggle("active", a.dataset.view === name);
    });
    const root = document.getElementById("view");
    if (showLoading) {
      root.replaceChildren(el("p", { class: "empty", text: "Loading…" }));
    }
    const container = document.createElement("div");
    try {
      await VIEWS[name](container);
    } catch (err) {
      if (err.message === "unauthenticated") return;
      if (!showLoading) return;   // keep last good content on poll errors
      container.replaceChildren(
        el("p", { class: "error", text: err.message }));
    }
    if (gen !== renderGen) return;   // a newer render superseded this one
    root.replaceChildren(...container.childNodes);
  }

  const render = () => renderInto(true);

  // live panels: runs/activities/overview re-render on a poll (the
  // reference dashboard's behavior) — skipped while a tab is hidden or
  // the reader is mid-interaction with a chart tooltip
  const REFRESH_MS = 15000;
  const LIVE_VIEWS = new Set(["overview", "runs", "activities",
                              "pipelines"]);

  function startAutoRefresh() {
    setInterval(() => {
      if (document.hidden) return;
      if (tooltip && tooltip.style.display === "block") return;
      if (LIVE_VIEWS.has(activeView())) renderInto(false);
    }, REFRESH_MS);
  }

  async function main() {
    await renderNamespaceSelector();
    renderEnvInfo();
    window.addEventListener("hashchange", render);
    await render();
    startAutoRefresh();
  }

  document.readyState === "loading"
    ? document.addEventListener("DOMContentLoaded", main)
    : main();
})();
