/* Kubeflow TPU central dashboard SPA.
 *
 * The Polymer main-page.js / namespace-selector.js analog rendered
 * client-side from the dashboard JSON API (webapps/dashboard.py), with:
 *  - namespace selector (persisted in localStorage) driving activities
 *  - activities, cluster metrics, TPU slice inventory panels
 *  - hash routing (#/overview, #/activities, #/notebooks)
 *  - the notebooks view iframes the jupyter web app, the reference's
 *    iframe-embedding pattern (main-page.js)
 *  - every API 401 redirects to the gatekeeper login page
 */
(function () {
  "use strict";

  const LOGIN_PATH = "/login";
  const JUPYTER_PATH = "/jupyter/";
  const NS_KEY = "kftpu.namespace";

  function esc(v) {
    return String(v).replace(/[&<>"']/g, (ch) => ({
      "&": "&amp;", "<": "&lt;", ">": "&gt;",
      '"': "&quot;", "'": "&#39;",
    }[ch]));
  }

  async function api(path) {
    const resp = await fetch(path, { credentials: "same-origin" });
    if (resp.status === 401) {
      // unauthenticated: bounce through the gatekeeper login page
      window.location.assign(LOGIN_PATH);
      throw new Error("unauthenticated");
    }
    if (!resp.ok) throw new Error(`${path}: HTTP ${resp.status}`);
    return resp.json();
  }

  function table(rows, cols) {
    const head = "<tr>" + cols.map((c) => `<th>${esc(c)}</th>`).join("") +
      "</tr>";
    const body = rows.map((r) =>
      "<tr>" + cols.map((c) => `<td>${esc(r[c] ?? "")}</td>`).join("") +
      "</tr>").join("");
    return `<table>${head}${body}</table>`;
  }

  // -- namespace selector ----------------------------------------------------

  async function renderNamespaceSelector() {
    const namespaces = await api("api/namespaces");
    const current = localStorage.getItem(NS_KEY) || namespaces[0] || "default";
    const sel = document.getElementById("ns-selector");
    sel.innerHTML = namespaces.map((n) =>
      `<option value="${esc(n)}"${n === current ? " selected" : ""}>` +
      `${esc(n)}</option>`).join("");
    sel.onchange = () => {
      localStorage.setItem(NS_KEY, sel.value);
      render();  // re-render the active view in the new namespace
    };
    return current;
  }

  function selectedNamespace() {
    const sel = document.getElementById("ns-selector");
    return (sel && sel.value) || localStorage.getItem(NS_KEY) || "default";
  }

  // -- views -----------------------------------------------------------------

  async function viewOverview(el) {
    const [slices, nodes] = await Promise.all([
      api("api/tpu/slices"), api("api/metrics/node"),
    ]);
    el.innerHTML =
      "<h2>TPU slices</h2>" +
      (slices.length
        ? table(slices, ["topology", "accelerator", "hosts", "chips", "ready"])
        : "<p class=empty>No TPU slices in this cluster.</p>") +
      "<h2>Nodes</h2>" + table(nodes, ["node", "value"]);
  }

  async function viewActivities(el) {
    const ns = selectedNamespace();
    const acts = await api(`api/activities/${encodeURIComponent(ns)}`);
    el.innerHTML = `<h2>Activities in ${esc(ns)}</h2>` +
      (acts.length
        ? table(acts, ["type", "reason", "involvedObject", "message",
                       "lastTimestamp"])
        : "<p class=empty>No recent events.</p>");
  }

  async function viewMetrics(el) {
    const kind = (location.hash.split("/")[2]) || "podcpu";
    const rows = await api(`api/metrics/${encodeURIComponent(kind)}`);
    const tabs = ["podcpu", "podmem", "node"].map((k) =>
      `<a href="#/metrics/${k}"${k === kind ? ' class="active"' : ""}>` +
      `${k}</a>`).join(" ");
    const cols = kind === "node" ? ["node", "value"]
      : ["namespace", "pod", "value"];
    el.innerHTML = `<h2>Cluster metrics</h2><nav class=tabs>${tabs}</nav>` +
      table(rows, cols);
  }

  async function viewRuns(el) {
    const ns = selectedNamespace();
    const runs = await api(`api/runs/${encodeURIComponent(ns)}`);
    el.innerHTML = `<h2>Runs in ${esc(ns)}</h2>` +
      (runs.length
        ? table(runs, ["kind", "name", "phase", "progress", "finishedAt"])
        : "<p class=empty>No training jobs or workflow runs.</p>");
  }

  function viewNotebooks(el) {
    // iframe-embedding, the reference dashboard's integration pattern
    el.innerHTML = "<h2>Notebooks</h2>" +
      `<iframe id="jupyter-frame" src="${JUPYTER_PATH}" ` +
      'style="width:100%;height:70vh;border:1px solid #ccc"></iframe>';
  }

  const VIEWS = {
    overview: viewOverview,
    runs: viewRuns,
    activities: viewActivities,
    metrics: viewMetrics,
    notebooks: viewNotebooks,
  };

  function activeView() {
    const name = (location.hash.replace(/^#\//, "") || "overview").split("/")[0];
    return VIEWS[name] ? name : "overview";
  }

  async function render() {
    const name = activeView();
    document.querySelectorAll("#sidebar a").forEach((a) => {
      a.classList.toggle("active", a.dataset.view === name);
    });
    const el = document.getElementById("view");
    el.innerHTML = "<p class=empty>Loading…</p>";
    try {
      await VIEWS[name](el);
    } catch (err) {
      if (err.message !== "unauthenticated") {
        el.innerHTML = `<p class=error>${esc(err.message)}</p>`;
      }
    }
  }

  async function main() {
    await renderNamespaceSelector();
    window.addEventListener("hashchange", render);
    await render();
  }

  document.readyState === "loading"
    ? document.addEventListener("DOMContentLoaded", main)
    : main();
})();
