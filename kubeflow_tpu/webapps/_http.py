"""Tiny JSON-over-HTTP routing base for the web apps (stdlib only).

The reference's web backends are Express (centraldashboard) and Flask
(jupyter-web-app); this is the shared scaffolding for our equivalents: path
patterns with ``{param}`` captures, JSON bodies in/out, threaded server.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import parse_qs, urlparse

Route = tuple[str, re.Pattern, Callable]


class JsonApp:
    """Register handlers with ``app.route("GET", "/api/x/{name}")``;
    handlers receive (params, query, body) and return (status, payload).

    ``prefix`` mounts the whole app under a URL base (the reference
    jupyter-web-app's url-prefix config): an ingress routing /jupyter/
    forwards paths verbatim, so the app strips its own prefix before
    matching. Both the bare and the prefixed path resolve."""

    def __init__(self, prefix: str = ""):
        self.prefix = "/" + prefix.strip("/") if prefix.strip("/") else ""
        self.routes: list[Route] = []
        self._request_ctx = threading.local()

    @property
    def request_headers(self) -> dict:
        """Lower-cased headers of the request currently being dispatched
        (thread-local — the server is threaded). Handlers that need
        identity headers the ingress injects (IAP_EMAIL_HEADER) read
        them here; empty when dispatch is called outside a request
        (unit tests driving the app object directly)."""
        return getattr(self._request_ctx, "headers", {})

    def route(self, method: str, pattern: str):
        regex = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$")

        def deco(fn):
            self.routes.append((method, regex, fn))
            return fn

        return deco

    def dispatch(self, method: str, path: str,
                 body: Optional[dict]) -> tuple[int, Any]:
        parsed = urlparse(path)
        query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        route_path = parsed.path
        if self.prefix and (route_path == self.prefix or
                            route_path.startswith(self.prefix + "/")):
            route_path = route_path[len(self.prefix):] or "/"
        for m, regex, fn in self.routes:
            if m != method:
                continue
            match = regex.match(route_path)
            if match:
                try:
                    return fn(match.groupdict(), query, body)
                except ApiError as e:
                    return e.status, {"error": str(e)}
                except Exception as e:  # noqa: BLE001 - 500 boundary
                    return 500, {"error": f"{type(e).__name__}: {e}"}
        return 404, {"error": f"no route for {method} {parsed.path}"}


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class RawResponse:
    """Non-JSON payload (e.g. Prometheus text exposition)."""

    def __init__(self, body: str,
                 content_type: str = "text/plain; version=0.0.4"):
        self.body = body
        self.content_type = content_type


class ThreadedServer:
    """Shared HTTP server lifecycle: construct with a handler class, start
    a daemon serve thread, stop with shutdown+close. Every HTTP-serving
    component builds on this so lifecycle fixes land in one place."""

    def __init__(self, handler_cls, host: str = "127.0.0.1", port: int = 0,
                 name: str = "webapp"):
        self.name = name
        self._httpd = ThreadingHTTPServer((host, port), handler_cls)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name=self.name)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class JsonServer(ThreadedServer):
    def __init__(self, app: JsonApp, host: str = "127.0.0.1", port: int = 0,
                 name: str = "webapp"):
        self.app = app
        super().__init__(_make_handler(app), host=host, port=port, name=name)


def _make_handler(app: JsonApp):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _handle(self, method: str):
            body = None
            length = int(self.headers.get("Content-Length", 0))
            if length:
                try:
                    body = json.loads(self.rfile.read(length))
                except json.JSONDecodeError:
                    self._respond(400, {"error": "invalid JSON body"})
                    return
            app._request_ctx.headers = {k.lower(): v for k, v
                                        in self.headers.items()}
            try:
                status, payload = app.dispatch(method, self.path, body)
            finally:
                app._request_ctx.headers = {}
            self._respond(status, payload)

        def _respond(self, status: int, payload: Any):
            if isinstance(payload, RawResponse):
                data = payload.body.encode()
                ctype = payload.content_type
            else:
                data = json.dumps(payload).encode()
                ctype = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            self._handle("GET")

        def do_POST(self):
            self._handle("POST")

        def do_DELETE(self):
            self._handle("DELETE")

        def do_PATCH(self):
            self._handle("PATCH")

    return Handler
