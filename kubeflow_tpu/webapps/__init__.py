"""User-facing web services (the reference's L4 layer, SURVEY.md §1).

- ``gatekeeper``: basic-auth session server (components/gatekeeper).
- ``dashboard``: central dashboard API (components/centraldashboard).
- ``jupyter``: notebook CRUD web API (components/jupyter-web-app).
"""
