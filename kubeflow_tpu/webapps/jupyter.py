"""Jupyter web app backend: Notebook CR + PVC CRUD.

The reference's jupyter-web-app (components/jupyter-web-app/
kubeflow_jupyter/common/api.py:30-191: list/create/delete Notebook CRs and
PVCs; main.py default/rok skins; spawner UI config). Same surface over the
KubeClient; the spawner config gains TPU shapes (a notebook can request a
single-host slice topology the way the reference's spawner offered GPUs).

Routes:
  GET    /                                  (spawner SPA shell)
  GET    /app.js                            (static/jupyter.js)
  GET    /api/config
  GET    /api/namespaces/{ns}/notebooks
  POST   /api/namespaces/{ns}/notebooks
  DELETE /api/namespaces/{ns}/notebooks/{name}
  GET    /api/namespaces/{ns}/pvcs
  POST   /api/namespaces/{ns}/pvcs
  GET    /healthz
"""

from __future__ import annotations

import os

from ..api import k8s
from ..cluster.client import AlreadyExistsError, KubeClient, NotFoundError
from ..controllers.notebook import (NOTEBOOK_API_VERSION, NOTEBOOK_KIND,
                                    TPU_RESOURCE)
from ._http import ApiError, JsonApp, JsonServer, RawResponse

DEFAULT_IMAGES = [
    "ghcr.io/kubeflow-tpu/notebook-jax:latest",
    "ghcr.io/kubeflow-tpu/notebook-jax-tpu:latest",
]
# single-host slice shapes a notebook may request interactively
TPU_SHAPES = ["", "1x1 (1 chip)", "2x2 (4 chips)", "2x4 (8 chips)"]
_TPU_CHIPS = {"1x1 (1 chip)": 1, "2x2 (4 chips)": 4, "2x4 (8 chips)": 8}


def notebook_summary(nb: dict) -> dict:
    spec = (nb.get("spec", {}).get("template", {}) or {}).get("spec", {})
    containers = spec.get("containers", []) or []
    image = containers[0].get("image", "") if containers else ""
    res = (containers[0].get("resources", {}) or {}) if containers else {}
    limits = res.get("limits") or {}
    return {
        "name": k8s.name_of(nb),
        "namespace": k8s.namespace_of(nb, "default"),
        "image": image,
        "cpu": (res.get("requests") or {}).get("cpu", ""),
        "memory": (res.get("requests") or {}).get("memory", ""),
        "tpu": limits.get(TPU_RESOURCE, 0),
        "status": "Running" if k8s.condition_true(nb, "Ready") else "Waiting",
    }


def workspace_pvc_name(notebook_name: str, ws: dict) -> str:
    """Single source of the default workspace claim name: the manifest's
    volume reference and the PVC creation path must agree."""
    return ws.get("name") or f"workspace-{notebook_name}"


def build_notebook_manifest(namespace: str, body: dict) -> dict:
    """POST body → Notebook CR (api.py:30-81 shape, TPU-aware).

    ``snapshotUri`` (the rok-skin analog: the reference's rok UI spawns
    notebooks from a Rok snapshot URL) records the workspace seed source
    as an annotation the storage layer resolves; gs:// is the TPU-era
    transport where the reference used rok://."""
    name = body.get("name")
    if not name:
        raise ApiError(400, "name is required")
    try:
        k8s.validate_name(name)
    except ValueError as e:
        raise ApiError(400, str(e))
    image = body.get("image") or DEFAULT_IMAGES[0]
    resources: dict = {"requests": {}, "limits": {}}
    if body.get("cpu"):
        resources["requests"]["cpu"] = body["cpu"]
    if body.get("memory"):
        resources["requests"]["memory"] = body["memory"]
    tpu_shape = body.get("tpu") or ""
    if tpu_shape:
        chips = _TPU_CHIPS.get(tpu_shape)
        if chips is None:
            raise ApiError(400, f"unknown TPU shape {tpu_shape!r}; "
                                f"choose from {TPU_SHAPES[1:]}")
        resources["limits"][TPU_RESOURCE] = chips
    container = {"name": name, "image": image}
    if resources["requests"] or resources["limits"]:
        container["resources"] = {k: v for k, v in resources.items() if v}
    pod_spec: dict = {"containers": [container]}
    volume_mounts = []
    volumes = []
    ws = body.get("workspaceVolume")
    if ws:
        volumes.append({"name": "workspace", "persistentVolumeClaim":
                        {"claimName": workspace_pvc_name(name, ws)}})
        volume_mounts.append({"name": "workspace",
                              "mountPath": ws.get("path", "/home/jovyan")})
    for i, dv in enumerate(body.get("dataVolumes") or []):
        volumes.append({"name": f"data-{i}", "persistentVolumeClaim":
                        {"claimName": dv["name"]}})
        volume_mounts.append({"name": f"data-{i}",
                              "mountPath": dv.get("path", f"/data/{i}")})
    if volume_mounts:
        container["volumeMounts"] = volume_mounts
        pod_spec["volumes"] = volumes
    manifest = {
        "apiVersion": NOTEBOOK_API_VERSION, "kind": NOTEBOOK_KIND,
        "metadata": {"name": name, "namespace": namespace,
                     "labels": {"app": name}},
        "spec": {"template": {"spec": pod_spec}},
    }
    snapshot = body.get("snapshotUri")
    if snapshot:
        if not snapshot.startswith(("gs://", "file://")):
            raise ApiError(400, f"snapshotUri must be gs:// or file://, "
                                f"got {snapshot!r}")
        manifest["metadata"]["annotations"] = {
            "kubeflow-tpu.org/workspace-snapshot": snapshot}
    return manifest


def build_pvc_manifest(namespace: str, body: dict) -> dict:
    name = body.get("name")
    if not name:
        raise ApiError(400, "name is required")
    try:
        k8s.validate_name(name)
    except ValueError as e:
        raise ApiError(400, str(e))
    return {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "accessModes": [body.get("mode", "ReadWriteOnce")],
            "resources": {"requests": {
                "storage": body.get("size", "10Gi")}},
            **({"storageClassName": body["class"]}
               if body.get("class") else {}),
        },
    }


# The spawner SPA shell (the reference jupyter-web-app's spawner UI,
# kubeflow_jupyter/default/static — new-notebook form + notebook/volume
# tables; rendering lives in static/jupyter.js, no build infra).
INDEX_HTML = """<!doctype html>
<html><head><title>Notebooks — Kubeflow TPU</title><meta charset="utf-8">
<style>
body{font-family:sans-serif;margin:1.5rem auto;max-width:62rem;
 color:#202124}
h1{font-size:1.4rem}h2{font-size:1.1rem;margin-top:1.8rem}
fieldset{border:1px solid #dadce0;border-radius:6px;margin:0 0 1rem;
 padding:0.8rem 1rem}
legend{font-weight:600;padding:0 0.4rem}
.grid{display:grid;grid-template-columns:11rem 1fr;gap:0.55rem;
 align-items:center}
input,select{padding:0.4rem;border:1px solid #dadce0;border-radius:4px}
button{padding:0.45rem 1rem;border:0;border-radius:4px;
 background:#1a73e8;color:#fff;cursor:pointer}
button.minor{background:#e8eaed;color:#202124}
button:disabled{opacity:0.5}
table{border-collapse:collapse;width:100%;margin:0.5rem 0}
td,th{border:1px solid #dadce0;padding:0.35rem 0.7rem;text-align:left}
.status-Running{color:#188038;font-weight:600}
.status-Waiting{color:#e8710a}
#message{min-height:1.4rem}.error{color:#b00020}.ok{color:#188038}
.empty{color:#777}
.volrow{display:flex;gap:0.5rem;margin:0.3rem 0}
</style></head><body>
<h1>Notebook Servers</h1>
<div class="grid" style="max-width:28rem">
  <label for="ns">namespace</label><input id="ns" value="kubeflow">
</div>
<div id="message"></div>
<form id="spawn-form">
<fieldset><legend>New notebook server</legend>
  <div class="grid">
    <label>name</label><input name="name" required
      pattern="[a-z0-9][a-z0-9-]*">
    <label>image</label><select name="image"></select>
    <label>custom image</label><input name="customImage"
      placeholder="(overrides the image list)">
    <label>CPU</label><input name="cpu" value="1">
    <label>memory</label><input name="memory" value="2Gi">
    <label>TPU shape</label><select name="tpu"></select>
    <label>workspace volume</label><select name="wsMode">
      <option value="create">create new</option>
      <option value="existing">use existing PVC</option>
      <option value="none">none</option></select>
    <label>workspace size</label><input name="wsSize" value="10Gi">
    <label data-skin="snapshot" hidden>snapshot URI</label>
    <input name="snapshotUri" data-skin="snapshot" hidden
      placeholder="gs://bucket/workspace-snapshot">
  </div>
  <div id="data-volumes"></div>
  <p>
    <button type="button" class="minor" id="add-volume">+ data volume
    </button>
    <button type="submit">Spawn</button>
  </p>
</fieldset>
</form>
<h2>Notebooks</h2><div id="notebooks"></div>
<h2>Workspace volumes</h2><div id="pvcs"></div>
<script src="app.js"></script>
</body></html>"""

_STATIC_DIR = os.path.join(os.path.dirname(__file__), "static")


def build_jupyter_app(client: KubeClient, prefix: str = "") -> JsonApp:
    app = JsonApp(prefix=prefix)

    @app.route("GET", "/healthz")
    def healthz(params, query, body):
        return 200, {"ok": True}

    @app.route("GET", "/")
    def index(params, query, body):
        return 200, RawResponse(INDEX_HTML,
                                content_type="text/html; charset=utf-8")

    @app.route("GET", "/app.js")
    def app_js(params, query, body):
        with open(os.path.join(_STATIC_DIR, "jupyter.js")) as f:
            return 200, RawResponse(
                f.read(),
                content_type="application/javascript; charset=utf-8")

    @app.route("GET", "/api/config")
    def config(params, query, body):
        # skin selects the spawner variant (the reference's default/rok
        # UIs): "snapshot" surfaces the workspace-seed URI field
        return 200, {
            "images": DEFAULT_IMAGES,
            "tpuShapes": TPU_SHAPES,
            "defaultWorkspaceSize": "10Gi",
            "skin": os.environ.get("KFTPU_JUPYTER_SKIN", "default"),
        }

    @app.route("GET", "/api/namespaces/{ns}/notebooks")
    def list_notebooks(params, query, body):
        nbs = client.list(NOTEBOOK_API_VERSION, NOTEBOOK_KIND, params["ns"])
        return 200, {"notebooks": [notebook_summary(nb) for nb in nbs]}

    @app.route("POST", "/api/namespaces/{ns}/notebooks")
    def create_notebook(params, query, body):
        if not body:
            raise ApiError(400, "JSON body required")
        ns = params["ns"]
        manifest = build_notebook_manifest(ns, body)
        try:
            created = client.create(manifest)
        except AlreadyExistsError:
            raise ApiError(409, f"notebook {body['name']} already exists")
        # PVC only after the notebook create succeeds: a 409 must not leak
        # an orphaned workspace volume
        ws = body.get("workspaceVolume")
        if ws and ws.get("create", True):
            pvc = build_pvc_manifest(ns, {
                "name": workspace_pvc_name(body["name"], ws),
                "size": ws.get("size", "10Gi")})
            try:
                client.create(pvc)
            except AlreadyExistsError:
                pass  # reuse the existing workspace (rok-skin behavior)
        return 200, {"notebook": notebook_summary(created)}

    @app.route("DELETE", "/api/namespaces/{ns}/notebooks/{name}")
    def delete_notebook(params, query, body):
        try:
            client.delete(NOTEBOOK_API_VERSION, NOTEBOOK_KIND,
                          params["ns"], params["name"])
        except NotFoundError:
            raise ApiError(404, f"notebook {params['name']} not found")
        return 200, {"deleted": params["name"]}

    @app.route("GET", "/api/namespaces/{ns}/pvcs")
    def list_pvcs(params, query, body):
        pvcs = client.list("v1", "PersistentVolumeClaim", params["ns"])
        return 200, {"pvcs": [{
            "name": k8s.name_of(p),
            "size": ((p.get("spec", {}).get("resources") or {})
                     .get("requests") or {}).get("storage", ""),
            "mode": (p.get("spec", {}).get("accessModes") or [""])[0],
        } for p in pvcs]}

    @app.route("POST", "/api/namespaces/{ns}/pvcs")
    def create_pvc(params, query, body):
        if not body:
            raise ApiError(400, "JSON body required")
        try:
            created = client.create(build_pvc_manifest(params["ns"], body))
        except AlreadyExistsError:
            raise ApiError(409, f"pvc {body.get('name')} already exists")
        return 200, {"pvc": k8s.name_of(created)}

    return app


class JupyterWebApp(JsonServer):
    def __init__(self, client: KubeClient, prefix: str = "", **kw):
        super().__init__(build_jupyter_app(client, prefix=prefix),
                         name="jupyter-web-app", **kw)
