"""Access-management API: Profile + Binding grants (the KFAM service).

The reference ships this as a design-stage swagger
(components/access-management/README.md:1-18, api/swagger.yaml): Profile =
owner + namespace (implemented by the profile controller), Binding = a
user↔namespace grant. This is the serving implementation of that
contract: a REST API that mints Profiles and translates Bindings into
RoleBindings against the kubeflow-{admin,edit,view} ClusterRoles —
the grant surface the profile controller's owner binding doesn't cover.

Routes (the kfam surface):
  GET    /kfam/v1/profiles                 | POST | DELETE /{name}
  GET    /kfam/v1/bindings?namespace=&user=&role=
  POST   /kfam/v1/bindings   {"user": {...}, "referredNamespace": ns,
                              "roleRef": {"kind": "ClusterRole",
                                          "name": "kubeflow-edit"}}
  DELETE /kfam/v1/bindings   (same body)
  GET    /healthz
"""

from __future__ import annotations

import logging
import re
from typing import Optional

from ..api import k8s
from ..cluster.client import AlreadyExistsError, KubeClient, NotFoundError
from ..controllers.profile import PROFILE_API_VERSION, PROFILE_KIND
from ._http import ApiError, JsonApp, JsonServer

log = logging.getLogger(__name__)

ROLES = ("kubeflow-admin", "kubeflow-edit", "kubeflow-view")
BINDING_LABEL = "app.kubernetes.io/managed-by"
BINDING_MANAGER = "kfam"


def _binding_name(user: str, role: str) -> str:
    """DNS-safe, collision-proof: distinct principals must never share a
    RoleBinding name (apply/delete would cross-grant), so the sanitized
    slug carries a short digest of the exact user string."""
    import hashlib
    safe = re.sub(r"[^a-z0-9-]", "-", user.lower()).strip("-")[:32]
    digest = hashlib.sha256(user.encode()).hexdigest()[:8]
    return f"user-{safe}-{digest}-clusterrole-{role}"


def _validate_binding(body: Optional[dict]) -> tuple[dict, str, str]:
    if not body:
        raise ApiError(400, "binding body required")
    user = body.get("user") or {}
    if not user.get("name"):
        raise ApiError(400, "user.name is required")
    ns = body.get("referredNamespace", "")
    if not ns:
        raise ApiError(400, "referredNamespace is required")
    role = (body.get("roleRef") or {}).get("name", "kubeflow-view")
    if role not in ROLES:
        raise ApiError(400, f"roleRef.name {role!r} not in {ROLES}")
    return user, ns, role


def build_kfam_app(client: KubeClient) -> JsonApp:
    app = JsonApp()

    @app.route("GET", "/healthz")
    def healthz(params, query, body):
        return 200, {"ok": True}

    # -- profiles -----------------------------------------------------------

    @app.route("GET", "/kfam/v1/profiles")
    def list_profiles(params, query, body):
        profiles = client.list(PROFILE_API_VERSION, PROFILE_KIND)
        return 200, {"profiles": [{
            "name": k8s.name_of(p),
            "owner": (p.get("spec", {}).get("owner") or {}),
            "ready": k8s.condition_true(p, "Ready"),
        } for p in profiles]}

    @app.route("POST", "/kfam/v1/profiles")
    def create_profile(params, query, body):
        if not body or not body.get("name"):
            raise ApiError(400, "name is required")
        owner = body.get("owner") or {}
        profile = {
            "apiVersion": PROFILE_API_VERSION, "kind": PROFILE_KIND,
            "metadata": {"name": body["name"], "namespace": "default"},
            "spec": {"owner": {"kind": owner.get("kind", "User"),
                               "name": owner.get("name", "")}},
        }
        try:
            client.create(profile)
        except AlreadyExistsError as e:
            raise ApiError(409, f"profile {body['name']}: {e}")
        # validation/transport errors bubble to the 500 boundary — a 409
        # here would tell callers the profile exists when it does not
        return 200, {"name": body["name"]}

    @app.route("DELETE", "/kfam/v1/profiles/{name}")
    def delete_profile(params, query, body):
        try:
            client.delete(PROFILE_API_VERSION, PROFILE_KIND, "default",
                          params["name"])
        except NotFoundError:
            raise ApiError(404, f"profile {params['name']} not found")
        return 200, {"deleted": params["name"]}

    # -- bindings -----------------------------------------------------------

    @app.route("GET", "/kfam/v1/bindings")
    def list_bindings(params, query, body):
        out = []
        selector = {BINDING_LABEL: BINDING_MANAGER}
        bindings = client.list("rbac.authorization.k8s.io/v1", "RoleBinding",
                               query.get("namespace") or None,
                               selector=selector)
        for rb in bindings:
            subject = (rb.get("subjects") or [{}])[0]
            entry = {
                "user": {"kind": subject.get("kind", "User"),
                         "name": subject.get("name", "")},
                "referredNamespace": k8s.namespace_of(rb, "default"),
                "roleRef": rb.get("roleRef", {}),
            }
            if query.get("user") and entry["user"]["name"] != query["user"]:
                continue
            if query.get("role") and \
                    entry["roleRef"].get("name") != query["role"]:
                continue
            out.append(entry)
        return 200, {"bindings": out}

    @app.route("POST", "/kfam/v1/bindings")
    def create_binding(params, query, body):
        user, ns, role = _validate_binding(body)
        rb = {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "RoleBinding",
            "metadata": {
                "name": _binding_name(user["name"], role),
                "namespace": ns,
                "labels": {BINDING_LABEL: BINDING_MANAGER,
                           "user": re.sub(r"[^a-zA-Z0-9-_.]", "-",
                                          user["name"]),
                           "role": role},
            },
            "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                        "kind": "ClusterRole", "name": role},
            "subjects": [{"kind": user.get("kind", "User"),
                          "name": user["name"],
                          "apiGroup": "rbac.authorization.k8s.io"}],
        }
        client.apply(rb)
        return 200, {"binding": rb["metadata"]["name"]}

    @app.route("DELETE", "/kfam/v1/bindings")
    def delete_binding(params, query, body):
        user, ns, role = _validate_binding(body)
        try:
            client.delete("rbac.authorization.k8s.io/v1", "RoleBinding",
                          ns, _binding_name(user["name"], role))
        except NotFoundError:
            raise ApiError(404, "binding not found")
        return 200, {"deleted": _binding_name(user["name"], role)}

    return app


class AccessManagementServer(JsonServer):
    def __init__(self, client: KubeClient, **kw):
        super().__init__(build_kfam_app(client), name="kfam", **kw)
