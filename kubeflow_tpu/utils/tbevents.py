"""TensorBoard event-file writer — dependency-free.

The platform deploys TensorBoard (the reference's kubeflow/tensorboard
package → manifests/serving.py tensorboard component) but the trainer only
streamed JSONL, which TensorBoard cannot read. This writes the event wire
format directly so the worker needs neither tensorflow nor torch on its
hot path (both cost seconds of import and huge deps for what is ~100
lines of framing):

- records: TFRecord framing — u64-LE length, masked crc32c(length),
  payload, masked crc32c(payload);
- payload: an ``Event`` protobuf — wall_time(1, double), step(2, int64),
  file_version(3, string) or summary(5) of ``Summary.Value``
  (tag(1, string), simple_value(2, float)) — hand-encoded (proto wire
  format is stable and tiny for this subset).

Verified round-trip against the real TensorBoard reader in
tests/test_support.py.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

__all__ = ["EventWriter"]

# -- crc32c (Castagnoli, reflected poly 0x82F63B78) --------------------------

_POLY = 0x82F63B78
_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
    _TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- minimal proto wire encoding ---------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _int64(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _scalar_event(wall_time: float, step: int,
                  scalars: dict[str, float]) -> bytes:
    summary = b"".join(
        _len_delim(1, _len_delim(1, tag.encode()) + _float(2, float(v)))
        for tag, v in scalars.items())
    return _double(1, wall_time) + _int64(2, step) + _len_delim(5, summary)


def _version_event(wall_time: float) -> bytes:
    return _double(1, wall_time) + _len_delim(3, b"brain.Event:2")


# -- the writer ---------------------------------------------------------------

class EventWriter:
    """Append scalar events to an ``events.out.tfevents.*`` file that
    TensorBoard tails. One writer per run directory."""

    def __init__(self, logdir: str, clock=time.time):
        os.makedirs(logdir, exist_ok=True)
        self._clock = clock
        host = socket.gethostname() or "local"
        self.path = os.path.join(
            logdir, f"events.out.tfevents.{int(clock())}.{host}")
        self._fh = open(self.path, "ab")
        self._write(_version_event(clock()))

    def _write(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._fh.write(header)
        self._fh.write(struct.pack("<I", _masked_crc(header)))
        self._fh.write(payload)
        self._fh.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag: str, value: float, step: int,
                   wall_time: Optional[float] = None) -> None:
        self.add_scalars({tag: value}, step, wall_time)

    def add_scalars(self, scalars: dict[str, float], step: int,
                    wall_time: Optional[float] = None) -> None:
        """One Event carrying every scalar (one point per tag per step)."""
        if not scalars:
            return
        self._write(_scalar_event(
            self._clock() if wall_time is None else wall_time,
            int(step), scalars))
        self._fh.flush()

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
