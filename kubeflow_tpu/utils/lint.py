"""Minimal AST lint: the in-repo analog of the reference's flake8 CI tier
(testing/test_flake8.py) — no third-party linter is available in the
image, and the checks the suite actually relies on are small:

- files parse (syntax);
- imports are used (unused imports are how dead dependencies accrete);
- no duplicate import of the same binding;
- no bare ``except:`` (swallows KeyboardInterrupt/SystemExit).

``# noqa`` on the offending line suppresses, flake8-style. ``__init__.py``
files are exempt from unused-import checks (re-export surface).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _noqa_lines(source: str) -> set[int]:
    return {i for i, line in enumerate(source.splitlines(), 1)
            if "# noqa" in line}


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # a.b.c marks 'a' used; the chain itself resolves at runtime
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
    # names exported via __all__ strings count as used
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "__all__" and \
                        isinstance(node.value, (ast.List, ast.Tuple)):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) and \
                                isinstance(elt.value, str):
                            used.add(elt.value)
    return used


def check_file(path: str) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "E999", f"syntax error: {e.msg}")]

    noqa = _noqa_lines(source)
    out: list[Finding] = []
    is_init = os.path.basename(path) == "__init__.py"

    # -- imports -------------------------------------------------------------
    # (key, used_name, node) triples. key mirrors flake8's binding key:
    # 'import a.b' and 'import a.c' coexist (key = dotted path) while the
    # usage check tracks the bound root name. Scope-aware: duplicates are
    # only duplicates within the SAME scope — a per-function local import
    # repeated across tests is idiomatic, not shadowing.
    def imports_in(body, scope_out):
        for node in body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    key = alias.asname or alias.name
                    used_name = alias.asname or alias.name.split(".")[0]
                    scope_out.append((key, used_name, node))
            elif isinstance(node, ast.ImportFrom):
                if node.module != "__future__":
                    for alias in node.names:
                        if alias.name != "*":
                            name = alias.asname or alias.name
                            scope_out.append((name, name, node))
            # one level of nesting inside try/if (conditional imports)
            for attr in ("body", "orelse", "finalbody"):
                if isinstance(node, (ast.Try, ast.If)) and \
                        getattr(node, attr, None):
                    imports_in(getattr(node, attr), scope_out)
            for h in getattr(node, "handlers", []) or []:
                imports_in(h.body, scope_out)

    scopes: list[list] = []
    module_scope: list = []
    imports_in(tree.body, module_scope)
    scopes.append(module_scope)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_scope: list = []
            imports_in(node.body, fn_scope)
            scopes.append(fn_scope)

    used = _used_names(tree)
    for scope in scopes:
        seen: dict[str, ast.stmt] = {}
        for key, used_name, node in scope:
            if node.lineno in noqa:
                continue
            prev = seen.get(key)
            if prev is not None and prev.lineno != node.lineno:
                out.append(Finding(path, node.lineno, "F811",
                                   f"redefinition of imported {key!r} "
                                   f"(first at line {prev.lineno})"))
            seen[key] = node
            if not is_init and used_name not in used:
                out.append(Finding(path, node.lineno, "F401",
                                   f"{key!r} imported but unused"))

    # -- bare except ---------------------------------------------------------
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None \
                and node.lineno not in noqa:
            out.append(Finding(path, node.lineno, "E722",
                               "bare 'except:' (catches SystemExit/"
                               "KeyboardInterrupt)"))
    return out


def check_tree(root: str, subdirs: tuple[str, ...]) -> list[Finding]:
    findings: list[Finding] = []
    for sub in subdirs:
        for dirpath, dirnames, filenames in os.walk(os.path.join(root, sub)):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "build")]
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    findings.extend(check_file(os.path.join(dirpath, fname)))
    return findings
