"""Shared utilities: YAML IO, retry/backoff, structured logging."""
