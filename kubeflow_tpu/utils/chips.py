"""TPU chip spec sheet + FLOP models shared by the perf surfaces
(bench.py headline artifact, workflows/kubebench.py matrix reports).

One table so the MFU denominator can never disagree between artifacts.
"""

from __future__ import annotations

from typing import Optional

# First-light ResNet-50 measurement on one TPU v5e chip (bf16, batch
# 256, synthetic data, this repo @ milestone 3) — the vs_baseline
# denominator for bench.py AND the kubebench matrix.
BASELINE_IMG_S = 1000.0

# bf16 peak TFLOP/s by device_kind substring (public spec sheets)
PEAK_TFLOPS = {
    "v5 lite": 197.0, "v5e": 197.0,
    "v5p": 459.0, "v5": 459.0,          # 'v5' alone = v5p
    "v4": 275.0, "v3": 123.0, "v2": 46.0,
    "v6 lite": 918.0, "v6e": 918.0,
}

# ResNet-50 @224 fwd ≈ 4.09 GFLOP/image; fwd+bwd ≈ 3x fwd (dgrad + wgrad
# each cost ~one fwd). Conventional MFU flop model (matmul/conv MACs only).
RESNET50_TRAIN_GFLOP_PER_IMAGE = 3 * 4.09


def detect_peak_tflops(device) -> Optional[float]:
    """Spec-sheet bf16 peak for a jax device, by device_kind substring;
    None when the platform is unknown (CPU smoke runs)."""
    kind = getattr(device, "device_kind", "").lower()
    for key in sorted(PEAK_TFLOPS, key=len, reverse=True):
        if key in kind:
            return PEAK_TFLOPS[key]
    return None


def resnet50_train_mfu(images_per_sec_per_chip: float,
                       device) -> Optional[float]:
    peak = detect_peak_tflops(device)
    if not peak:
        return None
    flops = images_per_sec_per_chip * RESNET50_TRAIN_GFLOP_PER_IMAGE * 1e9
    return flops / (peak * 1e12)
