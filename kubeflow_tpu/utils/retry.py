"""Retry/backoff helpers.

Reference parity: per-component apply retry (ksonnet.go:148-197, constant
6x5s), DM-op polling with exponential backoff (gcp.go:267-308,
newDefaultBackoff :129), pytest @retry decorators (kfctl_go_test.py:14-16).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Optional, Type, TypeVar

log = logging.getLogger(__name__)
T = TypeVar("T")


@dataclass
class Backoff:
    """Exponential backoff with cap: the gcp.go newDefaultBackoff analog."""

    initial: float = 1.0
    factor: float = 2.0
    max_interval: float = 60.0
    max_elapsed: float = 600.0

    def intervals(self):
        elapsed, cur = 0.0, self.initial
        while elapsed < self.max_elapsed:
            yield cur
            elapsed += cur
            cur = min(cur * self.factor, self.max_interval)


def retry(
    fn: Callable[[], T],
    *,
    attempts: int = 6,
    interval: float = 5.0,
    backoff: Optional[Backoff] = None,
    retriable: tuple[Type[BaseException], ...] = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
    desc: str = "",
) -> T:
    """Constant-interval (default: 6x5s, the applyComponent policy) or
    exponential-backoff retry."""
    waits = list(backoff.intervals()) if backoff else [interval] * (attempts - 1)
    last: BaseException | None = None
    for i in range(len(waits) + 1):
        try:
            return fn()
        except retriable as e:  # noqa: PERF203
            last = e
            if i >= len(waits):
                break
            log.warning("retry %d/%d %s: %s", i + 1, len(waits) + 1, desc or fn, e)
            sleep(waits[i])
    assert last is not None
    raise last


def poll_until(
    predicate: Callable[[], bool],
    *,
    timeout: float = 300.0,
    interval: float = 2.0,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    desc: str = "",
) -> None:
    """Poll until predicate() is true (kf_is_ready_test.py:35-68 analog)."""
    deadline = clock() + timeout
    while True:
        if predicate():
            return
        if clock() >= deadline:
            raise TimeoutError(f"timed out after {timeout}s waiting for {desc}")
        sleep(interval)
