"""YAML IO with a JSON fallback so the core library has zero hard deps."""

from __future__ import annotations

import json
from typing import Any, Iterable

try:
    import yaml as _yaml
except ImportError:  # pragma: no cover - PyYAML is present in the dev image
    _yaml = None


def dumps(obj: Any) -> str:
    if _yaml is not None:
        return _yaml.safe_dump(obj, sort_keys=False, default_flow_style=False)
    return json.dumps(obj, indent=2)


def loads(text: str) -> Any:
    if _yaml is not None:
        return _yaml.safe_load(text)
    return json.loads(text)


def load_all(text: str) -> list[Any]:
    """Parse a multi-document YAML stream (`---`-separated manifests)."""
    if _yaml is not None:
        return [d for d in _yaml.safe_load_all(text) if d is not None]
    return [json.loads(t) for t in text.split("\n---\n") if t.strip()]


def dump_all(objs: Iterable[Any]) -> str:
    if _yaml is not None:
        return _yaml.safe_dump_all(list(objs), sort_keys=False, default_flow_style=False)
    return "\n---\n".join(json.dumps(o, indent=2) for o in objs)


def dump_file(obj: Any, path: str) -> None:
    with open(path, "w") as f:
        f.write(dumps(obj))


def load_file(path: str) -> Any:
    with open(path) as f:
        return loads(f.read())
