"""TPU serving data plane.

Reference parity (SURVEY.md §3.4): TF-Serving container (gRPC :9000 /
REST :8500) + Tornado HTTP proxy (components/k8s-model-server/http-proxy/
server.py) + tf-batch-predict job. Here the model server IS the TPU
process: a jit-compiled predict function behind a micro-batching queue,
with a TF-Serving-compatible REST surface.

- :mod:`servable` — model loading (checkpoint → jitted predict), registry.
- :mod:`batcher`  — micro-batching queue with bucketed padding (static
  shapes: one XLA compile per bucket, never per request), bounded
  ``max_pending`` load shedding.
- :mod:`http_server` — REST front: /v1/models/<name>[:predict|/metadata].
- :mod:`batch_predict` — offline batch prediction job.
- :mod:`request_trace` — per-request ids + stage spans + ledgers
  (ISSUE 11: one slow request reconstructs from JSONL alone).
- :mod:`replica_state` — per-model rolling health + SLO burn rates,
  published on /metrics and /healthz?verbose=1 for the router and
  autoscaler.
- :mod:`fleet` — the resilience tier (ISSUE 12): health-routed
  FleetRouter over N replicas with per-replica circuit breakers,
  deadline-budgeted failover retries, tail hedging, and drain
  awareness.
"""

from .servable import Servable, ModelRepository  # noqa: F401
from .batcher import MicroBatcher, QueueFullError  # noqa: F401
from .http_server import ModelServer  # noqa: F401
from .replica_state import ModelSLO, ReplicaState  # noqa: F401
from .request_trace import ServingObs  # noqa: F401
from .fleet import (BreakerConfig, CircuitBreaker, FleetConfig,  # noqa: F401
                    FleetRouter)
